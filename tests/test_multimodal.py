"""Multimodal E/P/D flow: media codec, vision encoder, encode worker over
the runtime, and image-embedding splice through the real engine
(VERDICT #9 second half; ref: multimodal_handlers/ + preprocessor/media)."""

import asyncio

import jax
import jax.numpy as jnp

import numpy as np
import pytest

from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
from dynamo_tpu.llm import ModelDeploymentCard, tiny_tokenizer
from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.models.config import tiny_config
from dynamo_tpu.multimodal import (
    EncodeWorkerHandler,
    MultimodalPreprocessor,
    VisionEncoderConfig,
    encode_images,
    init_vision_params,
)
from dynamo_tpu.multimodal.media import (
    MediaError,
    encode_image_data_uri,
    fetch_media,
)
from dynamo_tpu.runtime import Context, DistributedRuntime, build_pipeline, collect

CFG = tiny_config()
VCFG = VisionEncoderConfig(
    image_size=64, patch_size=16, d_model=32, n_layers=1, n_heads=2,
    d_ff=64, out_dim=CFG.d_model,
)


def make_image(seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 255, size=(64, 64, 3), dtype=np.uint8)


class TestMedia:
    def test_data_uri_roundtrip(self):
        img = make_image(0)
        uri = encode_image_data_uri(img)
        out = fetch_media(uri, image_size=64)
        np.testing.assert_array_equal(out, img)  # PNG is lossless

    def test_local_file(self, tmp_path):
        from PIL import Image

        p = tmp_path / "x.png"
        Image.fromarray(make_image(1)).save(str(p))
        out = fetch_media(str(p), image_size=32)
        assert out.shape == (32, 32, 3)

    def test_errors(self):
        with pytest.raises(MediaError):
            fetch_media("data:image/png;base64,!!!notb64!!!")
        with pytest.raises(MediaError):
            fetch_media("https://example.com/cat.png")
        with pytest.raises(MediaError):
            fetch_media("/no/such/file.png")


class TestEncoder:
    def test_shapes_and_determinism(self):
        import jax

        params = init_vision_params(VCFG, jax.random.PRNGKey(0))
        imgs = np.stack([make_image(0), make_image(1)])
        e1 = encode_images(params, imgs, VCFG)
        e2 = encode_images(params, imgs, VCFG)
        assert e1.shape == (2, VCFG.n_patches, CFG.d_model)
        np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
        # different images produce different embeddings
        assert float(np.abs(np.asarray(e1[0] - e1[1])).max()) > 1e-3


async def test_encode_worker_over_runtime():
    drt = DistributedRuntime.detached()
    ep = drt.namespace("mm").component("encoder").endpoint("encode")
    handler = EncodeWorkerHandler(VCFG)
    await ep.serve_endpoint(handler.generate)
    client = await ep.client()
    uri = encode_image_data_uri(make_image(3))
    out = await collect(client.generate({"media": [uri]}, Context()))
    assert out[-1].get("error") is None
    assert out[-1]["n_tokens"] == VCFG.n_patches
    from dynamo_tpu.disagg.handlers import unpack_array

    embeds = unpack_array(out[-1]["embeddings"])
    assert embeds.shape == (1, VCFG.n_patches, CFG.d_model)
    # bad media comes back in-band
    bad = await collect(client.generate({"media": ["https://x/y.png"]}, Context()))
    assert "egress" in bad[-1]["error"]


async def _mm_pipeline():
    """Full staged flow: encode worker + preprocessor + engine."""
    drt = DistributedRuntime.detached()
    ep = drt.namespace("mm2").component("encoder").endpoint("encode")
    handler = EncodeWorkerHandler(VCFG)
    await ep.serve_endpoint(handler.generate)

    async def factory():
        return await ep.client()

    engine = JaxEngine(
        JaxEngineArgs(
            config=CFG, block_size=4, num_kv_blocks=256, max_num_seqs=4,
            max_model_len=256, prefill_chunk=16,  # chunk < n_patches: splice spans chunks
        )
    )
    tok = tiny_tokenizer()
    card = ModelDeploymentCard(name="mm-model", context_length=256)
    pipeline = build_pipeline(
        [
            OpenAIPreprocessor(card, tok),
            Backend(tok),
            MultimodalPreprocessor(factory),
        ],
        engine,
    )
    return pipeline, engine, handler


def chat_with_image(uri, text="describe this"):
    return {
        "model": "mm-model",
        "messages": [
            {
                "role": "user",
                "content": [
                    {"type": "image_url", "image_url": {"url": uri}},
                    {"type": "text", "text": text},
                ],
            }
        ],
        "max_tokens": 6,
        "temperature": 0.0,
        "ignore_eos": True,
    }


async def test_model_watcher_wires_encode_stage():
    """A model registered with model_type='multimodal' gets the encode
    splice in its WATCHER-built pipeline — the deployed E/P/D path, not
    just the hand-assembled one (recipes/multimodal-epd)."""
    from dynamo_tpu.http import ModelManager
    from dynamo_tpu.llm.discovery import ModelWatcher, register_llm

    drt = DistributedRuntime.detached()
    enc_ep = drt.namespace("mmw").component("encoder").endpoint("encode")
    handler = EncodeWorkerHandler(VCFG)
    await enc_ep.serve_endpoint(handler.generate)

    engine = JaxEngine(
        JaxEngineArgs(
            config=CFG, block_size=4, num_kv_blocks=256, max_num_seqs=4,
            max_model_len=256, prefill_chunk=16,
        )
    )
    gen_ep = drt.namespace("mmw").component("backend").endpoint("generate")
    await gen_ep.serve_endpoint(engine.generate)
    card = ModelDeploymentCard(
        name="mm-watched", model_type="multimodal", context_length=256
    )
    await register_llm(drt, card, gen_ep, instance_id=1)

    manager = ModelManager()
    watcher = ModelWatcher(
        drt, manager, enable_disagg=False, enable_busy_monitor=False,
    )
    await watcher.start()
    try:
        await watcher.wait_for_model("mm-watched")
        entry = manager.get("mm-watched")
        uri = encode_image_data_uri(make_image(7))
        body = chat_with_image(uri)
        body["model"] = "mm-watched"
        outs = await collect(entry.engine.generate(body, Context()))
        deltas = [o for o in outs if not isinstance(o, dict)]
        assert not any(o.error for o in deltas), [o.error for o in deltas]
        assert handler.encoded_images == 1  # the encode stage really ran
        assert sum(len(o.token_ids) for o in deltas) == 6
    finally:
        await watcher.stop()
        await engine.stop()


async def test_image_steers_generation_e2e():
    pipeline, engine, handler = await _mm_pipeline()
    uri_a = encode_image_data_uri(make_image(10))
    uri_b = encode_image_data_uri(make_image(20))
    try:
        async def run(body):
            outs = await collect(pipeline.generate(body, Context()))
            deltas = [o for o in outs if not isinstance(o, dict)]
            assert not any(o.error for o in deltas), [o.error for o in deltas]
            return [t for o in deltas for t in o.token_ids]

        out_a = await run(chat_with_image(uri_a))
        out_b = await run(chat_with_image(uri_b))
        out_text = await run(
            {
                "model": "mm-model",
                "messages": [{"role": "user", "content": "describe this"}],
                "max_tokens": 6,
                "temperature": 0.0,
                "ignore_eos": True,
            }
        )
        assert handler.encoded_images == 2
        assert out_a != out_text  # the image changed the generation
        assert out_a != out_b  # different images, different generations
        # same image again: deterministic AND the prefix cache (salted by
        # image content) must serve the same result
        out_a2 = await run(chat_with_image(uri_a))
        assert out_a2 == out_a
    finally:
        await engine.stop()


class TestClipParity:
    """Real vision checkpoint through the encoder (VERDICT r2 missing #5):
    a locally-created HF CLIPVisionModel maps through load_clip_vision and
    must match transformers CPU bit-for-tolerance."""

    def _clip_dir(self, tmp_path):
        import torch
        import transformers

        cfg = transformers.CLIPVisionConfig(
            hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, image_size=32, patch_size=8,
        )
        model = transformers.CLIPVisionModel(cfg).eval().to(torch.float32)
        d = tmp_path / "clip"
        model.save_pretrained(str(d), safe_serialization=True)
        return str(d), model

    def test_tower_matches_transformers(self, tmp_path):
        pytest.importorskip("transformers")
        import torch

        from dynamo_tpu.multimodal.encoder import encode_images, load_clip_vision

        model_dir, hf_model = self._clip_dir(tmp_path)
        params, cfg = load_clip_vision(model_dir, out_dim=16)
        rng = np.random.default_rng(0)
        # Pre-normalized pixel values (the HF model's input space):
        # [N, 3, H, W] for torch, [N, H, W, 3] float for ours.
        pix = rng.standard_normal((2, 3, 32, 32)).astype(np.float32)
        with torch.no_grad():
            want = hf_model(torch.from_numpy(pix)).last_hidden_state.numpy()
        got = np.asarray(
            encode_images(
                params, jnp.asarray(pix.transpose(0, 2, 3, 1)), cfg, True
            )
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_image_dependent_epd_output(self, tmp_path):
        """E/P/D e2e whose output depends on real image content: the same
        image twice → identical greedy output; a different image → a
        different embedding stream (and with real weights, different
        logits through the splice)."""
        pytest.importorskip("transformers")
        from dynamo_tpu.multimodal.encoder import encode_images, load_clip_vision

        model_dir, _ = self._clip_dir(tmp_path)
        params, cfg = load_clip_vision(model_dir, out_dim=16)
        rng = np.random.default_rng(1)
        img_a = rng.integers(0, 255, size=(1, 32, 32, 3), dtype=np.uint8)
        img_b = rng.integers(0, 255, size=(1, 32, 32, 3), dtype=np.uint8)
        ea1 = np.asarray(encode_images(params, jnp.asarray(img_a), cfg))
        ea2 = np.asarray(encode_images(params, jnp.asarray(img_a), cfg))
        eb = np.asarray(encode_images(params, jnp.asarray(img_b), cfg))
        np.testing.assert_array_equal(ea1, ea2)
        assert np.abs(ea1 - eb).max() > 1e-3, "embeddings ignore image content"

        # Through the LLM splice: different images → different logits.
        from dynamo_tpu.models import llama
        from dynamo_tpu.models.config import tiny_config

        lcfg = tiny_config(d_model=16)
        lparams = llama.init_params(lcfg, jax.random.PRNGKey(0))
        k_c, v_c = llama.init_kv_cache(lcfg, 16, 4, layered=True)
        toks = jnp.zeros((1, cfg.n_patches + 2), jnp.int32)
        mm_slot = jnp.asarray(
            [[-1] + list(range(cfg.n_patches)) + [-1]], jnp.int32
        )
        tables = jnp.arange(8, dtype=jnp.int32)[None, :]
        start = jnp.zeros((1,), jnp.int32)
        lens = jnp.full((1,), cfg.n_patches + 2, jnp.int32)

        def logits_for(embeds):
            out, _, _ = llama.forward_paged(
                lparams, lcfg, toks, start, lens, tables,
                *llama.init_kv_cache(lcfg, 16, 4, layered=True),
                mm_embeds=jnp.asarray(embeds[0]), mm_slot=mm_slot,
            )
            return np.asarray(out)

        la, lb = logits_for(ea1), logits_for(eb)
        assert np.abs(la - lb).max() > 1e-4, "logits ignore image content"
