"""Deploy control plane: spec parsing, controller reconcile, crash respawn,
planner-driven scaling, rolling restart (VERDICT row 38; ref:
deploy/operator reconciler)."""

import asyncio
import sys

import pytest

from dynamo_tpu.deploy import GraphController, GraphDeployment, ServiceSpec
from dynamo_tpu.runtime.discovery import MemoryDiscovery
from dynamo_tpu.planner.connectors import VirtualConnector
from dynamo_tpu.planner.planner_core import ReplicaPlan

SLEEPER = [sys.executable, "-c", "import time; time.sleep(60)"]


def sleeper_spec(replicas=1, **kw):
    return ServiceSpec(command=SLEEPER, replicas=replicas,
                       grace_period_s=5.0, **kw)


class TestSpec:
    def test_yaml_roundtrip(self, tmp_path):
        p = tmp_path / "g.yaml"
        p.write_text(
            """
name: t
namespace: ns1
envs: {A: "1"}
services:
  w:
    kind: worker
    replicas: 2
    args: ["--model", "tiny"]
    planner_scaled: true
  f:
    kind: frontend
"""
        )
        dep = GraphDeployment.from_file(str(p))
        assert dep.services["w"].replicas == 2
        assert dep.services["w"].planner_scaled
        cmd = dep.services["w"].resolved_command()
        assert cmd[1:] == ["-m", "dynamo_tpu.worker", "--model", "tiny"]

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kind 'warp-drive'"):
            GraphDeployment.from_dict(
                {"name": "x", "services": {"a": {"kind": "warp-drive"}}}
            )

    def test_example_manifest_parses(self):
        import os

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "deploy", "k8s", "example-disagg.yaml",
        )
        dep = GraphDeployment.from_file(path)
        assert dep.services["decode"].planner_scaled
        assert dep.services["prefill"].planner_role == "prefill"


class TestController:
    async def test_reconcile_and_crash_respawn(self):
        dep = GraphDeployment(
            name="t", services={
                "a": sleeper_spec(replicas=2),
                "b": ServiceSpec(command=[sys.executable, "-c", "pass"],
                                 replicas=1, grace_period_s=5.0),
            },
        )
        ctl = GraphController(dep)
        try:
            await ctl.reconcile_once()
            st = ctl.status()
            assert st["services"]["a"]["ready"] == 2
            # 'b' exits immediately; the next reconcile respawns it
            for _ in range(50):
                if ctl._connector.counts()["b"] == 0:
                    break
                await asyncio.sleep(0.1)
            await ctl.reconcile_once()
            assert len(ctl._connector._procs["b"]) == 1
            # kill one 'a' replica → reconcile brings it back
            victim = ctl._connector.alive("a")[0].proc
            victim.kill()
            victim.wait(timeout=5)
            await ctl.reconcile_once()
            assert ctl.status()["services"]["a"]["ready"] == 2
        finally:
            await ctl.stop()

    async def test_planner_scaled_counts(self):
        disc = MemoryDiscovery.shared(bus="deploy-test")
        conn = VirtualConnector(disc, "nsX")
        await conn.apply(ReplicaPlan(prefill=0, decode=3, reason="load"))
        dep = GraphDeployment(
            name="t", namespace="nsX",
            services={"workers": sleeper_spec(replicas=1, planner_scaled=True)},
        )
        ctl = GraphController(dep, discovery=disc)
        try:
            counts = await ctl.reconcile_once()
            assert counts["workers"] == 3  # planner overrode the spec
            assert ctl.status()["services"]["workers"]["ready"] == 3
            await conn.apply(ReplicaPlan(prefill=0, decode=1, reason="idle"))
            counts = await ctl.reconcile_once()
            assert counts["workers"] == 1
        finally:
            await ctl.stop()

    async def test_rolling_restart_on_id_change(self):
        dep = GraphDeployment(name="t", services={"a": sleeper_spec(replicas=1)})
        ctl = GraphController(dep)
        try:
            await ctl.reconcile_once()
            pid1 = ctl._connector.alive("a")[0].proc.pid
            dep.restart_id = "v2"
            await ctl.reconcile_once()
            procs = ctl._connector.alive("a")
            assert len(procs) == 1 and procs[0].proc.pid != pid1
        finally:
            await ctl.stop()
