"""Serving-plane observability e2e (ISSUE 1 acceptance): one system server
scraping a real serving run exposes at least one non-zero sample from each
of the four new subsystem families — router, KVBM, disagg, engine-step —
with every name sourced from runtime/metric_names.py."""

import asyncio

import aiohttp

from dynamo_tpu.disagg import DecodeHandler, KvTransferHandler, PrefillHandler
from dynamo_tpu.kvbm import HostTier, TieredKvManager
from dynamo_tpu.planner.metrics_source import parse_prometheus_text
from dynamo_tpu.router.router import KvRouter
from dynamo_tpu.runtime import Context, DistributedRuntime
from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.system_server import SystemStatusServer, attach_engine

from tests.test_jax_engine import make_engine, req


class _DirectKvClient:
    """Request-plane stand-in: routes pulls straight at a KvTransferHandler
    (the wire protocol is identical; no runtime needed for a metrics test)."""

    def __init__(self, handler):
        self._h = handler

    async def direct(self, payload, worker_id):
        async for reply in self._h.generate(payload, Context()):
            yield reply


def _nonzero(sample, name):
    """True when the family member has any sample > 0 (histograms expose
    name_bucket/_sum/_count series)."""
    for (n, _labels), v in sample.items():
        if (n == name or n.startswith(name + "_")) and v > 0:
            return True
    return False


async def test_metrics_expose_all_four_subsystem_families():
    prefill_engine, _ = make_engine()
    decode_engine, _ = make_engine()
    kvbm = TieredKvManager(HostTier(64))
    kvbm.attach(prefill_engine)

    prefill_handler = PrefillHandler(prefill_engine, worker_id=1)
    kv_handler = KvTransferHandler(prefill_engine)

    async def kv_client():
        return _DirectKvClient(kv_handler)

    decode_handler = DecodeHandler(decode_engine, kv_client_factory=kv_client)

    router = KvRouter(DistributedRuntime.detached(), "t", "c", block_size=4)
    router.scheduler.add_worker((1, 0))

    server = SystemStatusServer(host="127.0.0.1", port=0)
    attach_engine(server, decode_engine)
    kvbm.register_metrics(server)
    router.register_metrics(server)
    decode_handler.register_metrics(server)
    await server.start()
    try:
        prompt = list(range(100, 116))  # 4 full blocks at block_size=4

        # router family: a routing decision over the live scheduler state
        worker, _overlap = router.find_best_match(prompt)
        assert worker == (1, 0)

        # disagg + engine-step: prefill worker computes KV, decode worker
        # pulls it over the (stand-in) wire, then decodes
        pre_out = [
            o async for o in prefill_handler.generate(
                req(prompt, max_tokens=4), Context()
            )
        ]
        dp = pre_out[-1].disaggregated_params
        assert dp is not None and dp.kv_transfer["block_hashes"]
        decode_req = req(prompt, max_tokens=4)
        decode_req.disaggregated_params = dp
        out = [
            o async for o in decode_handler.generate(decode_req, Context())
        ]
        assert any(o.token_ids for o in out)
        assert decode_handler.blocks_pulled > 0

        # kvbm family: the prefill engine's committed blocks offload
        await asyncio.sleep(0.3)
        assert kvbm.offloaded > 0

        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{server.port}/metrics") as r:
                assert r.status == 200
                text = await r.text()
        sample = parse_prometheus_text(text)

        assert _nonzero(sample, mn.ROUTER_DECISIONS_TOTAL)
        assert _nonzero(sample, mn.ROUTER_WORKER_LOAD_BLOCKS) or (
            # a zero-load worker still exports its gauge series
            (mn.ROUTER_WORKER_LOAD_BLOCKS, (("worker", "1:0"),)) in sample
        )
        assert _nonzero(sample, mn.KVBM_OFFLOAD_BLOCKS_TOTAL)
        assert _nonzero(sample, mn.KVBM_OFFLOAD_BYTES_TOTAL)
        assert _nonzero(sample, mn.DISAGG_TRANSFERS_TOTAL)
        assert _nonzero(sample, mn.DISAGG_BLOCKS_PULLED_TOTAL)
        assert _nonzero(sample, mn.DISAGG_TRANSFER_DURATION)
        assert _nonzero(sample, mn.ENGINE_STEP_DURATION)
        assert _nonzero(sample, mn.ENGINE_BATCH_OCCUPANCY)
        assert _nonzero(sample, mn.ENGINE_STEP_PREFILL_TOKENS)
        assert _nonzero(sample, mn.ENGINE_STEP_DECODE_TOKENS)

        # every exposed dynamo_tpu_router/kvbm/disagg series name is
        # resolvable to a canonical constant (acceptance criterion)
        canonical = set(mn.ALL_ROUTER) | set(mn.ALL_KVBM) | set(mn.ALL_DISAGG)
        for (n, _labels) in sample:
            for prefix in (mn.ROUTER_PREFIX, mn.KVBM_PREFIX, mn.DISAGG_PREFIX):
                if n.startswith(prefix + "_"):
                    base = n
                    for suffix in ("_bucket", "_sum", "_count"):
                        if base.endswith(suffix):
                            base = base[: -len(suffix)]
                    assert base in canonical, f"non-canonical series {n}"
    finally:
        await server.stop()
        await kvbm.close()
        await prefill_engine.stop()
        await decode_engine.stop()


async def test_router_load_gauges_track_and_forget_workers():
    """Per-worker load gauges sample the scheduler at scrape time and drop
    series for departed workers (no frozen ghosts on dashboards)."""
    router = KvRouter(DistributedRuntime.detached(), "t", "c", block_size=4)
    router.scheduler.add_worker((1, 0))
    router.scheduler.add_worker((2, 0))
    text = router.metrics.render()
    assert 'worker="1:0"' in text and 'worker="2:0"' in text
    router.remove_worker((2, 0))
    text = router.metrics.render()
    assert 'worker="1:0"' in text and 'worker="2:0"' not in text


def test_frontend_exemplars_and_lifecycle_stamps():
    """TTFT/request-duration histograms carry the request's trace id as an
    OpenMetrics exemplar, and the timer stamps received/first_token/done
    onto the request's /debug timeline (tentpole part 3)."""
    from dynamo_tpu.http.metrics import FrontendMetrics, RequestTimer
    from dynamo_tpu.runtime.lifecycle import global_lifecycle
    from dynamo_tpu.utils.tracing import Tracer

    lc = global_lifecycle()
    lc.clear()
    metrics = FrontendMetrics()
    timer = RequestTimer(metrics, "m", "chat_completions")
    ctx = Context(baggage={})
    tracer = Tracer(max_spans=4)
    with tracer.span("http.chat_completions", ctx):
        timer.bind_context(ctx)
        timer.on_token()
        timer.on_token()
        timer.done(200)
    [span] = tracer.finished_spans()

    om = metrics.render(openmetrics=True).decode()
    assert f'trace_id="{span.trace_id}"' in om
    plain = metrics.render().decode()
    assert "trace_id" not in plain  # exemplars are openmetrics-only

    tl = lc.get(ctx.id)
    assert tl is not None and tl.trace_id == span.trace_id
    events = [e.name for e in tl.events]
    assert events == ["received", "first_token", "done"]
    assert tl.done
    lc.clear()


def test_counter_openmetrics_family_drops_total_suffix():
    """OpenMetrics keys counter metadata on the family name and requires the
    _total suffix on samples; classic text format keys metadata on the
    sample name. Strict scrapers reject a # TYPE line carrying _total."""
    from dynamo_tpu.runtime.metrics_core import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter(mn.DISAGG_TRANSFERS_TOTAL, "transfers", ["mode"])
    c.inc(mode="remote")

    family = mn.DISAGG_TRANSFERS_TOTAL[: -len("_total")]
    om = reg.render(openmetrics=True)
    assert f"# TYPE {family} counter" in om
    assert f"# HELP {family} transfers" in om
    assert f"# TYPE {mn.DISAGG_TRANSFERS_TOTAL} counter" not in om
    assert f'{mn.DISAGG_TRANSFERS_TOTAL}{{mode="remote"}} 1' in om

    plain = reg.render()
    assert f"# TYPE {mn.DISAGG_TRANSFERS_TOTAL} counter" in plain
    assert f'{mn.DISAGG_TRANSFERS_TOTAL}{{mode="remote"}} 1' in plain
