"""Planner dry-run simulator + SLA recommendation (ref: planner
utils/dryrun.py and the DGDR SLA-profiling flow)."""

import pytest

from dynamo_tpu.planner.dryrun import DryRunner, synth_trace
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.planner_core import PlannerConfig
from dynamo_tpu.profiler.sla import (
    ConfigProfile,
    SlaTargets,
    Workload,
    recommend,
)


def _prefill_points(scale=1.0):
    # ttft grows linearly with isl; throughput flat-ish.
    return [
        {"isl": 64.0, "ttft_s": 0.02 / scale, "tokens_per_s": 3200.0 * scale},
        {"isl": 512.0, "ttft_s": 0.16 / scale, "tokens_per_s": 3200.0 * scale},
        {"isl": 2048.0, "ttft_s": 0.64 / scale, "tokens_per_s": 3200.0 * scale},
    ]


def _decode_points(scale=1.0):
    return [
        {"concurrency": 1.0, "itl_s": 0.008 / scale, "tokens_per_s": 125.0 * scale},
        {"concurrency": 8.0, "itl_s": 0.012 / scale, "tokens_per_s": 666.0 * scale},
        {"concurrency": 32.0, "itl_s": 0.030 / scale, "tokens_per_s": 1066.0 * scale},
    ]


def _interps(scale=1.0):
    return (
        PrefillInterpolator.from_points(_prefill_points(scale)),
        DecodeInterpolator.from_points(_decode_points(scale)),
    )


class TestSynthTrace:
    @pytest.mark.parametrize("kind", ["ramp", "step", "sine", "spike"])
    def test_shapes(self, kind):
        tr = synth_trace(kind, duration_s=300, interval_s=30,
                         base_rate=1, peak_rate=9)
        assert len(tr) == 10
        rates = [p.request_rate for p in tr]
        assert min(rates) >= 1 and max(rates) <= 9 + 1e-9
        if kind == "ramp":
            assert rates == sorted(rates)
        if kind == "spike":
            assert sorted(rates)[-1] == 9 and sorted(rates)[-2] == 1

    def test_unknown_kind(self):
        with pytest.raises(ValueError):
            synth_trace("sawtooth")


class TestDryRunner:
    def test_ramp_scales_up(self):
        pre, dec = _interps()
        cfg = PlannerConfig(
            ttft_target_s=1.0, itl_target_s=0.02,
            max_replicas=16, total_chip_budget=32,
        )
        runner = DryRunner(cfg, pre, dec)
        report = runner.run(
            synth_trace("ramp", duration_s=600, interval_s=30,
                        base_rate=0.5, peak_rate=20, isl=512, osl=128)
        )
        assert report.final_plan is not None
        assert report.scale_events >= 2  # it actually reacted to the ramp
        first, last = report.timeline[0], report.timeline[-1]
        assert last.decode > first.decode
        assert report.peak_chips <= cfg.total_chip_budget
        assert "scale events" in report.summary()

    def test_flat_load_is_stable(self):
        pre, dec = _interps()
        cfg = PlannerConfig(ttft_target_s=1.0, itl_target_s=0.02,
                            max_replicas=16)
        runner = DryRunner(cfg, pre, dec)
        report = runner.run(
            synth_trace("step", duration_s=600, interval_s=30,
                        base_rate=2.0, peak_rate=2.0)
        )
        # Constant load → exactly one "scale" (the initial plan).
        assert report.scale_events == 1

    def test_ttft_violations_flagged(self):
        pre, dec = _interps()
        cfg = PlannerConfig(ttft_target_s=0.05, itl_target_s=0.02,
                            max_replicas=16)
        runner = DryRunner(cfg, pre, dec)
        report = runner.run(
            synth_trace("step", duration_s=120, interval_s=30,
                        base_rate=1, peak_rate=1, isl=2048)
        )
        assert report.ttft_violations > 0


class TestSlaRecommend:
    def test_picks_cheapest_feasible(self):
        profiles = [
            ConfigProfile("tp1", 1, _prefill_points(1.0), _decode_points(1.0)),
            ConfigProfile("tp4", 4, _prefill_points(4.0), _decode_points(4.0)),
        ]
        targets = SlaTargets(ttft_s=0.3, itl_s=0.02)
        report = recommend(profiles, targets, Workload(request_rate=2.0, isl=512))
        assert report.chosen is not None
        # tp1 meets the relaxed SLA with fewer chips.
        assert report.chosen.config_name == "tp1"
        assert report.chosen.total_chips <= 8
        assert "tok/s/chip" in report.summary()

    def test_tight_ttft_forces_bigger_config(self):
        profiles = [
            ConfigProfile("tp1", 1, _prefill_points(1.0), _decode_points(1.0)),
            ConfigProfile("tp4", 4, _prefill_points(4.0), _decode_points(4.0)),
        ]
        # tp1 TTFT at isl 512 is 160ms; demand 50ms → only tp4 (40ms) fits.
        targets = SlaTargets(ttft_s=0.05, itl_s=0.02)
        report = recommend(profiles, targets, Workload(request_rate=2.0, isl=512))
        assert report.chosen is not None
        assert report.chosen.config_name == "tp4"
        assert "tp1" in report.rejected
        assert "TTFT" in report.rejected["tp1"]

    def test_infeasible_everywhere(self):
        profiles = [
            ConfigProfile("tp1", 1, _prefill_points(1.0), _decode_points(1.0)),
        ]
        report = recommend(
            profiles, SlaTargets(ttft_s=0.001, itl_s=0.0001),
            Workload(request_rate=1.0),
        )
        assert report.chosen is None
        assert "no config meets" in report.summary()
