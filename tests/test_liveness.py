"""Crash plane (ISSUE 10): fast dead-worker detection, incarnation fencing,
and warm-restart rejoin.

The shared claim: an UNPLANNED worker death (kill -9, OOM, partition) is a
bounded, fenced serving event — detection is derived from missed load
reports (never TCP timeouts), one ``drop_worker`` call reconciles every
piece of router state, in-flight streams abort into the migration ladder
with a typed ``worker_lost`` reason, a zombie incarnation's late packets
are counted and dropped at every seam, and a restarted worker rejoins warm
(CRC-verified checkpoint restore before readiness, never a crash loop).
"""

import asyncio
import os
import time

import numpy as np
import pytest

from dynamo_tpu.router import (
    KvIndexer,
    KvRouterConfig,
    KvScheduler,
    LoadSnapshot,
    RouterEvent,
)
from dynamo_tpu.runtime import fault_names as fn
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import collect
from dynamo_tpu.runtime.liveness import (
    ALIVE,
    DEAD,
    SUSPECT,
    IncarnationFence,
    LivenessConfig,
    LivenessTracker,
    RESTORE_OUTCOME,
    STALE_DROPS,
    StaleIncarnationError,
    WorkerLostError,
    process_incarnation,
    set_process_incarnation,
)
from dynamo_tpu.runtime.tasks import Backoff
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.tokens.radix import OverlapScores


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


def drops(seam: str) -> float:
    return STALE_DROPS.value(seam=seam)


# ---------------------------------------------------------------------------
# Incarnation fence semantics
# ---------------------------------------------------------------------------


class TestIncarnationFence:
    def test_newest_wins_and_stale_is_counted(self):
        fence = IncarnationFence("load_report")
        before = drops("load_report")
        assert fence.admit(1, 100) == "applied"  # first sighting
        assert fence.admit(1, 100) == "applied"  # same incarnation
        assert fence.admit(1, 200) == "rejoined"  # restart
        assert fence.admit(1, 100) == "stale"  # zombie's late packet
        assert fence.admit(1, 200) == "applied"
        assert drops("load_report") == before + 1
        assert fence.newest(1) == 200

    def test_unstamped_peers_pass_free(self):
        """Mixed fleets: a pre-crash-plane peer (inc 0/None) is never
        fenced — fencing is opt-in by stamping."""
        fence = IncarnationFence("tcp")
        assert fence.admit(7, 0) == "applied"
        assert fence.admit(7, None) == "applied"
        assert fence.admit(7, 5) == "applied"  # first stamp, no prior
        assert fence.admit(7, 0) == "applied"  # unstamped still free

    def test_drop_forgets_key(self):
        fence = IncarnationFence("load_report")
        fence.admit(1, 100)
        fence.drop(1)
        # Re-registration re-establishes from its own stamp: an OLDER
        # stamp after a full departure is a fresh worldview, not a zombie.
        assert fence.admit(1, 50) == "applied"

    def test_process_incarnation_fits_the_wire(self):
        """The stamp must survive msgpack's int64 bound (tcp envelopes,
        pull replies) — a nanosecond stamp would not."""
        saved = process_incarnation()
        assert 0 < saved < 2 ** 63
        set_process_incarnation(None)
        try:
            fresh = process_incarnation()
            assert 0 < fresh < 2 ** 63
            assert fresh >= saved  # monotonically fresh across "restarts"
        finally:
            set_process_incarnation(saved)


# ---------------------------------------------------------------------------
# Detection state machine (fake clock — no TCP, no real time)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class TestDetection:
    def mk(self, **kw):
        clock = FakeClock()
        deaths, rejoins = [], []
        tracker = LivenessTracker(
            LivenessConfig(interval_s=1.0, suspect_after=2, dead_after=5),
            clock=clock,
            on_dead=lambda w, inc: deaths.append((w, inc)),
            on_rejoin=lambda w, inc: rejoins.append((w, inc)),
            **kw,
        )
        return tracker, clock, deaths, rejoins

    def test_suspect_then_dead_within_budget(self):
        tracker, clock, deaths, _ = self.mk()
        tracker.observe_report(1, 100)
        assert tracker.state_of(1) == ALIVE

        clock.advance(1.0)
        tracker.observe_report(1, 100)  # on-cadence report keeps it alive
        assert tracker.evaluate() == []
        assert tracker.state_of(1) == ALIVE

        clock.advance(2.5)  # 2.5 intervals missed
        assert tracker.evaluate() == []
        assert tracker.state_of(1) == SUSPECT
        assert not deaths

        clock.advance(2.5)  # 5 intervals total: the budget
        assert tracker.evaluate() == [1]
        assert tracker.state_of(1) == DEAD
        assert deaths == [(1, 100)]
        # The bound is CONFIGURATION, not TCP: detection latency recorded
        # for this death is exactly the elapsed fake time since the last
        # report — within one sweep of dead_after × interval_s.
        assert tracker.config.detection_budget_s == 5.0
        # A second sweep must not re-fire.
        assert tracker.evaluate() == []
        assert deaths == [(1, 100)]

    def test_report_after_death_is_a_rejoin_even_same_incarnation(self):
        """A worker that froze (GC pause, SIGSTOP) past the budget and
        resumed REPORTS again under the same incarnation. Its router
        state was purged at death, so re-admission must rebuild from a
        clean slate: the tracker treats it as a rejoin."""
        tracker, clock, deaths, rejoins = self.mk()
        tracker.observe_report(1, 100)
        clock.advance(6.0)
        assert tracker.evaluate() == [1]
        tracker.observe_report(1, 100)
        assert rejoins == [(1, 100)]
        assert tracker.state_of(1) == ALIVE

    def test_fresh_incarnation_purges_before_apply(self):
        """Restart detected by incarnation (before any death sweep):
        on_rejoin (the drop_worker hook) fires BEFORE the fresh report is
        applied, so old and new state never conflate."""
        tracker, clock, _, rejoins = self.mk()
        tracker.observe_report(1, 100)
        clock.advance(0.5)
        assert tracker.observe_report(1, 200) == "rejoined"
        assert rejoins == [(1, 200)]
        assert tracker.state_of(1) == ALIVE

    def test_zombie_report_does_not_keep_worker_alive(self):
        """The crash-plane failure mode fencing exists for: the restarted
        worker dies, and the OLD zombie's late reports keep arriving.
        They must not mask the death."""
        tracker, clock, deaths, _ = self.mk()
        before = drops("load_report")
        tracker.observe_report(1, 200)
        for _ in range(6):
            clock.advance(1.0)
            assert tracker.observe_report(1, 100) == "stale"  # zombie
        assert tracker.evaluate() == [1]
        assert deaths and drops("load_report") == before + 6

    def test_suspect_recovers_on_next_report(self):
        tracker, clock, deaths, rejoins = self.mk()
        tracker.observe_report(1, 100)
        clock.advance(3.0)
        tracker.evaluate()
        assert tracker.state_of(1) == SUSPECT
        tracker.observe_report(1, 100)
        assert tracker.state_of(1) == ALIVE
        assert not deaths and not rejoins

    def test_drop_forgets_worker_and_fence(self):
        tracker, clock, _, _ = self.mk()
        tracker.observe_report(1, 100)
        tracker.drop(1)
        assert tracker.state_of(1) is None
        assert tracker.observe_report(1, 50) == "applied"  # fresh fence

    def test_injected_report_loss_trips_detection(self):
        """The liveness.report chaos seam: N consecutive lost reports trip
        the same machinery a crashed worker does."""
        tracker, clock, deaths, _ = self.mk()
        tracker.observe_report(1, 100)
        plan = faults.FaultPlan(seed=3, rules=(
            faults.FaultRule(point=fn.LIVENESS_REPORT, every=1,
                             kind="error", times=100),
        ))
        with faults.armed(plan):
            for _ in range(6):
                clock.advance(1.0)
                with pytest.raises(faults.InjectedError):
                    tracker.observe_report(1, 100)
        assert tracker.evaluate() == [1]
        assert deaths == [(1, 100)]

    def test_metrics_and_flight_surface(self):
        tracker, clock, _, _ = self.mk()
        tracker.observe_report(1, 100)
        clock.advance(6.0)
        tracker.evaluate()
        text = tracker.metrics.render()
        assert "dynamo_tpu_liveness_worker_state" in text
        assert "dynamo_tpu_liveness_detection_seconds" in text
        kinds = [e["kind"] for e in tracker.flight.snapshot()]
        assert "discovered" in kinds and "dead" in kinds


# ---------------------------------------------------------------------------
# Jittered exponential backoff (satellite: reconnect herds)
# ---------------------------------------------------------------------------


class TestBackoff:
    def test_deterministic_under_seeded_rng(self):
        import random as _random

        a = Backoff(base_s=0.1, cap_s=2.0, rng=_random.Random(7))
        b = Backoff(base_s=0.1, cap_s=2.0, rng=_random.Random(7))
        seq_a = [a.next_delay() for _ in range(8)]
        seq_b = [b.next_delay() for _ in range(8)]
        assert seq_a == seq_b  # fake-clock replayable

    def test_doubles_caps_and_jitters(self):
        import random as _random

        bo = Backoff(base_s=0.1, cap_s=1.0, jitter=0.5,
                     rng=_random.Random(11))
        raw = [0.1 * 2 ** n for n in range(8)]
        for n, delay in enumerate(bo.next_delay() for _ in range(8)):
            base = min(raw[n], 1.0)
            assert 0.5 * base <= delay <= 1.5 * base

    def test_two_workers_desynchronize(self):
        """The point of the jitter: two processes failing at the same
        instant must NOT retry at the same instants."""
        import random as _random

        a = Backoff(base_s=0.5, cap_s=30.0, rng=_random.Random(1))
        b = Backoff(base_s=0.5, cap_s=30.0, rng=_random.Random(2))
        seq_a = [a.next_delay() for _ in range(6)]
        seq_b = [b.next_delay() for _ in range(6)]
        assert seq_a != seq_b

    def test_reset_restarts_cheap(self):
        bo = Backoff(base_s=0.1, cap_s=10.0, jitter=0.0)
        assert [bo.next_delay() for _ in range(3)] == [0.1, 0.2, 0.4]
        bo.reset()
        assert bo.next_delay() == 0.1


async def test_discd_watch_bootstrap_retries_until_server_appears():
    """A watch requested while discd is down (or mid-restart) must not die
    with a one-shot bootstrap failure: it retries with backoff and
    delivers the snapshot once the server is back."""
    import socket

    from dynamo_tpu.runtime.discovery import EventKind
    from dynamo_tpu.runtime.discovery.discd import DiscdDiscovery, DiscdServer

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    client = DiscdDiscovery(f"127.0.0.1:{port}")
    watch = client.watch("inst/")  # nothing is listening yet
    await asyncio.sleep(0.3)  # a few failed bootstrap attempts

    server = DiscdServer(host="127.0.0.1", port=port)
    await server.start()
    try:
        await client.put("inst/a", {"v": 1})
        event = await asyncio.wait_for(watch.__anext__(), timeout=10)
        assert event.kind == EventKind.PUT and event.key == "inst/a"
    finally:
        await watch.aclose()
        await client.close()
        await server.stop()


async def test_keepalive_outage_reregisters_under_fresh_lease():
    """A control-plane outage long enough to expire the serving lease
    must END with the worker re-registered (fresh lease, every leased doc
    re-put) — not permanently vanished until a human restarts it."""
    from dynamo_tpu.runtime.discovery import Lease, MemoryDiscovery
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    class OutageDiscovery(MemoryDiscovery):
        def __init__(self):
            super().__init__()
            self.down = False
            self.lease_seq = 0
            self.dead_leases = set()

        async def create_lease(self, ttl: float) -> Lease:
            if self.down:
                raise ConnectionError("control plane down")
            self.lease_seq += 1
            return Lease(id=f"l{self.lease_seq}", ttl=ttl)

        async def keep_alive(self, lease: Lease) -> None:
            if self.down:
                # A renewal missed while down expires the lease for good
                # — exactly etcd's behavior once the TTL lapses.
                self.dead_leases.add(lease.id)
                raise ConnectionError("control plane down")
            if lease.id in self.dead_leases:
                raise ConnectionError("lease expired")

    disco = OutageDiscovery()
    rt = DistributedRuntime(discovery=disco, bus="liveness-rereg")
    os.environ["DYN_TPU_LEASE_TTL"] = "0.2"
    served = None
    try:
        class Echo:
            async def generate(self, request, context):
                yield {"ok": True}

        ep = rt.namespace("lv").component("backend").endpoint("generate")
        served = await ep.serve_endpoint(Echo().generate, instance_id=9)
        key = served.instance.key
        assert await disco.get(key) is not None

        # Outage: keep-alives fail; past the TTL the lease is dead. The
        # memory backend doesn't sweep, so model the expiry explicitly.
        # Long enough for at least one keep-alive attempt to hit the
        # outage (the loop cadence is max(0.5, ttl/3) = 0.5s).
        disco.down = True
        await asyncio.sleep(1.2)
        await disco.delete(key)
        disco.down = False

        deadline = time.monotonic() + 10
        while await disco.get(key) is None:
            assert time.monotonic() < deadline, "never re-registered"
            await asyncio.sleep(0.05)
        assert disco.lease_seq >= 2  # a FRESH lease, not the dead one
    finally:
        os.environ.pop("DYN_TPU_LEASE_TTL", None)
        if served is not None:
            await served.shutdown(grace_period=1)
        await rt.shutdown(grace_period=1)


# ---------------------------------------------------------------------------
# drop_worker: the single purge path (leak audit)
# ---------------------------------------------------------------------------


def _loaded_scheduler():
    sched = KvScheduler(KvRouterConfig(), seed=0)
    sched.update_load(LoadSnapshot(
        worker_id=1, active_blocks=10, total_blocks=100, incarnation=100,
        link_bandwidth={7: 2e9}, link_faults=[8],
    ))
    sched.update_load(LoadSnapshot(
        worker_id=2, active_blocks=10, total_blocks=100, incarnation=300,
    ))
    return sched


class TestDropWorker:
    def test_stale_load_report_fenced_not_applied(self):
        sched = _loaded_scheduler()
        # The scheduler's fence counts under its OWN seam: the liveness
        # tracker consumes the same topic on a separate subscription, so
        # a shared label would double-count every zombie packet.
        before = drops("router_load")
        gen = sched.report_generation((1, 0))
        # Zombie incarnation: counted, dropped, state untouched.
        assert sched.update_load(LoadSnapshot(
            worker_id=1, active_blocks=99, total_blocks=100, incarnation=50,
        )) is False
        assert drops("router_load") == before + 1
        assert sched.report_generation((1, 0)) == gen
        assert sched._workers[(1, 0)].snapshot.active_blocks == 10
        # The live incarnation's identical-shaped report applies.
        assert sched.update_load(LoadSnapshot(
            worker_id=1, active_blocks=99, total_blocks=100, incarnation=100,
        )) is True
        assert sched._workers[(1, 0)].snapshot.active_blocks == 99

    def test_rejoin_purges_old_incarnation_first(self):
        sched = _loaded_scheduler()
        # Charge in-flight work to worker 1 (old incarnation).
        sched.select_worker(50, OverlapScores(scores={(1, 0): 40}),
                            [(1, 0), (2, 0)])
        assert sched._workers[(1, 0)].inflight_blocks > 0
        # The restarted worker's first report: old charges must be gone.
        assert sched.update_load(LoadSnapshot(
            worker_id=1, active_blocks=0, total_blocks=100, incarnation=200,
        )) is True
        state = sched._workers[(1, 0)]
        assert state.inflight_blocks == 0
        assert state.snapshot.incarnation == 200
        # And the zombie is now fenced.
        assert sched.update_load(LoadSnapshot(
            worker_id=1, incarnation=100,
        )) is False

    def test_drop_worker_leaves_zero_residue(self):
        """THE audit: one drop_worker call must release in-flight charges,
        link pairs (both directions), breaker faults, the fence entry, the
        radix index, and the metrics gauges — no piecemeal purging."""
        from dynamo_tpu.router.router import RouterMetrics

        sched = _loaded_scheduler()
        indexer = KvIndexer(block_size=4)
        sched.add_drop_callback(indexer.remove_worker)
        metrics = RouterMetrics(sched)

        hashes = compute_block_hashes(list(range(16)), 4)
        indexer.apply(RouterEvent(worker_id=1, kind="stored",
                                  block_hashes=hashes))
        sched.select_worker(50, OverlapScores(scores={(1, 0): 4}),
                            [(1, 0), (2, 0)])
        # Bidirectional link state: measured by 1, and measured about 1.
        sched.update_load(LoadSnapshot(
            worker_id=2, incarnation=300, link_bandwidth={1: 5e8},
        ))
        # Link state touches worker 1 in BOTH directions: as the pull dst
        # (its own report's link_bandwidth) and as the src another worker
        # measured (worker 2's report about src 1).
        assert any(src == 1 or dst == (1, 0)
                   for (src, dst) in sched.link_costs.pairs())
        assert any(dst == (1, 0) for (_s, dst) in sched.link_costs._faults)

        sched.drop_worker((1, 0))

        assert (1, 0) not in sched._workers
        assert not indexer.find_matches(hashes).scores
        for (src, dst) in sched.link_costs.pairs():
            assert src != 1 and dst != (1, 0)
        for (src, dst) in sched.link_costs._faults:
            assert src != 1 and dst != (1, 0)
        # The fence entry went too: a re-registration with ANY stamp is a
        # fresh worldview.
        assert sched.update_load(LoadSnapshot(
            worker_id=1, incarnation=42,
        )) is True
        sched.drop_worker((1, 0))
        # Metrics render after the drop: no worker-1 series resurrected.
        rendered = metrics.render()
        for line in rendered.splitlines():
            if line.startswith("dynamo_tpu_router_worker_"):
                assert "(1, 0)" not in line

    def test_remove_worker_is_drop_worker(self):
        """Back-compat callers (discovery DELETE) ride the same single
        purge path."""
        sched = _loaded_scheduler()
        sched.remove_worker((1, 0))
        assert (1, 0) not in sched._workers


# ---------------------------------------------------------------------------
# Stream aborts: dead worker → typed worker_lost into the migration ladder
# ---------------------------------------------------------------------------


async def test_abort_instance_fails_streams_immediately():
    """abort_instance must fail an in-flight stream NOW (typed), not
    after any transport timeout — and the reason label is worker_lost."""
    from dynamo_tpu.llm.migration import MIGRATABLE, _failure_reason
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    rt = DistributedRuntime.detached()
    served = None
    try:
        class Stuck:
            async def generate(self, request, context):
                yield {"token_ids": [1]}
                await asyncio.sleep(3600)  # the dead worker never answers

        ep = rt.namespace("lv").component("backend").endpoint("generate")
        served = await ep.serve_endpoint(Stuck().generate, instance_id=5)
        client = await ep.client()
        await client.wait_for_instances()
        client.enable_stream_aborts()

        got = []

        async def consume():
            async for item in client.generate({"token_ids": [1, 2]}, Context()):
                got.append(item)

        task = asyncio.ensure_future(consume())
        while not got:
            await asyncio.sleep(0.01)

        err = WorkerLostError("worker 0x5 declared dead")
        t0 = time.monotonic()
        assert client.abort_instance(5, err) == 1
        with pytest.raises(WorkerLostError):
            await task
        assert time.monotonic() - t0 < 2.0  # immediate, not a timeout
        assert isinstance(err, MIGRATABLE)
        assert _failure_reason(err) == "worker_lost"
        assert client.evict_instance(5) is True
        assert client.abort_instance(5, err) == 0  # nothing left
        # Same-incarnation rejoin (frozen worker resumed — it never
        # re-PUTs its discovery key, so the watch can't re-add it):
        # revive_instance is the road back, and it must round-trip.
        assert client.revive_instance(5) is True
        assert client.revive_instance(5) is False  # already routable
        assert 5 in (await client.wait_for_instances())
    finally:
        if served is not None:
            await served.shutdown(grace_period=1)
        await rt.shutdown(grace_period=1)


async def test_monitor_detects_silent_worker_and_fires_callbacks():
    """End-to-end detection through the real pump: a worker that stops
    publishing load reports is declared dead within the configured budget
    and the on_dead fan-out runs — nothing anywhere waits on TCP."""
    from dynamo_tpu.http.worker_monitor import WorkerLoadMonitor
    from dynamo_tpu.router.protocols import load_topic
    from dynamo_tpu.runtime.events import MemoryEventPlane

    plane = MemoryEventPlane()
    deaths = []
    tracker = LivenessTracker(
        LivenessConfig(interval_s=0.05, suspect_after=2, dead_after=4),
        on_dead=lambda w, inc: deaths.append(w),
    )
    monitor = WorkerLoadMonitor(plane, "lv", "backend", liveness=tracker)
    await monitor.start()
    topic = load_topic("lv", "backend")
    try:
        t_last = time.monotonic()
        for _ in range(3):
            await plane.publish(topic, LoadSnapshot(
                worker_id=1, incarnation=100).to_dict())
            t_last = time.monotonic()
            await asyncio.sleep(0.05)
        # ... kill -9: reports stop. Budget = 4 × 0.05s = 0.2s.
        deadline = time.monotonic() + 5.0
        while not deaths and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        detected = time.monotonic() - t_last
        assert deaths == [1]
        assert tracker.state_of(1) == DEAD
        # Bounded by budget + one evaluation sweep + scheduling slack —
        # and nowhere near any TCP timeout.
        assert detected < 3.0
        # The fresh incarnation rejoining flows back to ALIVE.
        await plane.publish(topic, LoadSnapshot(
            worker_id=1, incarnation=200).to_dict())
        deadline = time.monotonic() + 5.0
        while tracker.state_of(1) != ALIVE and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        assert tracker.state_of(1) == ALIVE
    finally:
        await monitor.stop()


# ---------------------------------------------------------------------------
# Readiness split (system server)
# ---------------------------------------------------------------------------


async def test_readyz_gates_on_sources_healthz_does_not():
    import aiohttp

    from dynamo_tpu.runtime.system_server import SystemStatusServer

    server = SystemStatusServer(host="127.0.0.1", port=0)
    state = {"ready": False}
    server.register_readiness("worker", lambda: (state["ready"], "restoring"))
    await server.start()
    try:
        async with aiohttp.ClientSession() as s:
            # Liveness answers while NOT ready (a restore in progress is
            # not a reason to restart the pod).
            async with s.get(f"http://127.0.0.1:{server.port}/healthz") as r:
                assert r.status == 200
            async with s.get(f"http://127.0.0.1:{server.port}/readyz") as r:
                assert r.status == 503
                body = await r.json()
                assert body["details"]["worker"] == "restoring"
            state["ready"] = True
            async with s.get(f"http://127.0.0.1:{server.port}/readyz") as r:
                assert r.status == 200
    finally:
        await server.stop()


def test_pod_spec_renders_probe_split():
    from dynamo_tpu.deploy.pod_connector import render_pod
    from dynamo_tpu.deploy.spec import GraphDeployment, ServiceSpec

    dep = GraphDeployment(name="g", services={
        "decode": ServiceSpec(kind="worker", system_port=9090),
    })
    body = render_pod(dep, "decode", dep.services["decode"], 0, 0)
    container = body["spec"]["containers"][0]
    assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert container["readinessProbe"]["httpGet"]["path"] == "/readyz"
    assert container["livenessProbe"]["httpGet"]["port"] == 9090


# ---------------------------------------------------------------------------
# Seam fences: pull replies, handoff acks, tcp frames
# ---------------------------------------------------------------------------


async def test_stale_pull_reply_dropped_and_counted():
    """A KV pull whose bootstrap promised incarnation A but whose replies
    carry incarnation B (the prefill worker restarted mid-handshake, or a
    zombie answered) must never scatter those blocks — the typed error is
    migratable and the payload is counted at the pull_reply seam."""
    from dynamo_tpu.disagg import DecodeHandler

    class FakeKvClient:
        def __init__(self, inc):
            self.inc = inc

        def direct(self, request, src, context=None):
            async def gen():
                # Shape does not matter past the fence: an empty found
                # set ends the live pull cleanly before any import.
                yield {"found": [], "kv": None, "k": None, "v": None,
                       "done": True, "inc": self.inc}
            return gen()

    class FakeEngine:
        pool = type("P", (), {"contains": staticmethod(lambda h: False)})()

    live_inc = 1000
    handler = DecodeHandler(
        FakeEngine(), kv_client_factory=None, worker_id=2,
        pull_attempts=1, backoff_base_s=0.0,
    )

    before = drops("pull_reply")
    # Zombie reply: expected 1000, got 999 → dropped + counted + typed.
    handler._kv_client = FakeKvClient(999)
    with pytest.raises(StaleIncarnationError):
        await handler._pull_once(
            [1, 2], None, 7, {"blocks": 0, "bytes": 0},
            expect_inc=live_inc,
        )
    assert drops("pull_reply") == before + 1

    # The live incarnation's identical-shaped reply is applied (no raise
    # at the fence; it proceeds into normal import handling).
    handler._kv_client = FakeKvClient(live_inc)
    await handler._pull_once(
        [1], None, 7, {"blocks": 0, "bytes": 0},
        expect_inc=live_inc,
    )
    assert drops("pull_reply") == before + 1  # unchanged


async def test_stale_handoff_ack_reads_as_refusal():
    """A handoff accept-ack from a PRIOR peer incarnation (zombie) must
    not release the source's copy of the stream: it reads as a refusal
    and the ladder continues (next peer / re-prefill)."""
    from dynamo_tpu.runtime.drain import DrainController

    class NullEngine:
        pool = type("P", (), {"usage": 0.0, "cached_blocks": 0})()

        def stats(self):
            return {}

    controller = DrainController(NullEngine(), worker_id=1)
    fence = controller._peer_fence
    before = drops("handoff_ack")
    # The peer's live incarnation acks once...
    assert fence.admit(5, 2000) != "stale"
    # ...then a zombie ack surfaces: counted, and _ship treats it as a
    # refusal (the stale verdict path).
    assert fence.admit(5, 1500) == "stale"
    assert drops("handoff_ack") == before + 1


async def test_tcp_frames_fenced_to_one_incarnation():
    """One tcp stream = one serving incarnation: frames claiming another
    (a zombie's late packets after the listener restarted) are counted
    and dropped, never delivered."""
    from dynamo_tpu.runtime.network.tcp import _TcpClientEngine

    class FakeConn:
        def __init__(self):
            self.q = asyncio.Queue()
            self.closed_streams = []

        def open_stream(self):
            return 1, self.q

        async def send(self, header, payload=None):
            pass

        def close_stream(self, sid):
            self.closed_streams.append(sid)

    class FakePlane:
        def __init__(self, conn):
            self.conn = conn

        async def _conn(self, addr):
            return self.conn

    conn = FakeConn()
    engine = _TcpClientEngine(FakePlane(conn), ("127.0.0.1", 1), "k")
    conn.q.put_nowait(("item", {"t": 1}, 7000))
    conn.q.put_nowait(("item", {"t": 666}, 6999))  # zombie frame
    conn.q.put_nowait(("item", {"t": 2}, 7000))
    conn.q.put_nowait(("end", None, 7000))

    before = drops("tcp")
    items = await collect(engine.generate({}, Context()))
    assert [i["t"] for i in items] == [1, 2]
    assert drops("tcp") == before + 1


# ---------------------------------------------------------------------------
# Warm-restart restore: never a crash loop (satellite 2)
# ---------------------------------------------------------------------------


def _outcome(name):
    return RESTORE_OUTCOME.value(outcome=name)


async def test_partial_crc_corruption_drops_only_bad_blocks(tmp_path):
    """Per-block CRCs: flipping bytes in ONE block's rows drops that block
    (and its chain descendants — children must not commit under a parent
    that never installed) while every other block restores."""
    import json

    from tests.test_jax_engine import make_engine, req, run_one

    ckpt = str(tmp_path / "ckpt")
    prompt = list(range(10, 42))  # 8 blocks of 4
    engine_a, _ = make_engine()
    try:
        await run_one(engine_a, req(prompt, max_tokens=3))
        result = await engine_a.save_checkpoint(ckpt)
        assert result["blocks"] >= 8
    finally:
        await engine_a.stop()

    with open(os.path.join(ckpt, "manifest.json")) as f:
        manifest = json.load(f)
    n = len(manifest["blocks"])
    data = np.load(os.path.join(ckpt, manifest["data"]))
    k, v = data["k"].copy(), data["v"].copy()
    # Corrupt block row 2's K payload.
    flat = k[2].reshape(-1).view(np.uint8)
    flat[: 8] ^= 0xFF
    np.savez(os.path.join(ckpt, manifest["data"]).replace(".npz", ""),
             k=k, v=v)

    before_partial = _outcome("partial")
    engine_b, _ = make_engine()
    try:
        restored = await engine_b.load_checkpoint(ckpt)
        # Row 2 and its descendants dropped; ancestors restored.
        assert 0 < restored < n
        assert restored <= n - 1
        assert _outcome("partial") == before_partial + 1
    finally:
        await engine_b.stop()


async def test_fully_corrupt_archive_is_counted_cold_start(tmp_path):
    from tests.test_jax_engine import make_engine, req, run_one

    ckpt = str(tmp_path / "ckpt")
    engine_a, _ = make_engine()
    try:
        await run_one(engine_a, req(range(10, 30), max_tokens=3))
        await engine_a.save_checkpoint(ckpt)
    finally:
        await engine_a.stop()

    import json

    with open(os.path.join(ckpt, "manifest.json")) as f:
        data_name = json.load(f)["data"]
    with open(os.path.join(ckpt, data_name), "wb") as f:
        f.write(b"not a zip at all")

    before = _outcome("cold_corrupt")
    engine_b, _ = make_engine()
    try:
        assert await engine_b.load_checkpoint(ckpt) == 0
        assert engine_b.pool.cached_blocks == 0
        assert _outcome("cold_corrupt") == before + 1
    finally:
        await engine_b.stop()


async def test_empty_and_missing_dirs_restore_zero(tmp_path):
    from tests.test_jax_engine import make_engine

    before = _outcome("empty")
    engine, _ = make_engine()
    try:
        os.makedirs(str(tmp_path / "empty"), exist_ok=True)
        assert await engine.load_checkpoint(str(tmp_path / "empty")) == 0
        assert await engine.load_checkpoint(str(tmp_path / "missing")) == 0
        assert _outcome("empty") == before + 2
    finally:
        await engine.stop()


async def test_seed_stamp_mismatch_is_cold_start(tmp_path):
    """The sampling seed is part of the compatibility stamp: restored KV
    under a different seed would continue streams with DIFFERENT noise —
    bit-exactness requires a cold start instead."""
    from tests.test_jax_engine import make_engine, req, run_one

    ckpt = str(tmp_path / "ckpt")
    engine_a, _ = make_engine(seed=1)
    try:
        await run_one(engine_a, req(range(10, 30), max_tokens=3))
        await engine_a.save_checkpoint(ckpt)
    finally:
        await engine_a.stop()

    before = _outcome("cold_mismatch")
    engine_b, _ = make_engine(seed=2)
    try:
        assert await engine_b.load_checkpoint(ckpt) == 0
        assert _outcome("cold_mismatch") == before + 1
    finally:
        await engine_b.stop()


async def test_injected_restore_failure_is_cold_error(tmp_path):
    """The restore.load chaos seam: the restore machinery failing outright
    resolves to a logged cold start — never a crash loop."""
    from tests.test_jax_engine import make_engine, req, run_one

    ckpt = str(tmp_path / "ckpt")
    engine_a, _ = make_engine()
    try:
        await run_one(engine_a, req(range(10, 30), max_tokens=3))
        await engine_a.save_checkpoint(ckpt)
    finally:
        await engine_a.stop()

    plan = faults.FaultPlan(seed=5, rules=(
        faults.FaultRule(point=fn.RESTORE_LOAD, at=(1,), kind="error"),
    ))
    before = _outcome("cold_error")
    engine_b, _ = make_engine()
    try:
        with faults.armed(plan) as plane:
            assert await engine_b.load_checkpoint(ckpt) == 0
        assert plane.trace == [(fn.RESTORE_LOAD, 1, 0, "error")]
        assert _outcome("cold_error") == before + 1
        # The seam only poisoned that one attempt: the next restore works.
        assert await engine_b.load_checkpoint(ckpt) > 0
    finally:
        await engine_b.stop()
