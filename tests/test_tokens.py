"""Block hashing + radix tree (ref: lib/tokens tests, radix_tree.rs tests)."""

import pytest

from dynamo_tpu.tokens import (
    RadixTree,
    compute_block_hash_for_seq,
    compute_block_hashes,
)

W0 = (1, 0)
W1 = (2, 0)


def test_hashes_deterministic_and_chained():
    tokens = list(range(256))
    h1 = compute_block_hashes(tokens, 64)
    h2 = compute_block_hashes(tokens, 64)
    assert h1 == h2 and len(h1) == 4
    # Chained: changing an early token changes every later hash.
    tokens2 = [999] + tokens[1:]
    h3 = compute_block_hashes(tokens2, 64)
    assert all(a != b for a, b in zip(h1, h3))
    # Same prefix ⇒ same leading hashes.
    h4 = compute_block_hashes(tokens[:128], 64)
    assert h4 == h1[:2]


def test_partial_tail_block_not_hashed():
    assert len(compute_block_hashes(list(range(150)), 64)) == 2
    assert compute_block_hashes([1, 2, 3], 64) == []


def test_incremental_extension():
    tokens = list(range(192))
    full = compute_block_hashes(tokens, 64)
    prefix = compute_block_hashes(tokens[:64], 64)
    ext = compute_block_hashes(tokens[64:], 64, parent_hash=prefix[-1])
    assert prefix + ext == full


def test_salt_changes_hashes():
    tokens = list(range(64))
    assert compute_block_hashes(tokens, 64) != compute_block_hashes(tokens, 64, salt=7)


def test_reference_alias():
    tokens = list(range(64))
    assert compute_block_hash_for_seq(tokens, 64) == compute_block_hashes(tokens, 64)


def test_block_size_validation():
    with pytest.raises(ValueError):
        compute_block_hashes([1], 0)


# -- radix tree -------------------------------------------------------------


def seq_hashes(n_blocks, block_size=16, start=0):
    return compute_block_hashes(list(range(start, start + n_blocks * block_size)), block_size)


def test_store_and_find():
    tree = RadixTree()
    hashes = seq_hashes(4)
    tree.store(W0, hashes)
    scores = tree.find_matches(hashes)
    assert scores.scores == {W0: 4}
    assert scores.matched_blocks == 4


def test_partial_overlap():
    tree = RadixTree()
    hashes = seq_hashes(4)
    tree.store(W0, hashes[:2])
    tree.store(W1, hashes)
    scores = tree.find_matches(hashes)
    assert scores.scores == {W0: 2, W1: 4}
    assert scores.best() == (W1, 4)


def test_no_match_on_divergent_prefix():
    tree = RadixTree()
    tree.store(W0, seq_hashes(4, start=0))
    scores = tree.find_matches(seq_hashes(4, start=10_000))
    assert scores.scores == {}


def test_incremental_store_with_parent():
    tree = RadixTree()
    hashes = seq_hashes(4)
    tree.store(W0, hashes[:2])
    tree.store(W0, hashes[2:], parent_hash=hashes[1])
    assert tree.find_matches(hashes).scores == {W0: 4}


def test_remove_blocks():
    tree = RadixTree()
    hashes = seq_hashes(4)
    tree.store(W0, hashes)
    tree.remove(W0, hashes[2:])
    scores = tree.find_matches(hashes)
    assert scores.scores == {W0: 2}
    assert tree.num_blocks == 2  # pruned


def test_remove_worker():
    tree = RadixTree()
    hashes = seq_hashes(3)
    tree.store(W0, hashes)
    tree.store(W1, hashes[:1])
    tree.remove_worker(W0)
    scores = tree.find_matches(hashes)
    assert scores.scores == {W1: 1}
    assert tree.num_blocks == 1
    assert tree.workers == [W1]


def test_hole_ends_run():
    tree = RadixTree()
    hashes = seq_hashes(4)
    tree.store(W0, hashes)
    tree.remove(W0, [hashes[1]])  # hole at depth 2
    scores = tree.find_matches(hashes)
    assert scores.scores.get(W0) == 1


def test_dp_ranks_distinct():
    tree = RadixTree()
    hashes = seq_hashes(2)
    tree.store((5, 0), hashes)
    tree.store((5, 1), hashes[:1])
    scores = tree.find_matches(hashes)
    assert scores.scores == {(5, 0): 2, (5, 1): 1}
