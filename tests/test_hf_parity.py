"""Real-checkpoint parity: HF safetensors → our engine vs transformers CPU.

Reference parity: the reference validates each engine against real models in
tests/serve/test_vllm.py (greedy text from an actual checkpoint). This
environment has no network, so the checkpoints are *created locally* with
transformers (`save_pretrained`) — small random-init models in real HF
format (safetensors + config.json + tokenizer.json). That still exercises
everything downloads would: name mapping, transposes, biases, tied
embeddings, RoPE convention, GQA layout — the bug classes random in-process
init can hide (VERDICT weak #7).
"""

import asyncio
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.hf_loader import load_hf_checkpoint
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import collect

VOCAB = 256


def _save_tokenizer(model_dir):
    from dynamo_tpu.llm.tokenizer import tiny_tokenizer

    tok = tiny_tokenizer(VOCAB)
    tok._tok.save(str(model_dir / "tokenizer.json"))


def _make_llama_dir(tmp_path, *, tie=False, qwen=False):
    torch.manual_seed(7)
    common = dict(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=tie,
        eos_token_id=0,
        bos_token_id=None,
    )
    if qwen:
        cfg = transformers.Qwen2Config(**common)
        model = transformers.Qwen2ForCausalLM(cfg)
    else:
        cfg = transformers.LlamaConfig(**common, attention_bias=False)
        model = transformers.LlamaForCausalLM(cfg)
    model = model.eval().to(torch.float32)
    model_dir = tmp_path / ("qwen2-tiny" if qwen else "llama-tiny")
    model.save_pretrained(str(model_dir), safe_serialization=True)
    _save_tokenizer(model_dir)
    return model_dir, model


def _our_config(model_dir) -> ModelConfig:
    cfg = ModelConfig.from_model_dir(str(model_dir))
    return dataclasses.replace(cfg, dtype=jnp.float32)


def _hf_greedy(model, prompt, n):
    ids = torch.tensor([prompt])
    with torch.no_grad():
        out = model.generate(
            ids, max_new_tokens=n, do_sample=False, eos_token_id=None,
            pad_token_id=0,
        )
    return out[0, len(prompt):].tolist()


def _engine_for(model_dir, config):
    params = load_hf_checkpoint(str(model_dir), config)
    return JaxEngine(
        JaxEngineArgs(
            config=config, block_size=4, num_kv_blocks=128, max_num_seqs=2,
            max_model_len=128, prefill_chunk=32,
        ),
        params,
    )


async def _engine_greedy(engine, prompt, n):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        request_id="parity",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )
    outs = await collect(engine.generate(req, Context()))
    assert not any(o.error for o in outs), [o.error for o in outs]
    return [t for o in outs for t in o.token_ids]


def test_llama_checkpoint_logits_parity(tmp_path):
    model_dir, hf = _make_llama_dir(tmp_path)
    config = _our_config(model_dir)
    assert config.n_kv_heads == 2 and not config.qkv_bias

    prompt = [3, 17, 42, 99, 5, 250, 11, 64]
    params = load_hf_checkpoint(str(model_dir), config)
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    logits, _, _ = llama.forward_paged(
        params, config,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table), k, v,
    )
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    np.testing.assert_allclose(np.asarray(logits[0]), ref, rtol=2e-4, atol=2e-4)


async def test_llama_checkpoint_greedy_decode_parity(tmp_path):
    model_dir, hf = _make_llama_dir(tmp_path)
    config = _our_config(model_dir)
    engine = _engine_for(model_dir, config)
    prompt = [3, 17, 42, 99, 5, 250, 11, 64, 7, 8, 9, 200, 13]
    try:
        ours = await _engine_greedy(engine, prompt, 16)
    finally:
        await engine.stop()
    assert ours == _hf_greedy(hf, prompt, 16)


async def test_qwen2_checkpoint_greedy_decode_parity(tmp_path):
    """Qwen2 exercises qkv bias + tied word embeddings."""
    model_dir, hf = _make_llama_dir(tmp_path, tie=True, qwen=True)
    config = _our_config(model_dir)
    assert config.qkv_bias and config.tie_word_embeddings
    engine = _engine_for(model_dir, config)
    prompt = [5, 77, 131, 9, 44, 202, 3, 18]
    try:
        ours = await _engine_greedy(engine, prompt, 16)
    finally:
        await engine.stop()
    assert ours == _hf_greedy(hf, prompt, 16)


async def test_chunked_prefill_matches_hf(tmp_path):
    """A prompt longer than prefill_chunk goes through the chunked path."""
    model_dir, hf = _make_llama_dir(tmp_path)
    config = _our_config(model_dir)
    params = load_hf_checkpoint(str(model_dir), config)
    engine = JaxEngine(
        JaxEngineArgs(
            config=config, block_size=4, num_kv_blocks=128, max_num_seqs=2,
            max_model_len=128, prefill_chunk=8,
        ),
        params,
    )
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, VOCAB, size=29).tolist()
    try:
        ours = await _engine_greedy(engine, prompt, 8)
    finally:
        await engine.stop()
    assert ours == _hf_greedy(hf, prompt, 8)


async def test_http_serves_real_checkpoint(tmp_path):
    """Model dir → tokenizer + chat template + engine → OpenAI pipeline.

    End-to-end over the real checkpoint: text in, text out, with the
    tokenizer resolved from the saved tokenizer.json (VERDICT #4 e2e leg).
    """
    from dynamo_tpu.llm.entrypoint import build_local_pipeline
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    model_dir, hf = _make_llama_dir(tmp_path)
    # give the dir a chat template so chat/completions renders
    with open(model_dir / "tokenizer_config.json", "w") as f:
        json.dump(
            {
                "chat_template": (
                    "{% for m in messages %}{{ m['content'] }}{% endfor %}"
                )
            },
            f,
        )
    config = _our_config(model_dir)
    engine = _engine_for(model_dir, config)
    card = ModelDeploymentCard(
        name="llama-tiny", model_path=str(model_dir), context_length=128,
        kv_block_size=4, eos_token_ids=list(config.eos_token_ids),
    )
    pipeline = build_local_pipeline(card, engine)
    try:
        outs = await collect(
            pipeline.generate(
                {
                    "model": "llama-tiny",
                    "messages": [
                        {"role": "user", "content": "the quick brown fox"}
                    ],
                    "max_tokens": 8,
                    "temperature": 0.0,
                    "ignore_eos": True,
                },
                Context(),
            )
        )
    finally:
        await engine.stop()
    deltas = [o for o in outs if not isinstance(o, dict)]  # skip annotations
    assert not any(o.error for o in deltas), [o.error for o in deltas]
    text = "".join(o.text for o in deltas)
    from dynamo_tpu.llm.tokenizer import HFTokenizer

    tok = HFTokenizer.from_pretrained_dir(str(model_dir))
    prompt_ids = tok.encode("the quick brown fox")
    ref_ids = _hf_greedy(hf, prompt_ids, 8)
    # DecodeStream withholds trailing incomplete UTF-8 (U+FFFD) at flush;
    # normalize the reference the same way before comparing.
    assert text == tok.decode(ref_ids).rstrip("�")


def test_int8_checkpoint_load_logits_close(tmp_path):
    """hf_loader quantization="int8": host-side per-layer quantization must
    land within int8 rounding of the fp32 logits, and the int8 weight cache
    must round-trip the quantized tree bit-exactly."""
    from dynamo_tpu.models.quantize import is_quantized
    from dynamo_tpu.models.weight_cache import load_checkpoint_cached

    model_dir, hf = _make_llama_dir(tmp_path)
    config = _our_config(model_dir)
    prompt = [3, 17, 42, 99, 5, 250, 11, 64]

    qparams = load_hf_checkpoint(str(model_dir), config, quantization="int8")
    assert is_quantized(qparams)
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    args = (
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table),
    )
    logits, _, _ = llama.forward_paged(qparams, config, *args, k, v)
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    rel = np.max(np.abs(np.asarray(logits[0]) - ref)) / (np.max(np.abs(ref)) + 1e-9)
    assert rel < 0.06, rel

    # cache round-trip: second load hits the int8 cache, same tree
    cache_dir = str(tmp_path / "wcache")
    p1, hit1 = load_checkpoint_cached(
        str(model_dir), config, cache_dir=cache_dir, quantization="int8"
    )
    p2, hit2 = load_checkpoint_cached(
        str(model_dir), config, cache_dir=cache_dir, quantization="int8"
    )
    assert not hit1 and hit2
    import jax

    assert jax.tree.all(jax.tree.map(lambda a, b: bool(jnp.all(a == b)), p1, p2))
    # fp cache key unaffected
    pf, hitf = load_checkpoint_cached(str(model_dir), config, cache_dir=cache_dir)
    assert not hitf and not is_quantized(pf)


def _make_gemma2_dir(tmp_path):
    """Tiny Gemma-2: alternating sliding-window layers, softcaps, GeGLU,
    unit-offset + post norms, scaled embeddings — every arch knob."""
    torch.manual_seed(11)
    cfg = transformers.Gemma2Config(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        query_pre_attn_scalar=16,
        sliding_window=8,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        hidden_activation="gelu_pytorch_tanh",
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        eos_token_id=0,
        bos_token_id=None,
        attn_implementation="eager",  # sdpa skips the softcap
    )
    model = transformers.Gemma2ForCausalLM(cfg).eval().to(torch.float32)
    model_dir = tmp_path / "gemma2-tiny"
    model.save_pretrained(str(model_dir), safe_serialization=True)
    _save_tokenizer(model_dir)
    return model_dir, model


def test_gemma2_config_ingestion(tmp_path):
    model_dir, _ = _make_gemma2_dir(tmp_path)
    config = _our_config(model_dir)
    assert config.act_fn == "gelu_tanh"
    assert config.rmsnorm_unit_offset and config.post_norms and config.embed_scale
    assert config.attn_logit_softcap == 50.0
    assert config.final_logit_softcap == 30.0
    assert config.query_scale == 16
    assert config.sliding_window == 8 and config.sliding_window_every == 2
    assert config.tie_word_embeddings
    # alternating pattern: even layers windowed
    assert config.layer_windows() == [8, 0, 8, 0]


async def test_gemma2_checkpoint_greedy_decode_parity(tmp_path):
    """Prompt longer than the sliding window (8) so local layers actually
    mask; greedy tokens must match transformers exactly."""
    model_dir, hf = _make_gemma2_dir(tmp_path)
    config = _our_config(model_dir)
    engine = _engine_for(model_dir, config)
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, VOCAB, size=21).tolist()
    try:
        ours = await _engine_greedy(engine, prompt, 12)
    finally:
        await engine.stop()
    assert ours == _hf_greedy(hf, prompt, 12)


def test_gemma2_logits_parity(tmp_path):
    model_dir, hf = _make_gemma2_dir(tmp_path)
    config = _our_config(model_dir)
    prompt = [3, 17, 42, 99, 5, 250, 11, 64, 7, 8, 9, 200, 13, 77, 101]
    params = load_hf_checkpoint(str(model_dir), config)
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    logits, _, _ = llama.forward_paged(
        params, config,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table), k, v,
    )
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    np.testing.assert_allclose(np.asarray(logits[0]), ref, rtol=2e-4, atol=2e-4)


def test_config_dialect_gates():
    """Family detection edges: Gemma-1 (no post-norms), Qwen2's vestigial
    sliding_window behind use_sliding_window=false, Gemma-3 refusal."""
    base = dict(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
    )
    g1 = ModelConfig.from_hf_config(
        {**base, "architectures": ["GemmaForCausalLM"], "model_type": "gemma",
         "hidden_activation": "gelu_pytorch_tanh"}
    )
    assert g1.rmsnorm_unit_offset and g1.embed_scale and g1.tie_word_embeddings
    assert not g1.post_norms and g1.sliding_window is None

    qwen = ModelConfig.from_hf_config(
        {**base, "architectures": ["Qwen2ForCausalLM"],
         "sliding_window": 4096, "use_sliding_window": False}
    )
    assert qwen.sliding_window is None

    mistral = ModelConfig.from_hf_config(
        {**base, "architectures": ["MistralForCausalLM"], "sliding_window": 4096}
    )
    assert mistral.sliding_window == 4096 and mistral.sliding_window_every == 1

    # Gemma-3 (r5: implemented — was refused in r4): qk-norm + pattern +
    # dual-frequency rope fields ingest; softcaps stay unset.
    g3 = ModelConfig.from_hf_config(
        {**base, "architectures": ["Gemma3ForCausalLM"],
         "model_type": "gemma3_text", "sliding_window": 512,
         "sliding_window_pattern": 6, "rope_local_base_freq": 10000.0,
         "rope_theta": 1000000.0,
         "hidden_activation": "gelu_pytorch_tanh"}
    )
    assert g3.qk_norm and g3.post_norms and g3.rmsnorm_unit_offset
    assert g3.sliding_window_pattern == 6 and g3.rope_local_theta == 10000.0
    assert g3.attn_logit_softcap is None

    # layer_types list alone (no explicit pattern) also derives the pattern
    g3b = ModelConfig.from_hf_config(
        {**base, "architectures": ["Gemma3ForCausalLM"],
         "model_type": "gemma3_text", "sliding_window": 512,
         "layer_types": ["sliding_attention", "full_attention"],
         "hidden_activation": "gelu_pytorch_tanh"}
    )
    # the layer_types list is honored VERBATIM (aperiodic layouts included)
    assert g3b.layer_windows() == [512, 0]

    # neither pattern nor layer_types on a gemma-3 config → loud refusal
    # (the silent every-layer-windowed fallback is the garbage-logits mode)
    with __import__("pytest").raises(ValueError, match="gemma-3"):
        ModelConfig.from_hf_config(
            {**base, "architectures": ["Gemma3ForCausalLM"],
             "model_type": "gemma3_text", "sliding_window": 512,
             "hidden_activation": "gelu_pytorch_tanh"}
        )


# ---------------------------------------------------------------------------
# Qwen3 (qk-norm family — the reference's in-tree perf-anchor architecture)
# ---------------------------------------------------------------------------


def _make_qwen3_dir(tmp_path):
    """Tiny Qwen3: per-head q/k RMSNorm before RoPE, no qkv bias,
    explicit head_dim — the aiconfigurator anchor family."""
    torch.manual_seed(13)
    cfg = transformers.Qwen3Config(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,
        max_position_embeddings=256,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        eos_token_id=0,
        bos_token_id=None,
        attn_implementation="eager",
    )
    model = transformers.Qwen3ForCausalLM(cfg).eval().to(torch.float32)
    model_dir = tmp_path / "qwen3-tiny"
    model.save_pretrained(str(model_dir), safe_serialization=True)
    _save_tokenizer(model_dir)
    return model_dir, model


def test_qwen3_config_dialect(tmp_path):
    model_dir, _ = _make_qwen3_dir(tmp_path)
    config = _our_config(model_dir)
    assert config.qk_norm
    assert not config.qkv_bias
    assert config.head_dim_ == 32


def test_qwen3_logits_parity(tmp_path):
    model_dir, hf = _make_qwen3_dir(tmp_path)
    config = _our_config(model_dir)
    prompt = [3, 17, 42, 99, 5, 250, 11, 64, 7, 8, 9, 200, 13]
    params = load_hf_checkpoint(str(model_dir), config)
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    logits, _, _ = llama.forward_paged(
        params, config,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table), k, v,
    )
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    np.testing.assert_allclose(np.asarray(logits[0]), ref, rtol=2e-4, atol=2e-4)


async def test_qwen3_checkpoint_greedy_decode_parity(tmp_path):
    model_dir, hf = _make_qwen3_dir(tmp_path)
    config = _our_config(model_dir)
    engine = _engine_for(model_dir, config)
    rng = np.random.default_rng(7)
    prompt = rng.integers(1, VOCAB, size=19).tolist()
    try:
        ours = await _engine_greedy(engine, prompt, 10)
    finally:
        await engine.stop()
    assert ours == _hf_greedy(hf, prompt, 10)


def _make_mixtral_dir(tmp_path):
    """Tiny random Mixtral checkpoint in the real HF layout
    (block_sparse_moe.gate + experts.{e}.w1/w3/w2) — exercises the MoE
    expert-weight mapping (ref: recipes/deepseek-r1/README.md:9-12 MoE
    serving; MIXTRAL layout is the public HF contract)."""
    torch.manual_seed(11)
    cfg = transformers.MixtralConfig(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        eos_token_id=0,
        bos_token_id=None,
    )
    model = transformers.MixtralForCausalLM(cfg)
    model_dir = tmp_path / "mixtral-tiny"
    model.save_pretrained(str(model_dir), safe_serialization=True)
    _save_tokenizer(model_dir)
    return model_dir, model.eval()


def test_mixtral_checkpoint_logits_parity(tmp_path):
    model_dir, hf = _make_mixtral_dir(tmp_path)
    config = _our_config(model_dir)
    assert config.is_moe and config.n_experts == 4
    assert config.n_experts_per_tok == 2

    prompt = [3, 17, 42, 99, 5, 250, 11, 64, 7, 131]
    params = load_hf_checkpoint(str(model_dir), config)
    assert "we_gate" in params["layers"] and "router_w" in params["layers"]
    assert params["layers"]["we_gate"].shape == (2, 4, 64, 96)
    assert params["layers"]["we_down"].shape == (2, 4, 96, 64)
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    logits, _, _ = llama.forward_paged(
        params, config,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table), k, v,
    )
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    np.testing.assert_allclose(np.asarray(logits[0]), ref, rtol=2e-3, atol=2e-3)


async def test_mixtral_checkpoint_greedy_decode_parity(tmp_path):
    model_dir, hf = _make_mixtral_dir(tmp_path)
    config = _our_config(model_dir)
    prompt = [9, 88, 201, 54, 33, 120]
    want = _hf_greedy(hf, prompt, 8)
    engine = _engine_for(model_dir, config)
    try:
        got = await _engine_greedy(engine, prompt, 8)
    finally:
        await engine.stop()
    assert got == want, (got, want)


def test_mixtral_int8_checkpoint_loads(tmp_path):
    """Quantized expert loading: per-expert int8 == stacked int8; logits
    stay close to the fp32 reference."""
    model_dir, hf = _make_mixtral_dir(tmp_path)
    config = _our_config(model_dir)
    params = load_hf_checkpoint(str(model_dir), config, quantization="int8")
    lg = params["layers"]["we_gate"]
    assert lg["q8"].shape == (2, 4, 64, 96) and lg["q8"].dtype == jnp.int8
    assert lg["s"].shape == (2, 4, 1, 96)
    prompt = [3, 17, 42, 99, 5, 250]
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    logits, _, _ = llama.forward_paged(
        params, config,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table), k, v,
    )
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    # int8 weight rounding: coarse bound, but argmax must agree
    assert np.argmax(np.asarray(logits[0])) == np.argmax(ref)


def test_mixtral_checkpoint_ep_sharded_parity(tmp_path):
    """The REAL-checkpoint MoE tree ep-shards on the virtual mesh and
    produces the same logits as unsharded (closing the loop: HF layout →
    loader → expert-parallel serving)."""
    from dynamo_tpu.parallel import (
        MeshConfig,
        ShardingRules,
        make_mesh,
        shard_params,
    )

    model_dir, hf = _make_mixtral_dir(tmp_path)
    config = _our_config(model_dir)
    params = load_hf_checkpoint(str(model_dir), config)
    prompt = [3, 17, 42, 99, 5, 250, 11, 64]
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    args = (
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table),
    )
    base, _, _ = llama.forward_paged(params, config, *args, k, v)

    mesh = make_mesh(MeshConfig(ep=4, tp=2))
    rules = ShardingRules()
    sp = shard_params(params, llama.param_logical_axes(config), rules, mesh)
    k2 = jax.device_put(k, rules.sharding(mesh, *llama.kv_cache_logical_axes()))
    v2 = jax.device_put(v, rules.sharding(mesh, *llama.kv_cache_logical_axes()))
    sharded, _, _ = jax.jit(
        lambda p, kc, vc: llama.forward_paged(p, config, *args, kc, vc)
    )(sp, k2, v2)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(sharded), rtol=2e-4, atol=2e-4
    )


def _make_gemma3_dir(tmp_path):
    """Tiny Gemma-3 text model: 5:1-style local/global pattern (pattern=3
    here so a 6-layer model exercises both kinds), qk-norm, dual-frequency
    RoPE, no softcaps — the r4-refused architecture, now implemented."""
    torch.manual_seed(12)
    cfg = transformers.Gemma3TextConfig(
        vocab_size=VOCAB,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=6,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        query_pre_attn_scalar=16,
        sliding_window=8,
        sliding_window_pattern=3,
        rope_theta=1000000.0,
        rope_local_base_freq=10000.0,
        hidden_activation="gelu_pytorch_tanh",
        max_position_embeddings=256,
        rms_norm_eps=1e-6,
        eos_token_id=0,
        bos_token_id=None,
        attn_implementation="eager",
    )
    model = transformers.Gemma3ForCausalLM(cfg).eval().to(torch.float32)
    model_dir = tmp_path / "gemma3-tiny"
    model.save_pretrained(str(model_dir), safe_serialization=True)
    _save_tokenizer(model_dir)
    return model_dir, model


def test_gemma3_config_ingestion(tmp_path):
    model_dir, _ = _make_gemma3_dir(tmp_path)
    config = _our_config(model_dir)
    assert config.qk_norm and config.rmsnorm_unit_offset
    assert config.post_norms and config.embed_scale
    assert config.attn_logit_softcap is None
    assert config.sliding_window == 8
    assert config.rope_local_theta == 10000.0
    assert config.rope_theta == 1000000.0
    # every 3rd layer global, others windowed
    assert config.layer_windows() == [8, 8, 0, 8, 8, 0]


def test_gemma3_logits_parity(tmp_path):
    model_dir, hf = _make_gemma3_dir(tmp_path)
    config = _our_config(model_dir)
    prompt = [3, 17, 42, 99, 5, 250, 11, 64, 7, 8, 9, 200, 13, 77, 101]
    params = load_hf_checkpoint(str(model_dir), config)
    k, v = llama.init_kv_cache(config, 16, 4)
    table = np.zeros((1, 8), dtype=np.int32)
    table[0, :4] = [1, 2, 3, 4]
    logits, _, _ = llama.forward_paged(
        params, config,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.zeros(1, jnp.int32),
        jnp.asarray([len(prompt)], dtype=jnp.int32),
        jnp.asarray(table), k, v,
    )
    with torch.no_grad():
        ref = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    np.testing.assert_allclose(np.asarray(logits[0]), ref, rtol=2e-4, atol=2e-4)


async def test_gemma3_checkpoint_greedy_decode_parity(tmp_path):
    """Prompt longer than the window (8) so local layers mask AND the
    local/global rope split matters; greedy tokens must match
    transformers exactly."""
    model_dir, hf = _make_gemma3_dir(tmp_path)
    config = _our_config(model_dir)
    engine = _engine_for(model_dir, config)
    rng = np.random.default_rng(4)
    prompt = rng.integers(1, VOCAB, size=21).tolist()
    try:
        ours = await _engine_greedy(engine, prompt, 12)
    finally:
        await engine.stop()
    assert ours == _hf_greedy(hf, prompt, 12)
