"""Logits processors (ops/logits_process.py): penalty math vs a numpy
reference, bias packing, min-p sampling, and end-to-end engine behavior
(bias-forced generation, penalty plumbing through the fused decode).

Reference parity: the reference surfaces logits processing to engines via
`dynamo.logits_processing` (python bindings) and relies on vLLM's sampler
for penalties/bias; here they are fused into the native engine's decode."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops import logits_process as lp
from dynamo_tpu.ops.sampling import sample_tokens

from tests.test_jax_engine import make_engine, req, run_one
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)


class TestApply:
    def _np_reference(self, logits, counts, pmask, rep, pres, freq):
        out = logits.astype(np.float64).copy()
        seen = (counts > 0) | pmask
        for b in range(out.shape[0]):
            for v in range(out.shape[1]):
                if seen[b, v]:
                    out[b, v] = (
                        out[b, v] / rep[b] if out[b, v] > 0 else out[b, v] * rep[b]
                    )
                out[b, v] -= freq[b] * counts[b, v]
                if counts[b, v] > 0:
                    out[b, v] -= pres[b]
        return out

    def test_penalties_match_reference(self):
        rng = np.random.default_rng(0)
        B, V = 3, 16
        logits = rng.normal(size=(B, V)).astype(np.float32)
        counts = rng.integers(0, 3, size=(B, V)).astype(np.int32)
        pmask = rng.random((B, V)) < 0.3
        rep = np.array([1.0, 1.5, 0.8], np.float32)
        pres = np.array([0.0, 0.7, -0.2], np.float32)
        freq = np.array([0.0, 0.3, 0.1], np.float32)
        params = lp.ProcParams(
            rep=jnp.asarray(rep), pres=jnp.asarray(pres), freq=jnp.asarray(freq),
            bias_ids=jnp.full((B, lp.MAX_BIAS_SLOTS), -1, jnp.int32),
            bias_vals=jnp.zeros((B, lp.MAX_BIAS_SLOTS), jnp.float32),
        )
        state = lp.ProcState(
            out_counts=jnp.asarray(counts), prompt_mask=jnp.asarray(pmask)
        )
        got = np.asarray(lp.apply(jnp.asarray(logits), params, state))
        want = self._np_reference(logits, counts, pmask, rep, pres, freq)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_neutral_params_are_identity(self):
        B, V = 2, 8
        logits = np.random.default_rng(1).normal(size=(B, V)).astype(np.float32)
        state = lp.init_state(B, V)
        # garbage counts must not matter under neutral params
        state = state._replace(
            out_counts=jnp.ones((B, V), jnp.int32),
            prompt_mask=jnp.ones((B, V), jnp.bool_),
        )
        got = np.asarray(lp.apply(jnp.asarray(logits), lp.neutral_params(B), state))
        np.testing.assert_allclose(got, logits, rtol=1e-6)

    def test_bias_scatter_and_prompt_only(self):
        B, V = 2, 12
        logits = np.zeros((B, V), np.float32)
        ids = np.full((B, lp.MAX_BIAS_SLOTS), -1, np.int32)
        vals = np.zeros((B, lp.MAX_BIAS_SLOTS), np.float32)
        ids[0, 0], vals[0, 0] = 3, 2.5
        ids[1, 0], vals[1, 0] = 7, -4.0
        params = lp.ProcParams(
            rep=jnp.ones(B), pres=jnp.zeros(B), freq=jnp.zeros(B),
            bias_ids=jnp.asarray(ids), bias_vals=jnp.asarray(vals),
        )
        pmask = jnp.zeros((B, V), jnp.bool_)
        got = np.asarray(lp.apply_prompt_only(jnp.asarray(logits), pmask, params))
        assert got[0, 3] == 2.5 and got[1, 7] == -4.0
        assert np.count_nonzero(got) == 2

    def test_record_tokens_respects_active(self):
        state = lp.init_state(2, 8)
        state = lp.record_tokens(
            state, jnp.asarray([3, 5]), jnp.asarray([1, 0])
        )
        counts = np.asarray(state.out_counts)
        assert counts[0, 3] == 1 and counts[1, 5] == 0

    def test_reset_and_count_slot(self):
        state = lp.init_state(2, 10)
        state = lp.reset_slot(state, 1, [2, 4, 4, 9])
        state = lp.count_token(state, 1, 7)
        mask = np.asarray(state.prompt_mask)
        counts = np.asarray(state.out_counts)
        assert mask[1, 2] and mask[1, 4] and mask[1, 9] and not mask[1, 0]
        assert counts[1, 7] == 1 and counts[0].sum() == 0

    def test_reset_slot_restores_generated_history(self):
        """Preempted re-admission: output counts survive, prompt mask does
        not absorb generated tokens."""
        state = lp.init_state(1, 10)
        state = lp.reset_slot(state, 0, [1, 2], generated_tokens=[5, 5, 7])
        counts = np.asarray(state.out_counts)
        mask = np.asarray(state.prompt_mask)
        assert counts[0, 5] == 2 and counts[0, 7] == 1
        assert mask[0, 1] and mask[0, 2] and not mask[0, 5]


class TestPackBias:
    def test_openai_extremes_map_to_ban_scale(self):
        ids, vals = lp.pack_bias({"5": -100, "9": 100, 3: 1.5}, vocab=100)
        by_id = dict(zip(ids.tolist(), vals.tolist()))
        assert by_id[5] == lp.BAN_BIAS
        assert by_id[9] == -lp.BAN_BIAS
        assert by_id[3] == 1.5

    def test_truncation_keeps_extreme_entries(self):
        bias = {i: 0.01 for i in range(lp.MAX_BIAS_SLOTS + 10)}
        bias[999] = -100  # the ban must survive truncation
        ids, vals = lp.pack_bias(bias, vocab=2000)
        assert 999 in ids.tolist()
        assert (ids >= -1).all() and (ids < 2000).all()

    def test_out_of_vocab_dropped(self):
        ids, _ = lp.pack_bias({50_000: -100}, vocab=100)
        assert (ids == -1).all()


class TestMinP:
    def test_min_p_one_is_greedy(self):
        rng = jax.random.PRNGKey(0)
        logits = jnp.asarray(
            np.random.default_rng(2).normal(size=(4, 64)).astype(np.float32)
        )
        ones = jnp.ones(4)
        toks = sample_tokens(
            logits, rng, ones, jnp.zeros(4, jnp.int32), ones, min_p=ones
        )
        np.testing.assert_array_equal(
            np.asarray(toks), np.asarray(jnp.argmax(logits, axis=-1))
        )

    def test_min_p_zero_matches_off(self):
        rng = jax.random.PRNGKey(3)
        logits = jnp.asarray(
            np.random.default_rng(4).normal(size=(4, 32)).astype(np.float32)
        )
        ones = jnp.ones(4)
        a = sample_tokens(logits, rng, ones, jnp.zeros(4, jnp.int32), ones)
        b = sample_tokens(
            logits, rng, ones, jnp.zeros(4, jnp.int32), ones, min_p=jnp.zeros(4)
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _req_with(tokens, sampling, max_tokens=6):
    return PreprocessedRequest(
        token_ids=list(tokens),
        request_id="r-procs",
        sampling=sampling,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )


async def test_engine_logit_bias_forces_token():
    engine, _ = make_engine()
    try:
        forced = 11
        out = await run_one(
            engine,
            _req_with(
                range(10, 20),
                SamplingOptions(temperature=1.0, logit_bias={forced: 100}),
            ),
        )
        toks = [t for o in out for t in o.token_ids]
        assert toks and all(t == forced for t in toks)
    finally:
        await engine.stop()


async def test_engine_ban_token_never_appears():
    engine, _ = make_engine()
    try:
        # Greedy would emit some token sequence; ban the first greedy token
        # and it must never appear anywhere in the regenerated stream.
        base = await run_one(
            engine, _req_with(range(30, 40), SamplingOptions(temperature=0.0))
        )
        banned = base[0].token_ids[0]
        out = await run_one(
            engine,
            _req_with(
                range(30, 40),
                SamplingOptions(temperature=0.0, logit_bias={banned: -100}),
            ),
        )
        toks = [t for o in out for t in o.token_ids]
        assert toks and banned not in toks
    finally:
        await engine.stop()


async def test_engine_repetition_penalty_changes_greedy():
    """A huge repetition penalty must prevent the greedy loop emitting the
    same token twice in a row (tiny random models love fixed points)."""
    engine, _ = make_engine()
    try:
        out = await run_one(
            engine,
            _req_with(
                range(50, 60),
                SamplingOptions(temperature=0.0, repetition_penalty=8.0),
                max_tokens=8,
            ),
        )
        toks = [t for o in out for t in o.token_ids]
        assert len(toks) == 8
        assert all(a != b for a, b in zip(toks, toks[1:]))
    finally:
        await engine.stop()


async def test_engine_mixed_batch_procs_and_plain():
    """Processor and non-processor requests batched together: the plain
    request's output must match its solo greedy run (neutral-row identity)."""
    engine, _ = make_engine()
    try:
        plain = _req_with(range(10, 22), SamplingOptions(temperature=0.0))
        solo = await run_one(engine, plain)
        solo_toks = [t for o in solo for t in o.token_ids]
        biased = _req_with(
            range(40, 52),
            SamplingOptions(temperature=1.0, logit_bias={7: 100}),
        )
        outs = await asyncio.gather(
            run_one(engine, plain), run_one(engine, biased)
        )
        plain_toks = [t for o in outs[0] for t in o.token_ids]
        biased_toks = [t for o in outs[1] for t in o.token_ids]
        assert plain_toks == solo_toks
        assert all(t == 7 for t in biased_toks)
    finally:
        await engine.stop()
