"""Int8 vs bf16 decode at the 3B shape (fits both on one 16GB chip).

In the bandwidth-bound decode regime weight-only int8 must WIN (half the
weight bytes) — if it doesn't, the dequant isn't fusing into the dot.
"""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
import os
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import llama3_3b_config
from dynamo_tpu.models.quantize import init_quantized_params
from dynamo_tpu.ops.sampling import sample_tokens

cfg = llama3_3b_config()
BS = 64
NB = 16384 // BS  # 256 blocks * 64 = 16k positions; KV = 28L*16k*8KH*128D*2*2B = 1.9GB
B = 64
STEPS = 32
L = cfg.n_layers
MAXB = 4

which = sys.argv[1] if len(sys.argv) > 1 else "both"

tokens = jnp.ones((B,), jnp.int32)
start_pos = jnp.full((B,), 128, jnp.int32)
active = jnp.ones((B,), jnp.int32)
tables = jnp.asarray((np.arange(B * MAXB, dtype=np.int32) % NB).reshape(B, MAXB))
rng = jax.random.PRNGKey(1)
temp = jnp.ones((B,), jnp.float32)
topk = jnp.zeros((B,), jnp.int32)
topp = jnp.full((B,), 0.95, jnp.float32)


def bench(name, params):
    k, v = llama.init_kv_cache(cfg, NB, BS, layered=True)

    def run(params, k, v):
        return llama.decode_multi(
            params, cfg, tokens, start_pos, active, tables, k, v,
            rng, temp, topk, topp, num_steps=STEPS, use_kernel=True,
            want_logprobs=False,
        )

    f = jax.jit(run, donate_argnums=(1, 2))
    out = f(params, k, v); k, v = out[-2], out[-1]; np.asarray(out[0])
    n = 5
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(params, k, v); k, v = out[-2], out[-1]; np.asarray(out[0])
    dt = (time.perf_counter() - t0) / n
    print(f"{name}: {dt/STEPS*1000:.2f} ms/step ({B*STEPS/dt:.0f} tok/s)", flush=True)


if which in ("both", "int8"):
    qp = init_quantized_params(cfg, 0)
    bench("3B int8", qp)
    del qp
if which in ("both", "bf16"):
    fp = llama.init_params(cfg, jax.random.PRNGKey(0))
    bench("3B bf16", fp)
