"""Compare decode-step cache strategies on the real chip at the bench shape.

A: current — layer-scan with cache as xs/ys (full stacked cache rematerialized
   per step).
B: unrolled — Python loop over layers, cache as L-tuples of 4D arrays carried
   through the step scan (in-place scatter, no stacked copy).

Run: python _prof_unroll.py [steps]
"""
import sys
import time
import numpy as np
import jax, jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import qwen2_500m_config
from dynamo_tpu.ops.sampling import sample_tokens

cfg = qwen2_500m_config()
BS = 128
NB = 65536 // BS  # 512 blocks
B = 256
STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 64
MAX_BLOCKS = 4  # per-seq table: 4*128 = 512 positions, enough for ISL+OSL

params = llama.init_params(cfg, jax.random.PRNGKey(0))
L = cfg.n_layers

tokens = jnp.ones((B,), jnp.int32)
start_pos = jnp.full((B,), 128, jnp.int32)
active = jnp.ones((B,), jnp.int32)
tables = jnp.asarray((np.arange(B * MAX_BLOCKS, dtype=np.int32) % NB).reshape(B, MAX_BLOCKS))
rng = jax.random.PRNGKey(1)
temp = jnp.ones((B,), jnp.float32)
topk = jnp.zeros((B,), jnp.int32)
topp = jnp.full((B,), 0.95, jnp.float32)


def timeit(name, f, k, v):
    # Donated caches: thread the returned cache arrays into the next call.
    out = f(params, k, v)
    k, v = out[-2], out[-1]
    np.asarray(jax.tree.leaves(out[0])[0])  # force completion (axon quirk)
    n = 8
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(params, k, v)
        k, v = out[-2], out[-1]
        np.asarray(jax.tree.leaves(out[0])[0])
    dt = (time.perf_counter() - t0) / n
    print(f"{name}: {dt*1000:.1f} ms/dispatch = {dt/STEPS*1000:.2f} ms/step "
          f"({B*STEPS/dt:.0f} tok/s)", flush=True)
    return out


# ---------------- A: current scan form ----------------
def run_scan(params, k_cache, v_cache):
    return llama.decode_multi(
        params, cfg, tokens, start_pos, active, tables, k_cache, v_cache,
        rng, temp, topk, topp, num_steps=STEPS, use_kernel=True,
        want_logprobs=False,
    )

k_cache, v_cache = llama.init_kv_cache(cfg, NB, BS)
f_scan = jax.jit(run_scan, donate_argnums=(1, 2))
print("compiling A (scan xs/ys)...", flush=True)
out = timeit("A scan-xs/ys", f_scan, k_cache, v_cache)
del out, k_cache, v_cache


# ---------------- B: unrolled per-layer tuples ----------------
from dynamo_tpu.models.llama import decoder_layer, embed_tokens, lm_head_logits, rope_table


def forward_unrolled(params, toks, pos, lens, block_tables, k_layers, v_layers):
    c = cfg
    Bb, C = toks.shape
    hd = c.head_dim_
    x = embed_tokens(params, c, toks)
    p = pos[:, None] + jax.lax.broadcasted_iota(jnp.int32, (Bb, C), 1)
    cos, sin = rope_table(p, hd, c.rope_theta)
    windows = c.layer_windows()
    k_out, v_out = [], []
    for l in range(L):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        win = jnp.asarray(windows[l], jnp.int32)
        x, k_l, v_l = decoder_layer(
            c, lp, {}, win, x, cos, sin, k_layers[l], v_layers[l],
            block_tables, pos, lens, use_kernel=True, adapter_ids=None,
        )
        k_out.append(k_l)
        v_out.append(v_l)
    last = jnp.clip(lens - 1, 0, C - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    return lm_head_logits(params, c, x_last), tuple(k_out), tuple(v_out)


def run_unrolled(params, k_layers, v_layers):
    def one(carry, step_rng):
        toks, pos, k_t, v_t = carry
        logits, k_t, v_t = forward_unrolled(
            params, toks[:, None], pos, active, tables, k_t, v_t
        )
        nxt = sample_tokens(logits, step_rng, temp, topk, topp)
        nxt = jnp.where(active > 0, nxt, toks)
        return (nxt, pos + active, k_t, v_t), nxt

    rngs = jax.random.split(rng, STEPS)
    (_, _, k_t, v_t), toks_out = jax.lax.scan(
        one, (tokens, start_pos, k_layers, v_layers), rngs
    )
    return toks_out.T, k_t, v_t


k5, v5 = llama.init_kv_cache(cfg, NB, BS)
k_layers = tuple(k5[l] for l in range(L))
v_layers = tuple(v5[l] for l in range(L))
del k5, v5
f_unroll = jax.jit(run_unrolled, donate_argnums=(1, 2))
print("compiling B (unrolled per-layer)...", flush=True)
t0 = time.perf_counter()
out = timeit("B unrolled", f_unroll, k_layers, v_layers)
print(f"(B total incl first compile+run: {time.perf_counter()-t0:.0f}s)")
