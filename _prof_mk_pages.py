"""Profile the fused megakernel's dynamic page streaming across table widths.

Sweeps block-table widths (pow2 buckets) at a fixed shape and reports, per
width: trace+compile wall time and steady-state per-layer step time. The
r5 static unroll made BOTH scale with width (and refused widths > 16); the
r6 dynamic page loop must hold trace/compile ~flat while step time tracks
the ACTUAL history length, not the table capacity — this script is the
measurement for docs/design_docs/megakernel_paged_streaming.md.

Run: python _prof_mk_pages.py [widths...]   (default: 16 64 256)
On CPU the kernel runs in interpret mode (timings are relative only); on
the real chip it exercises Mosaic lowering at every width.
"""
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.quantize import quantize_params
from dynamo_tpu.ops.pallas.fused_layer import fused_decoder_layer, supports
from dynamo_tpu.ops.rope import rope_table

ON_TPU = jax.default_backend() == "tpu"
# On the chip, the 8B serving shape; on CPU a 1-layer miniature (interpret
# mode pays python-per-op, the sweep's SHAPE of the curve is what matters).
if ON_TPU:
    cfg = ModelConfig(
        name="prof-8b", d_model=4096, n_layers=1, n_heads=32, n_kv_heads=8,
        d_ff=14336, vocab_size=128256, head_dim=128, rope_theta=500000.0,
        dtype=jnp.bfloat16,
    )
    B, BS = 64, 16
else:
    cfg = ModelConfig(
        name="prof-mini", d_model=256, n_layers=1, n_heads=4, n_kv_heads=2,
        d_ff=512, vocab_size=128, head_dim=128, rope_theta=10000.0,
        dtype=jnp.bfloat16,
    )
    B, BS = 8, 16

assert supports(cfg, lora=False, quantized_weights=True)
widths = [int(w) for w in sys.argv[1:]] or [16, 64, 256]

params = llama.init_params(cfg, jax.random.PRNGKey(0))
qparams, _ = quantize_params(params, llama.param_logical_axes(cfg))
lp = jax.tree.map(lambda a: a[0], qparams["layers"])

rng = np.random.default_rng(0)
x = jnp.asarray(
    rng.standard_normal((B, cfg.d_model)).astype(np.float32) * 0.3
).astype(jnp.bfloat16)

# Sliding window for the windowed-row columns: a fixed 4-page window, so
# as the table grows the LIVE span per row stays constant — the r11
# windowed page loop (per-row start at floor((pos−W)/BS)) must hold
# windowed step time ~flat across widths while the full-attention column
# keeps tracking the table-filling history.
WINDOW_TOKENS = 4 * 16

rows = []
for P in widths:
    NB = B * P + 8
    KH, D = cfg.n_kv_heads, cfg.head_dim_
    k_pool = jnp.zeros((NB, BS, KH, D), jnp.bfloat16)
    v_pool = jnp.zeros((NB, BS, KH, D), jnp.bfloat16)
    tables = jnp.asarray(
        (np.arange(B * P, dtype=np.int32) % NB).reshape(B, P)
    )
    # history fills the table: step time at width P measures P real pages
    start_pos = jnp.full((B,), P * BS - 1, jnp.int32)
    cos, sin = rope_table(start_pos[:, None], D, cfg.rope_theta)

    def run(window=None):
        return fused_decoder_layer(
            x, cos[:, 0], sin[:, 0], lp, k_pool, v_pool, tables, start_pos,
            eps=cfg.rms_norm_eps, sm_scale=D**-0.5, batch_block=4,
            window=window,
        )

    t0 = time.perf_counter()
    out = run()
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    n = 20 if ON_TPU else 3
    t0 = time.perf_counter()
    for _ in range(n):
        out = run()
    jax.block_until_ready(out)
    step_ms = (time.perf_counter() - t0) / n * 1000

    # Windowed-row column: same full table, but every row's live span is
    # the fixed window — pages before floor((pos−W)/BS) are never
    # streamed, so this column should stay ~flat as P grows.
    win = jnp.asarray(WINDOW_TOKENS, jnp.int32)
    jax.block_until_ready(run(win))  # compile the windowed variant
    t0 = time.perf_counter()
    for _ in range(n):
        out = run(win)
    jax.block_until_ready(out)
    win_step_ms = (time.perf_counter() - t0) / n * 1000
    rows.append(
        {"table_pages": P, "ctx_tokens": P * BS,
         "trace_compile_s": round(compile_s, 3),
         "step_ms_per_layer": round(step_ms, 3),
         "window_tokens": WINDOW_TOKENS,
         "windowed_step_ms_per_layer": round(win_step_ms, 3),
         "windowed_vs_full": round(win_step_ms / max(step_ms, 1e-9), 3)}
    )
    print(json.dumps(rows[-1]), flush=True)

print(json.dumps({"backend": jax.default_backend(), "B": B, "sweep": rows}))
