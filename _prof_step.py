import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import qwen2_500m_config
from dynamo_tpu.ops.sampling import sample_tokens, compute_logprobs

cfg = qwen2_500m_config()
BS = 32; NB = 65536 // BS
params = llama.init_params(cfg, jax.random.PRNGKey(0))

def mkcache():
    return llama.init_kv_cache(cfg, NB, BS)

B, C = 8, 128
toks = jnp.ones((B, C), jnp.int32)
pos = jnp.zeros((B,), jnp.int32)
lens = jnp.full((B,), C, jnp.int32)
tables = jnp.asarray(np.arange(B*16, dtype=np.int32).reshape(B, 16))
rng = jax.random.PRNGKey(1)
t = jnp.ones((B,), jnp.float32); tk = jnp.zeros((B,), jnp.int32); tp = jnp.ones((B,), jnp.float32)

def variant(name, donate, with_sampling, kernel):
    def step(p_, k_, v_):
        logits, k_, v_ = llama.forward_paged(p_, cfg, toks, pos, lens, tables, k_, v_, use_kernel=kernel)
        if with_sampling:
            s = sample_tokens(logits, rng, t, tk, tp)
            lp = compute_logprobs(logits, s)
            return s, lp, k_, v_
        return logits, k_, v_
    f = jax.jit(step, donate_argnums=(1,2)) if donate else jax.jit(step)
    k, v = mkcache()
    out = f(params, k, v); jax.block_until_ready(out)
    if donate: k, v = out[-2], out[-1]
    n = 5; t0 = time.perf_counter()
    for _ in range(n):
        out = f(params, k, v)
        if donate: k, v = out[-2], out[-1]
    jax.block_until_ready(out)
    print(f"{name}: {(time.perf_counter()-t0)/n*1000:.1f} ms")

variant("prefill donate+sample kernel=T", True, True, True)
variant("prefill donate+sample kernel=F", True, True, False)
variant("prefill donate no-sample kernel=T", True, False, True)
variant("prefill NO-donate+sample kernel=T", False, True, True)
