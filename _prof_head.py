"""Prototype v2: fused pallas int8 lm-head kernel + two-stage exact top-k.

Weight pre-chunked [NC, D, BN] so every grid step DMAs one contiguous
chunk; logits computed directly in [B, BN] layout; stage-2 top-k in XLA.
"""
import functools, time, sys
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
from dynamo_tpu.ops.quant import quantize_q8

V, D, B = 128256, 4096, 64
BN = int(sys.argv[1]) if len(sys.argv) > 1 else 768
NC = V // BN
assert NC * BN == V, (V, BN)
NG = V // 128
W = 64


def _head_kernel(wc_ref, s_ref, x_ref, out_ref):
    w = wc_ref[0].astype(jnp.bfloat16)  # [D, BN]
    y = jax.lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, BN]
    out_ref[...] = y * s_ref[0]


@jax.jit
def head_fused(wc, ws, x):
    return pl.pallas_call(
        _head_kernel,
        grid=(NC,),
        in_specs=[
            pl.BlockSpec((1, D, BN), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, BN), lambda i: (i, 0, 0)),
            pl.BlockSpec((B, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B, BN), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((B, V), jnp.float32),
    )(wc, ws, x)


@jax.jit
def topk2(logits):
    g = logits.reshape(B, NG, 128)
    gmax = g.max(-1)  # [B, NG]
    gv, gi = jax.lax.top_k(gmax, W)
    cand = jnp.take_along_axis(g, gi[:, :, None], axis=1)  # [B, W, 128]
    cv, ci = jax.lax.top_k(cand.reshape(B, W * 128), W)
    tok = jnp.take_along_axis(gi, ci // 128, axis=1) * 128 + ci % 128
    return cv, tok


def bench(label, f, *a, n=20):
    r = f(*a)
    _ = jax.tree.map(np.asarray, r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*a)
    _ = jax.tree.map(np.asarray, r)
    print(f"{label}: {(time.perf_counter()-t0)/n*1000:.2f} ms", flush=True)


rng = np.random.default_rng(0)
w0 = rng.standard_normal((D, V), dtype=np.float32)
qt = quantize_q8(w0, [0])  # q8 [D, V], s [1, V]
wc = jnp.asarray(
    np.ascontiguousarray(qt["q8"].reshape(D, NC, BN).transpose(1, 0, 2))
)
ws = jnp.asarray(np.ascontiguousarray(qt["s"].reshape(1, NC, BN).transpose(1, 0, 2)))
x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32)).astype(jnp.bfloat16)

bench(f"fused head kernel BN={BN} [B,V]", head_fused, wc, ws, x)
lg = head_fused(wc, ws, x)
bench("topk2 (XLA two-stage)", topk2, lg)
full = jax.jit(lambda wc_, ws_, x_: topk2(head_fused(wc_, ws_, x_)))
bench("fused head + topk2", full, wc, ws, x)

cv, tok = full(wc, ws, x)
ref = x.astype(jnp.float32) @ (qt["q8"].astype(np.float32) * qt["s"])
ev, ei = jax.lax.top_k(ref, W)
print("values close:", bool(jnp.allclose(cv, ev, rtol=1e-3, atol=1e-3)))
print("ids match:", float((tok == ei).mean()))
