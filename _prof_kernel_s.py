import time, functools
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import qwen2_500m_config
import dynamo_tpu.ops.attention as att
import dynamo_tpu.ops.pallas.paged_attention as pk

cfg = qwen2_500m_config()
B, BS, P = 128, 32, 16
NB = 65536 // BS
params = llama.init_params(cfg, jax.random.PRNGKey(0))
tables = jnp.asarray(np.random.default_rng(0).permutation(NB)[:B*P].reshape(B, P).astype(np.int32))
tok = jnp.ones((B,), jnp.int32); pos = jnp.full((B,), 200, jnp.int32); act = jnp.ones((B,), jnp.int32)
rng = jax.random.PRNGKey(1)
t = jnp.ones((B,), jnp.float32); tk = jnp.zeros((B,), jnp.int32); tp = jnp.ones((B,), jnp.float32)

def run(label, use_kernel, S=None):
    if S is not None:
        orig = pk.paged_attention_kernel
        att._kernel_fn = functools.partial(orig, pages_per_step=S)
    else:
        att._kernel_fn = None; att._kernel_load_failed = False
    def step(p_, k_, v_):
        return llama.decode_multi(p_, cfg, tok, pos, act, tables, k_, v_, rng, t, tk, tp,
                                  num_steps=32, use_kernel=use_kernel, want_logprobs=False)
    f = jax.jit(step, donate_argnums=(1,2))
    k, v = llama.init_kv_cache(cfg, NB, BS)
    out = f(params, k, v); jax.block_until_ready(out); k, v = out[2], out[3]
    n = 3; t0 = time.perf_counter()
    for _ in range(n):
        out = f(params, k, v); k, v = out[2], out[3]
    jax.block_until_ready(out)
    dt = (time.perf_counter()-t0)/n
    print(f"{label}: {dt*1000:.0f} ms -> {B*32/dt:.0f} tok/s")

run("xla attention", False)
run("kernel S=1", True, 1)
run("kernel S=2", True, 2)
run("kernel S=4", True, 4)
run("kernel S=8", True, 8)
run("kernel S=16", True, 16)
