import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

def bench(label, fn, n=30):
    fn()
    t0 = time.perf_counter()
    for _ in range(n): r = fn()
    jax.block_until_ready(r) if r is not None else None
    print(f"{label}: {(time.perf_counter()-t0)/n*1000:.2f} ms")

a = np.zeros((128, 16), np.int32)
bench("jnp.asarray [128,16] (async)", lambda: jnp.asarray(a))
bench("jnp.asarray + block", lambda: jax.block_until_ready(jnp.asarray(a)))
key = jax.random.PRNGKey(0)
def split():
    k1, k2 = jax.random.split(key)
    return k2
bench("jax.random.split (async)", split)
bench("jax.random.split + block", lambda: jax.block_until_ready(split()))
x = jnp.ones((128, 32), jnp.int32)
bench("device_get [128,32]", lambda: jax.device_get(x))
f = jax.jit(lambda v: v + 1)
f(x)
bench("tiny jit dispatch + get", lambda: jax.device_get(f(x)))
