"""Benchmark: aggregated serving throughput of the native JAX engine.

Runs on whatever chip JAX sees (the driver provides one real TPU). AIPerf-
style fixed ISL/OSL/concurrency workload (BASELINE.md measurement plan,
config 1: Qwen2.5-0.5B-shape aggregated worker, random weights — weights
don't affect throughput).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
supporting fields. vs_baseline compares tokens/sec/chip against an assumed
A100-vLLM anchor for a 0.5B-class model (BASELINE.md north star: ≥ A100-vLLM
tokens/sec/chip); the anchor is an estimate recorded here, not a measured
number from the reference tree (it publishes none for this shape).
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

import jax

# Persistent XLA compilation cache: first bench run pays the compiles,
# subsequent runs (and driver re-runs) hit the cache.
jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__) or ".", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# A100 + vLLM, 0.5B-class model, moderate concurrency: ~5k decode tok/s/GPU
# (estimate; the reference repo publishes no in-tree number for this shape).
BASELINE_TOKS_PER_SEC_PER_CHIP = 5000.0

ISL = int(os.environ.get("BENCH_ISL", 128))
OSL = int(os.environ.get("BENCH_OSL", 64))
CONCURRENCY = int(os.environ.get("BENCH_CONCURRENCY", 256))
REQUESTS = int(os.environ.get("BENCH_REQUESTS", 512))
VERBOSE = os.environ.get("BENCH_VERBOSE") == "1"


async def run_bench():
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import (
        llama3_8b_config,
        mixtral_8x7b_config,
        qwen2_500m_config,
    )
    from dynamo_tpu.runtime.context import Context

    # BENCH_MODEL selects the shape. llama3-8b requires BENCH_QUANT=int8 to
    # fit the single 16 GB chip (8 GB int8 weights + KV).
    model_name = os.environ.get("BENCH_MODEL", "qwen2.5-0.5b")
    cfg = {
        "qwen2.5-0.5b": qwen2_500m_config,
        "llama3-8b": llama3_8b_config,
        "mixtral-8x7b": mixtral_8x7b_config,
    }[model_name]()
    # Measured sweep (kernel × block size × concurrency) on the real chip:
    # 128-token pages give the decode kernel large contiguous page DMAs
    # (32-token pages: 5.8k tok/s; 64: 7.0k; 128: 7.6k; 256 over-pads at
    # ISL=128 and drops to 5.0k). Concurrency 256 beats 384/512 on ITL
    # without losing aggregate throughput.
    block_size = int(os.environ.get("BENCH_BLOCK_SIZE", 128))
    engine = JaxEngine(
        JaxEngineArgs(
            config=cfg,
            block_size=block_size,
            num_kv_blocks=int(os.environ.get("BENCH_KV_BLOCKS", 65536 // block_size)),
            max_num_seqs=CONCURRENCY,
            max_model_len=max(512, ISL + OSL + 64),
            prefill_chunk=int(os.environ.get("BENCH_PREFILL_CHUNK", 128)),
            # One admission dispatch for the whole wave: prefill rows are
            # near-free to batch (measured Bp 8→128 = 2.4× cost for 16× rows)
            # and fewer admission rounds stop prefill from stealing decode
            # ticks (measured 9.4k → 11.0k tok/s, ITL 20.9 → 15.4ms).
            prefill_batch=int(os.environ.get("BENCH_PREFILL_BATCH", CONCURRENCY)),
            enable_prefix_caching=True,
            decode_steps=int(os.environ.get("BENCH_DECODE_STEPS", 64)),
            use_kernel=(
                None if (uk := os.environ.get("BENCH_USE_KERNEL")) is None
                else uk == "1"
            ),
            # BENCH_QUANT=int8 → weight-only int8 (8B-class shapes fit the
            # one 16 GB chip; see tests/test_quant.py for parity bounds).
            quantization=os.environ.get("BENCH_QUANT") or None,
        )
    )

    rng = np.random.default_rng(0)

    def make_req(i: int) -> PreprocessedRequest:
        return PreprocessedRequest(
            token_ids=rng.integers(10, cfg.vocab_size - 10, size=ISL).tolist(),
            request_id=f"bench-{i}",
            sampling=SamplingOptions(temperature=1.0, top_p=0.95),
            stop=StopConditions(max_tokens=OSL, ignore_eos=True),
        )

    async def run_one(req):
        t0 = time.monotonic()
        ttft = None
        n = 0
        async for out in engine.generate(req, Context()):
            if out.token_ids:
                if ttft is None:
                    ttft = time.monotonic() - t0
                n += len(out.token_ids)
        return n, ttft, time.monotonic() - t0

    async def run_wave(count, offset):
        sem = asyncio.Semaphore(CONCURRENCY)

        async def limited(i):
            async with sem:
                return await run_one(make_req(offset + i))

        return await asyncio.gather(*(limited(i) for i in range(count)))

    # Warmup wave triggers all jit compiles (prefill buckets + decode buckets).
    if VERBOSE:
        print("warmup wave...", flush=True)
    t0 = time.monotonic()
    await run_wave(CONCURRENCY, offset=10_000)
    if VERBOSE:
        print(f"warmup done in {time.monotonic()-t0:.1f}s; stats={engine.stats()}", flush=True)

    t0 = time.monotonic()
    results = await run_wave(REQUESTS, offset=0)
    wall = time.monotonic() - t0
    await engine.stop()

    total_tokens = sum(r[0] for r in results)
    ttfts = sorted(r[1] for r in results if r[1] is not None)
    itls = sorted(
        (r[2] - r[1]) / max(r[0] - 1, 1) for r in results if r[1] is not None
    )
    toks_per_sec = total_tokens / wall
    n_chips = jax.device_count()
    value = toks_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": (
                    "aggregated decode throughput "
                    f"({cfg.name}-shape, ISL={ISL}, OSL={OSL})"
                ),
                "value": round(value, 2),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(value / BASELINE_TOKS_PER_SEC_PER_CHIP, 4),
                "total_tokens": total_tokens,
                "wall_s": round(wall, 2),
                "p50_ttft_ms": round(1000 * ttfts[len(ttfts) // 2], 1),
                "p50_itl_ms": round(1000 * itls[len(itls) // 2], 2),
                "n_chips": n_chips,
                "backend": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    asyncio.run(run_bench())
