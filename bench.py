"""Benchmark: aggregated serving throughput of the native JAX engine.

Runs on whatever chip JAX sees (the driver provides one real TPU). AIPerf-
style fixed ISL/OSL/concurrency workload (BASELINE.md measurement plan,
config 1: Qwen2.5-0.5B-shape aggregated worker, random weights — weights
don't affect throughput; config 2 proxy: Llama-3-8B int8 on the same chip,
run as the "secondary" leg unless BENCH_SECONDARY=0).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
supporting fields:

  - ``anchor``: the baseline this run is judged against — a DERIVED
    bandwidth-roofline estimate of A100-80G + vLLM decode throughput for
    the SAME model/batch/context (BASELINE.md north star is "≥ A100-vLLM
    tokens/sec/chip"; the reference publishes no in-tree number for these
    shapes, so the anchor is computed from public hardware specs and a
    stated efficiency factor instead of invented). Formula in the JSON.
  - ``mfu`` / ``hbm_util``: this chip's achieved fraction of v5e peak
    compute (197 TFLOP/s bf16) and of its decode bandwidth roofline
    (819 GB/s HBM) — absolute efficiency, independent of any anchor.
  - ``secondary``: the 8B-int8 leg's numbers.

Knob reference (env): BENCH_ISL/OSL/CONCURRENCY/REQUESTS, BENCH_MODEL
(qwen2.5-0.5b | llama3-8b | llama3-3b | qwen3-8b | gemma3-1b | gemma2-2b |
mixtral-8x7b — the qwen3/gemma shapes ride the megakernel's epilogue path),
BENCH_QUANT=int8,
BENCH_BLOCK_SIZE/KV_BLOCKS/PREFILL_CHUNK/PREFILL_BATCH/DECODE_STEPS,
BENCH_USE_KERNEL, BENCH_SPEC=ngram (speculative decoding),
BENCH_PIPELINE_DEPTH (decode-tick pipelining; 2 default, 1 = synchronous),
BENCH_SECONDARY=0 (skip the 8B-int8 leg), BENCH_DISAGG=0 / BENCH_OVERLOAD=0
/ BENCH_DRAIN=0 / BENCH_CRASH=0 (skip the disagg / overload-armor /
SIGTERM-drain / kill-9-crash legs), BENCH_PROJECTION=0 (skip the modeled
70B tp8 projection leg — it otherwise ALWAYS lands, measured per-layer
inputs on TPU, roofline-modeled inputs elsewhere), BENCH_ELASTICITY=0
(skip the sim-clocked elasticity leg: planner ramp convergence,
scale-down re-prefill, select_worker cost at 10 vs 100 workers — pure
CPU arithmetic, lands on any backend), BENCH_KVREUSE=0 (skip the
KV-reuse leg: shared-prefix mix through a tiny real engine — hit rate
by tier, prefill tokens saved, TTFT delta vs cold-cache control; lands
on any backend), BENCH_TICKBUDGET=0 (skip the tick-budgeter leg:
prefill-heavy wave over a steady decode population, budgeted vs
aggregated p99 ITL + throughput; lands on any backend).
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

import jax

# Persistent XLA compilation cache: first bench run pays the compiles,
# subsequent runs (and driver re-runs) hit the cache.
jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__) or ".", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

ISL = int(os.environ.get("BENCH_ISL", 128))
OSL = int(os.environ.get("BENCH_OSL", 64))
CONCURRENCY = int(os.environ.get("BENCH_CONCURRENCY", 256))
REQUESTS = int(os.environ.get("BENCH_REQUESTS", 512))
VERBOSE = os.environ.get("BENCH_VERBOSE") == "1"

# Public hardware specs the roofline anchor/metrics derive from. The
# v5e decode roofline itself lives in runtime/roofline.py — ONE formula
# shared with the always-on perf ledger's achieved-fraction gauge — and
# is imported below; only the A100 anchor model stays bench-local.
A100_80G_BW = 2039e9  # B/s (SXM)
# Achieved-bandwidth fraction granted to the A100+vLLM anchor. Optimistic
# for the anchor (generous to the baseline): well-tuned decode sustains
# ~40-60% of peak HBM bandwidth end-to-end; we grant 60%.
ANCHOR_EFF = 0.6
# Per-layer decode-step latency floor granted to the anchor: small models
# are kernel-launch/overhead-bound on GPUs, not bandwidth-bound (~7-10
# kernels per decoder layer × ~30-40µs launch+sync each). Without this
# term a 0.5B "anchor" would claim 200k+ tok/s — far beyond anything vLLM
# reports. 0.3 ms/layer ≈ the well-tuned end of small-model GPU serving.
ANCHOR_LAYER_FLOOR_S = 0.3e-3
# Public on-demand list prices (GCP, us-central, mid-2024 era): the
# per-chip comparison is bandwidth-lopsided (A100-80G has 2.5× the HBM
# bandwidth of a v5e), so the JSON also reports throughput per dollar.
A100_80G_USD_HR = 3.67
V5E_USD_HR = 1.20


# Shared pure-arithmetic roofline model (runtime/roofline.py): param
# counts, decode step bytes, and the v5e constants — the perf ledger
# grades live windows against the same math these legs report.
from dynamo_tpu.runtime.roofline import (  # noqa: E402
    V5E_BW,
    V5E_PEAK_BF16,
    active_param_count as _active_param_count,
    decode_step_bytes as _decode_step_bytes,
    param_count as _param_count,
)


def _record_stamp(preset: str | None, quant: str | None) -> dict:
    """Provenance stamp for every emitted record (ISSUE 19): schema
    version, backend/host/preset fingerprint, git rev — cross-round
    comparison (`dynamo-tpu bench compare`) is only sound when both
    records prove they measured the same thing."""
    import socket
    import subprocess

    from dynamo_tpu.bench.compare import BENCH_SCHEMA_VERSION

    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:
        rev = None
    try:
        backend = jax.default_backend()
    except Exception:
        backend = None
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "git_rev": rev,
        "fingerprint": {
            "backend": backend,
            "host": socket.gethostname(),
            "preset": preset,
            "quant": quant,
        },
    }


def _sentinel_epilogue(out: dict) -> None:
    """Run the regression sentinel against the newest usable previous
    round's BENCH_*.json (when present): attach the typed report to the
    record and print the human table to stderr (stdout stays ONE JSON
    line). Never raises — a broken epilogue must not cost the round its
    perf record."""
    import glob
    import sys as _sys

    try:
        from dynamo_tpu.bench.compare import (
            compare_records,
            format_report,
            unwrap_record,
        )

        here = os.path.dirname(os.path.abspath(__file__))
        ref = ref_path = None
        for p in sorted(glob.glob(os.path.join(here, "BENCH_*.json")),
                        reverse=True):
            try:
                with open(p, "r", encoding="utf-8") as f:
                    doc = unwrap_record(json.load(f))
            except (OSError, ValueError):
                doc = None
            if doc is not None:
                ref, ref_path = doc, os.path.basename(p)
                break
        if ref is None:
            return
        report = compare_records(ref, out)
        report["reference_path"] = ref_path
        report["candidate_path"] = "(this run)"
        out["sentinel"] = report
        print(format_report(report), file=_sys.stderr)
    except Exception as exc:
        out["sentinel"] = {"error": f"{type(exc).__name__}: {exc}"}


def _anchor_toks_per_sec(cfg, batch: int, avg_ctx: float, quant: str | None) -> float:
    """Derived A100-80G + vLLM decode estimate for the same workload:
    per-step time = max(bandwidth roofline, kernel-launch floor)."""
    step_bytes = _decode_step_bytes(cfg, batch, avg_ctx, quant)
    step_s = max(
        step_bytes / (A100_80G_BW * ANCHOR_EFF),
        cfg.n_layers * ANCHOR_LAYER_FLOOR_S,
    )
    return batch / step_s


def _fault_activity_start() -> dict:
    from dynamo_tpu.runtime import faults

    return faults.activity_snapshot()


def _fault_plane_record(activity_before: dict) -> dict:
    """Fault-plane counters for one leg (deltas since the leg started):
    a chaos-free bench run must show zero retries, breaker opens, and
    migrations — a nonzero here is a self-healing path activating
    SPURIOUSLY, which is itself a perf regression (every retry is wire
    time, every migration a re-prefill). The overload-plane counters
    (sheds / brownout transitions / deadline expiries) extend the same
    contract: under-capacity legs must record ZERO for all three."""
    from dynamo_tpu.runtime import faults

    snap = faults.plane_snapshot()
    delta = {
        k: v - activity_before.get(k, 0)
        for k, v in snap["activity"].items()
    }
    return {
        "armed": snap["armed"],
        "injections": snap["injections"],
        "pull_retries": delta.get("pull_retries", 0),
        "breaker_opens": delta.get("breaker_opens", 0),
        "migrations": delta.get("migrations", 0),
        "sheds": delta.get("sheds", 0),
        "brownout_transitions": delta.get("brownout_transitions", 0),
        "deadline_expired": delta.get("deadline_expired", 0),
        # Parser plane (ISSUE 15): the degradation ladder / parse-error
        # frames activating on a clean-corpus leg would be the jail
        # mangling healthy traffic — same zero-spurious contract.
        "parser_degraded": delta.get("parser_degraded", 0),
        "parser_exceptions": delta.get("parser_exceptions", 0),
    }


def _kv_reuse_start() -> dict:
    """Snapshot the KV-reuse plane's counters before a leg."""
    from dynamo_tpu.runtime.kv_reuse_observe import global_plane

    m = global_plane().metrics
    return {
        "hits": {t: m.hits.value(tier=t) for t in sorted(m._known_tiers)},
        "misses": m.misses.value(),
        "reused": m.reused_tokens.value(),
        "recomputed": m.recomputed_tokens.value(),
        "saved_s": m.seconds_saved.value(),
    }


def _kv_reuse_record(before: dict) -> dict:
    """KV-reuse deltas for one leg: hit rate by tier, reused vs recomputed
    prefill tokens, and the plane's priced prefill-seconds-saved. On the
    random-prompt decode legs hit_rate reads ~0 — the number exists so a
    cache win (or an accounting regression) is visible NEXT TO the tok/s
    headline, not in a separate tool."""
    after = _kv_reuse_start()
    hits = {
        t: after["hits"].get(t, 0) - before["hits"].get(t, 0)
        for t in after["hits"]
    }
    hits = {t: n for t, n in hits.items() if n > 0}
    misses = after["misses"] - before["misses"]
    lookups = sum(hits.values()) + misses
    return {
        "hit_rate": round(sum(hits.values()) / lookups, 4) if lookups else 0.0,
        "hit_rate_by_tier": {
            t: round(n / lookups, 4) for t, n in hits.items()
        } if lookups else {},
        "hits": {t: int(n) for t, n in hits.items()},
        "misses": int(misses),
        "tokens_saved": int(after["reused"] - before["reused"]),
        "tokens_recomputed": int(after["recomputed"] - before["recomputed"]),
        "prefill_seconds_saved": round(after["saved_s"] - before["saved_s"], 4),
    }


def _trajectory_start() -> dict:
    """Snapshot the trajectory plane's counters before a leg (the SLO
    verdicts + span ingest deltas the zero-spurious record reads)."""
    from dynamo_tpu.runtime.trajectory import global_store

    store = global_store()
    return {
        "spans": store.spans_ingested,
        "dropped": store.spans_dropped,
        "good": store.slo.good_streams,
        "breached": store.slo.breached_streams,
    }


def _trajectory_record(before: dict) -> dict:
    """Trajectory/SLO record for one leg: goodput + multi-window burn rate
    + per-phase p99 contribution from the process-global SloTracker, span
    ingest/drop deltas (bench legs drive engines with traceless contexts,
    so a nonzero span delta here is trajectory machinery activating
    SPURIOUSLY on the hot path — same contract as fault_plane), and the
    measured per-span export cost (the trajectory-overhead delta the <1%
    observe bar covers, see _prof_gap.py)."""
    import time as _time

    from dynamo_tpu.runtime.context import Context as _Ctx
    from dynamo_tpu.runtime.trajectory import global_store
    from dynamo_tpu.utils.tracing import Tracer as _Tracer
    from dynamo_tpu.utils.tracing import export_span as _export_span

    store = global_store()
    slo = store.slo.snapshot()
    tracer = _Tracer(path="", otlp=False)  # never ship synthetic spans
    ctx = _Ctx(
        baggage={"traceparent": "00-" + "a" * 32 + "-" + "b" * 16 + "-01"}
    )
    n = 2000
    t0 = _time.perf_counter()
    for _ in range(n):
        _export_span(
            "engine.decode", ctx, start_mono=0.0, end_mono=0.001,
            tracer=tracer, generated=8,
        )
    span_us = (_time.perf_counter() - t0) / n * 1e6
    return {
        "spans_ingested": store.spans_ingested - before["spans"],
        "spans_dropped": store.spans_dropped - before["dropped"],
        "good_streams": store.slo.good_streams - before["good"],
        "breached_streams": store.slo.breached_streams - before["breached"],
        "goodput": slo["goodput"],
        "burn_rate": slo["burn_rate"],
        "phase_p99_ms": slo["phase_p99_ms"],
        "trajectory_span_us": round(span_us, 3),
        # 3 retrospective phase spans per traced request, all at stream
        # end — the whole trajectory delta a served request pays.
        "trajectory_request_us": round(3 * span_us, 3),
    }


async def run_leg(model_name: str, quant: str | None, spec: str | None,
                  concurrency: int | None = None, requests: int | None = None,
                  kv_quant: str | None = None, isl: int | None = None,
                  osl: int | None = None):
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import (
        gemma2_2b_config,
        gemma3_1b_config,
        llama3_3b_config,
        llama3_8b_config,
        mixtral_8x7b_config,
        qwen2_500m_config,
        qwen3_8b_config,
    )
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.device_observe import global_compile_watcher

    # Per-leg compile deltas: the watcher is process-global, so snapshot
    # BEFORE the leg's engine exists (its programs compile during warmup).
    compile_before = global_compile_watcher().totals()
    fault_activity0 = _fault_activity_start()
    trajectory0 = _trajectory_start()
    kv_reuse0 = _kv_reuse_start()

    cfg = {
        "qwen2.5-0.5b": qwen2_500m_config,
        "llama3-3b": llama3_3b_config,
        "llama3-8b": llama3_8b_config,
        "qwen3-8b": qwen3_8b_config,
        "gemma3-1b": gemma3_1b_config,
        "gemma2-2b": gemma2_2b_config,
        "mixtral-8x7b": mixtral_8x7b_config,
    }[model_name]()
    # Measured sweep (kernel × block size × concurrency) on the real chip:
    # 128-token pages give the decode kernel large contiguous page DMAs
    # (32-token pages: 5.8k tok/s; 64: 7.0k; 128: 7.6k; 256 over-pads at
    # ISL=128 and drops to 5.0k). Concurrency 256 beats 384/512 on ITL
    # without losing aggregate throughput.
    block_size = int(os.environ.get("BENCH_BLOCK_SIZE", 128))
    concurrency = concurrency or CONCURRENCY
    requests = requests or REQUESTS
    isl = isl or ISL
    osl = osl or OSL
    kv_quant = kv_quant or os.environ.get("BENCH_KV_QUANT") or None
    # 8B int8 on one 16 GB chip: ~8 GB of weights leave ~3 GB for KV, which
    # must cover concurrency × ceil((ISL+OSL)/block) blocks WITH headroom —
    # undersizing thrashes preemption-by-recompute (measured: 256-seq batch
    # on 256 blocks → 625 tok/s, TTFT 32s).
    default_blocks = 65536 // block_size
    if model_name in ("llama3-8b", "qwen3-8b"):
        # int8 KV halves bytes/token -> double the token budget fits the
        # same ~3 GB beside 8 GB of int8 weights
        budget = 49152 if kv_quant == "int8" else 24576
        default_blocks = budget // block_size
    engine = JaxEngine(
        JaxEngineArgs(
            config=cfg,
            block_size=block_size,
            num_kv_blocks=int(os.environ.get("BENCH_KV_BLOCKS", default_blocks)),
            max_num_seqs=concurrency,
            max_model_len=max(512, isl + osl + 64),
            prefill_chunk=int(os.environ.get("BENCH_PREFILL_CHUNK", 128)),
            # One admission dispatch for the whole wave: prefill rows are
            # near-free to batch (measured Bp 8→128 = 2.4× cost for 16×
            # rows) and fewer admission rounds stop prefill from stealing
            # decode ticks (measured 9.4k → 11.0k tok/s, ITL 20.9 → 15.4ms).
            prefill_batch=int(os.environ.get("BENCH_PREFILL_BATCH", concurrency)),
            enable_prefix_caching=True,
            decode_steps=int(os.environ.get("BENCH_DECODE_STEPS", 64)),
            use_kernel=(
                None if (uk := os.environ.get("BENCH_USE_KERNEL")) is None
                else uk == "1"
            ),
            # BENCH_QUANT=int8 → weight-only int8. At ≥3B shapes int8 BEATS
            # bf16 (measured 3B: 16.2 vs 22.5 ms/step — decode is weight-
            # bandwidth-bound and int8 halves the stream); at 0.5B the
            # weights are too small for bandwidth to matter.
            quantization=quant,
            spec_mode=spec,
            kv_cache_dtype=kv_quant,
            # Decode-tick pipelining (docs/design_docs/decode_pipelining.md):
            # 2 double-buffers bursts so readback + emit hide under device
            # compute; 1 reproduces the pre-pipelining synchronous ticks.
            pipeline_depth=int(os.environ.get("BENCH_PIPELINE_DEPTH", 2)),
        )
    )

    rng = np.random.default_rng(0)

    # BENCH_PROMPT=repeat: prompts are a repeated short pattern — the
    # lookup-friendly workload (extractive/templated traffic) where
    # speculative decoding should win; default is worst-case random.
    repeat_prompts = os.environ.get("BENCH_PROMPT") == "repeat"

    def make_req(i: int) -> PreprocessedRequest:
        if repeat_prompts:
            pattern = rng.integers(10, cfg.vocab_size - 10, size=8).tolist()
            toks = (pattern * (isl // 8 + 1))[:isl]
        else:
            toks = rng.integers(10, cfg.vocab_size - 10, size=isl).tolist()
        return PreprocessedRequest(
            token_ids=toks,
            request_id=f"bench-{i}",
            sampling=SamplingOptions(
                temperature=0.0 if spec else 1.0, top_p=None if spec else 0.95
            ),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def run_one(req):
        t0 = time.monotonic()
        ttft = None
        n = 0
        async for out in engine.generate(req, Context()):
            if out.token_ids:
                if ttft is None:
                    ttft = time.monotonic() - t0
                n += len(out.token_ids)
        return n, ttft, time.monotonic() - t0

    async def run_wave(count, offset):
        sem = asyncio.Semaphore(concurrency)

        async def limited(i):
            async with sem:
                return await run_one(make_req(offset + i))

        return await asyncio.gather(*(limited(i) for i in range(count)))

    # Warmup wave triggers all jit compiles (prefill buckets + decode buckets).
    if VERBOSE:
        print(f"[{model_name}] warmup wave...", flush=True)
    t0 = time.monotonic()
    await run_wave(concurrency, offset=10_000)
    engine.hbm.snapshot()  # sample the post-warmup ledger (peak tracking)
    if VERBOSE:
        print(f"[{model_name}] warmup done in {time.monotonic()-t0:.1f}s; "
              f"stats={engine.stats()}", flush=True)

    t0 = time.monotonic()
    results = await run_wave(requests, offset=0)
    wall = time.monotonic() - t0
    await engine.stop()
    stats = engine.stats()
    # Device-plane regressions this leg: compile time/program count (a
    # recompile storm shows up as compile_s exploding while tok/s sags)
    # and the HBM ledger's footprint (accounting drift / unplanned growth).
    hbm_bytes = engine.hbm.total_bytes()
    hbm_peak_bytes = engine.hbm.peak_bytes
    compile_after = global_compile_watcher().totals()
    compile_s = round(
        compile_after["compile_seconds"] - compile_before["compile_seconds"], 2
    )
    compiles = compile_after["compiles"] - compile_before["compiles"]
    # Process-CUMULATIVE distinct watched sites (program names are reused
    # across legs, so a per-leg delta would read ~0 after leg 1).
    compiled_programs = compile_after["programs"]
    recompile_storms = compile_after["storms"] - compile_before["storms"]
    # Host-gap aggregate: mean host-injected device wait per decode
    # dispatch (0 when the next burst was already in flight) — the number
    # the pipeline_depth knob exists to shrink.
    gap_count, gap_sum = engine.step_metrics.host_gap_stats()
    host_gap_ms = round(1000 * gap_sum / gap_count, 3) if gap_count else None

    # Drop every reference to the engine's device arrays BEFORE the next
    # leg allocates (an un-GC'd 8 GB int8 tree plus the next leg's engine
    # is over HBM: measured RESOURCE_EXHAUSTED cascade).
    import gc

    del engine
    gc.collect()

    # Megakernel coverage: decode bursts on the fused vs the XLA-fallback
    # path. A per-key compile demotion shifts bursts to fallback, so a
    # silent demotion shows up HERE as a coverage drop instead of
    # masquerading as a plain tok/s regression.
    mk_fused = int(stats.get("mk_fused_bursts", 0))
    mk_fallback = int(stats.get("mk_fallback_bursts", 0))
    fused_coverage = (
        round(mk_fused / (mk_fused + mk_fallback), 4)
        if (mk_fused + mk_fallback) else None
    )

    total_tokens = sum(r[0] for r in results)
    ttfts = sorted(r[1] for r in results if r[1] is not None)
    if not ttfts:
        raise RuntimeError(
            f"leg produced no successful requests ({len(results)} issued)"
        )
    itls = sorted(
        (r[2] - r[1]) / max(r[0] - 1, 1) for r in results if r[1] is not None
    )
    toks_per_sec = total_tokens / wall
    avg_ctx = isl + osl / 2
    step_bytes = _decode_step_bytes(cfg, concurrency, avg_ctx, quant)
    # Our own decode roofline on this chip (ignores prefill: decode
    # dominates the wall at OSL=64) and compute utilization.
    roofline = concurrency * V5E_BW / step_bytes
    flops_per_tok = 2 * _active_param_count(cfg)
    return {
        "model": cfg.name,
        "quant": quant,
        "kv_quant": kv_quant,
        "isl": isl,
        "osl": osl,
        "concurrency": concurrency,
        "toks_per_sec_per_chip": round(toks_per_sec / jax.device_count(), 2),
        "total_tokens": total_tokens,
        "wall_s": round(wall, 2),
        "p50_ttft_ms": round(1000 * ttfts[len(ttfts) // 2], 1),
        "p50_itl_ms": round(1000 * itls[len(itls) // 2], 2),
        "pipeline_depth": stats.get("pipeline_depth"),
        "host_gap_ms": host_gap_ms,
        "mk_fused_bursts": mk_fused,
        "mk_fallback_bursts": mk_fallback,
        "mk_demoted_variants": int(stats.get("mk_demoted_variants", 0)),
        "fused_coverage": fused_coverage,
        "compile_s": compile_s,
        # compiles = this leg's compilation events (signatures);
        # compiled_programs = process-cumulative distinct watched sites;
        # recompile_storms = this leg's budget violations.
        "compiles": compiles,
        "compiled_programs": compiled_programs,
        "recompile_storms": recompile_storms,
        "hbm_ledger_bytes": hbm_bytes,
        "hbm_ledger_peak_bytes": hbm_peak_bytes,
        "anchor_toks_per_sec": round(
            _anchor_toks_per_sec(cfg, concurrency, avg_ctx, quant), 1
        ),
        "mfu": round(toks_per_sec * flops_per_tok / V5E_PEAK_BF16, 4),
        "hbm_util": round(toks_per_sec / roofline, 4),
        "fault_plane": _fault_plane_record(fault_activity0),
        "trajectory": _trajectory_record(trajectory0),
        "kv_reuse": _kv_reuse_record(kv_reuse0),
        **(
            {
                "spec_proposed": stats.get("spec_proposed", 0),
                "spec_accepted": stats.get("spec_accepted", 0),
            }
            if spec
            else {}
        ),
    }


async def run_disagg_leg(isl: int = 512, osl: int = 64, concurrency: int = 4,
                         requests: int = 12, *, ceiling_only: bool = False,
                         n_layers: int | None = None):
    """Disaggregated P/D measurement — the north-star metric's missing
    number (BASELINE.md: 'disaggregated Llama-3-70B'; ref methodology
    docs/benchmarks/benchmarking.md). One chip timeshares a prefill engine
    and a decode engine wired through the real runtime endpoints + chunked
    KV transfer (disagg/handlers.py). Two measurements:

      1. ``transfer``: an IDLE-PATH pull of one prompt's KV through the
         real kv endpoint (export gather → wire → import scatter), timed
         directly — the unambiguous achieved rate.
      2. serving comparison at low concurrency vs an aggregated control:
         TTFT delta (= transfer + routing overhead) and ITL delta (decode
         ticks degraded by concurrent pulls). Low concurrency because the
         two engines TIMESHARE one chip here — queueing at high
         concurrency measures the missing second chip, not the transfer
         (the ``one_chip_timeshared`` field flags this).

    The model is the 0.5B bench shape: two 8B engines cannot share one
    16 GB chip, and every cost this leg measures (gather, serialize, wire,
    scatter, overlap) is mechanism — per-GB rates transfer to bigger
    models; docs/design_docs/performance.md extrapolates."""
    from dynamo_tpu.disagg import (
        DecodeHandler,
        KvTransferHandler,
        PrefillHandler,
        PrefillRouter,
    )
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import qwen2_500m_config
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.pipeline import build_pipeline

    import dataclasses

    fault_activity0 = _fault_activity_start()
    cfg = qwen2_500m_config()
    if n_layers:
        cfg = dataclasses.replace(cfg, n_layers=n_layers)

    def mk_engine():
        return JaxEngine(
            JaxEngineArgs(
                config=cfg,
                block_size=128,
                num_kv_blocks=256,
                max_num_seqs=concurrency,
                max_model_len=isl + osl + 64,
                prefill_chunk=min(512, isl),
                prefill_batch=concurrency,
                decode_steps=32,
            )
        )

    rng = np.random.default_rng(7)
    V = cfg.vocab_size

    def mk_req(i):
        return PreprocessedRequest(
            token_ids=rng.integers(10, V - 10, size=isl).tolist(),
            request_id=f"disagg-{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def run_wave(gen_fn, count):
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            async with sem:
                t0 = time.monotonic()
                ttft, n = None, 0
                async for out in gen_fn(mk_req(i)):
                    ids = (
                        out.token_ids if hasattr(out, "token_ids")
                        else out.get("token_ids")
                    ) or []
                    if ids and ttft is None:
                        ttft = time.monotonic() - t0
                    n += len(ids)
                return n, ttft, time.monotonic() - t0

        t0 = time.monotonic()
        res = await asyncio.gather(*(one(i) for i in range(count)))
        return res, time.monotonic() - t0

    def stats(res, wall):
        ttfts = sorted(r[1] for r in res if r[1] is not None)
        itls = sorted(
            (r[2] - r[1]) / max(r[0] - 1, 1) for r in res if r[1] is not None
        )
        toks = sum(r[0] for r in res)
        return {
            "toks_per_sec": round(toks / wall, 1),
            "p50_ttft_ms": round(1000 * ttfts[len(ttfts) // 2], 1),
            "p50_itl_ms": round(1000 * itls[len(itls) // 2], 2),
        }

    # -- aggregated control -------------------------------------------------
    agg_stats = None
    if not ceiling_only:
        agg = mk_engine()
        try:
            await run_wave(lambda r: agg.generate(r, Context()), concurrency)
            res, wall = await run_wave(
                lambda r: agg.generate(r, Context()), requests
            )
            agg_stats = stats(res, wall)
        finally:
            await agg.stop()

    # -- disaggregated ------------------------------------------------------
    rt = DistributedRuntime.detached()
    prefill_engine, decode_engine = mk_engine(), mk_engine()
    ns = rt.namespace("bench-disagg")
    served = []
    try:
        pc = ns.component("prefill")
        served.append(
            await pc.endpoint("generate").serve_endpoint(
                PrefillHandler(prefill_engine, worker_id=1).generate,
                instance_id=1,
            )
        )
        served.append(
            await pc.endpoint("kv").serve_endpoint(
                KvTransferHandler(prefill_engine).generate, instance_id=1
            )
        )

        async def kv_client():
            return await pc.endpoint("kv").client()

        dc = ns.component("backend")
        decode_handler = DecodeHandler(
            decode_engine, kv_client_factory=kv_client
        )
        served.append(
            await dc.endpoint("generate").serve_endpoint(
                decode_handler.generate, instance_id=2
            )
        )
        decode_client = await dc.endpoint("generate").client()

        async def prefill_client():
            return await pc.endpoint("generate").client()

        pipeline = build_pipeline(
            [PrefillRouter(prefill_client, threshold_tokens=64)],
            decode_client,
        )

        async def gen(r):
            async for out in pipeline.generate(r.to_dict(), Context()):
                yield out

        await run_wave(gen, concurrency)  # warm both engines + transfer

        # -- idle-path transfer microbench: one prompt's KV, timed alone --
        from dynamo_tpu.llm.protocols.common import DisaggregatedParams
        from dynamo_tpu.tokens.blocks import compute_block_hashes

        xfer_rates = []
        for trial in range(3):
            prompt = rng.integers(10, V - 10, size=isl).tolist()
            pre_req = mk_req(10_000 + trial)
            pre_req.token_ids = prompt
            pre_req.stop.max_tokens = 1  # prefill only
            async for _ in prefill_engine.generate(pre_req, Context()):
                pass
            dp = DisaggregatedParams(
                worker_id=1, prefilled_tokens=isl,
                kv_transfer={
                    "block_hashes": compute_block_hashes(prompt, 128),
                    "block_size": 128,
                },
            )
            b0 = decode_handler.bytes_pulled
            t0 = time.monotonic()
            pulled = await decode_handler._pull_blocks(dp)
            dt = time.monotonic() - t0
            nbytes = decode_handler.bytes_pulled - b0
            if pulled and nbytes:
                xfer_rates.append(nbytes / dt)
        xfer_mb_s = round(max(xfer_rates) / 1e6, 1) if xfer_rates else None

        if ceiling_only:
            # On-host ceiling mode (VERDICT r4 item 5): the same gather →
            # wire → scatter path with NO device tunnel in it — the
            # framework's own transfer cost as a number. Also measure
            # decode ITL with and without a concurrent export stream
            # draining (VERDICT item 4's overlap bound).
            # warm + baseline on the SAME engine the loaded wave uses so
            # the degradation ratio compares compiled-state like-for-like
            await run_wave(
                lambda r: prefill_engine.generate(r, Context()), concurrency
            )
            base_res, base_wall = await run_wave(
                lambda r: prefill_engine.generate(r, Context()), concurrency
            )
            base_itl = stats(base_res, base_wall)["p50_itl_ms"]

            stop_xfer = asyncio.Event()

            async def export_loop():
                from dynamo_tpu.tokens.blocks import (
                    compute_block_hashes as cbh,
                )
                prompt = rng.integers(10, V - 10, size=isl).tolist()
                r = mk_req(77_000)
                r.token_ids = prompt
                r.stop.max_tokens = 1
                async for _ in prefill_engine.generate(r, Context()):
                    pass
                hashes = cbh(prompt, 128)
                while not stop_xfer.is_set():
                    await prefill_engine.export_blocks_async(hashes)

            xfer_task = asyncio.ensure_future(export_loop())
            await asyncio.sleep(0.2)
            loaded_res, loaded_wall = await run_wave(
                lambda r: prefill_engine.generate(r, Context()), concurrency
            )
            stop_xfer.set()
            try:
                await xfer_task
            except Exception:
                pass
            loaded_itl = stats(loaded_res, loaded_wall)["p50_itl_ms"]
            return {
                "transfer_onhost_mb_per_s": xfer_mb_s,
                "itl_ms": base_itl,
                "itl_under_transfer_ms": loaded_itl,
                "itl_transfer_degradation": round(
                    loaded_itl / max(base_itl, 1e-9) - 1.0, 3
                ),
                "n_layers": cfg.n_layers,
                "note": (
                    "CPU backend, no tunnel in the path; on this 1-core "
                    "host the engines, wire, and decode compute share one "
                    "core, so itl degradation bounds CPU contention, not "
                    "device stalls (overlap is asserted by "
                    "tests/test_disagg.py::test_export_readback_overlaps_decode)"
                ),
                "fault_plane": _fault_plane_record(fault_activity0),
            }

        res, wall = await run_wave(gen, requests)
        dis_stats = stats(res, wall)
        return {
            "mode": "disaggregated P/D",
            "one_chip_timeshared": True,
            "model": "qwen2.5-0.5b",
            "isl": isl,
            "osl": osl,
            "concurrency": concurrency,
            "aggregated": agg_stats,
            "disagg": dis_stats,
            "ttft_delta_ms": round(
                dis_stats["p50_ttft_ms"] - agg_stats["p50_ttft_ms"], 1
            ),
            "itl_delta_ms": round(
                dis_stats["p50_itl_ms"] - agg_stats["p50_itl_ms"], 2
            ),
            "transfer_idle_mb_per_s": xfer_mb_s,
            "transfer_note": (
                "dev-tunnel floor: each chunk costs a device gather + "
                "scatter dispatch at ~77ms RTT through the tunnel; "
                "on-host the same path is dispatch-cheap"
            ),
            "blocks_pulled": decode_handler.blocks_pulled,
            "transfer_failures": decode_handler.transfer_failures,
            # Wire-format v2 telemetry: serialized bytes actually pulled,
            # split by wire dtype (int8 pools ship {q8, scales} ≈ 0.53x
            # the dense bf16 bytes), and the measured per-(src prefill
            # worker → this decode worker) bandwidth EWMA the router's
            # link-cost model consumes.
            "wire_bytes": decode_handler.bytes_pulled,
            "wire_bytes_by_dtype": dict(decode_handler.wire_bytes_by_dtype),
            "wire_dtype": max(
                decode_handler.wire_bytes_by_dtype,
                key=decode_handler.wire_bytes_by_dtype.get,
                default=None,
            ),
            "link_bandwidth_mb_per_s": {
                str(src): round(bw / 1e6, 1)
                for src, bw in decode_handler.link_bandwidth().items()
            },
            # Chaos-free proof: retries/breaker/migration counters must be
            # zero when no fault plan is armed (self-healing sat idle).
            "fault_plane": _fault_plane_record(fault_activity0),
            "pull_retries": decode_handler.pull_retries,
            "breaker_opens": decode_handler.breaker_opens,
            "pull_fallbacks": decode_handler.pull_fallbacks,
        }
    finally:
        for s in served:
            await s.shutdown()
        await prefill_engine.stop()
        await decode_engine.stop()
        await rt.shutdown()


async def run_overload_leg(isl: int = 64, osl: int = 32,
                           concurrency: int = 16):
    """Overload-armor measurement (ISSUE 8): an OPEN-LOOP arrival ramp
    through the admission controller, calibrated against the engine's own
    measured capacity. Two sub-legs share one engine + controller config:

      * ``under_capacity`` (0.5× the calibrated request rate) — the
        zero-spurious-activation contract: NO sheds, NO brownout
        transitions, NO deadline expiries (same contract as the PR 7
        chaos-free fault-plane check);
      * ``over_capacity`` (4× the calibrated rate) — the armor working:
        queue depth stays bounded at the configured cap, the excess sheds
        with typed reasons, deadline-carrying requests that expire
        mid-queue are shed before prefill, and every ADMITTED stream
        completes with its full output.
    """
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import qwen2_500m_config
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.overload import (
        OverloadConfig,
        OverloadController,
        OverloadShedError,
    )

    fault_activity0 = _fault_activity_start()
    cfg = qwen2_500m_config()
    engine = JaxEngine(
        JaxEngineArgs(
            config=cfg,
            block_size=64,
            num_kv_blocks=2048,
            max_num_seqs=concurrency,
            max_model_len=isl + osl + 64,
            prefill_chunk=64,
            prefill_batch=concurrency,
            decode_steps=16,
        )
    )
    rng = np.random.default_rng(11)

    def mk_req(i):
        return PreprocessedRequest(
            token_ids=rng.integers(10, cfg.vocab_size - 10, size=isl).tolist(),
            request_id=f"ovl-{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def run_one(req, ctrl=None, deadline_s=None):
        """→ ('ok', tokens) | ('shed', reason) | ('error', kind)."""
        ctx = Context(
            deadline=(time.monotonic() + deadline_s) if deadline_s else None
        )
        ticket = None
        try:
            if ctrl is not None:
                ticket = await ctrl.admit(ctx, request_id=req.request_id)
            n = 0
            async for out in engine.generate(req, ctx):
                if out.error:
                    return ("error", out.error_kind or "other")
                n += len(out.token_ids or [])
            return ("ok", n)
        except OverloadShedError as exc:
            return ("shed", exc.reason)
        finally:
            if ticket is not None:
                ctrl.release(ticket)

    try:
        # Calibrate: one closed-loop wave → sustainable requests/sec
        # (also triggers every compile so the ramp measures serving, not
        # XLA).
        await asyncio.gather(*(run_one(mk_req(10_000 + i)) for i in range(concurrency)))
        t0 = time.monotonic()
        results = await asyncio.gather(
            *(run_one(mk_req(20_000 + i)) for i in range(2 * concurrency))
        )
        calib_wall = time.monotonic() - t0
        assert all(r[0] == "ok" for r in results)
        capacity_rps = (2 * concurrency) / calib_wall

        async def ramp(rate_rps, n_requests, ctrl, deadline_s):
            tasks = []
            interval = 1.0 / rate_rps
            for i in range(n_requests):
                tasks.append(
                    asyncio.ensure_future(
                        run_one(mk_req(30_000 + i), ctrl, deadline_s)
                    )
                )
                await asyncio.sleep(interval)
            outcomes = await asyncio.gather(*tasks)
            counts: dict = {}
            for kind, detail in outcomes:
                key = kind if kind == "ok" else f"{kind}:{detail}"
                counts[key] = counts.get(key, 0) + 1
            return counts, outcomes

        def mk_ctrl():
            return OverloadController(
                OverloadConfig(
                    max_concurrency=concurrency,
                    max_queue_depth=2 * concurrency,
                    max_queue_delay_s=20.0,
                )
            )

        # Deadlines scale with MEASURED service time so the leg is about
        # the armor, not the host's speed: generous under capacity
        # (nothing may expire), ~2 service waves over capacity (the
        # queue tail expires, the admitted head completes).
        service_s = calib_wall
        # Under capacity: nothing may activate. No deadlines — the
        # zero-spurious contract must hold on any hardware.
        under_ctrl = mk_ctrl()
        under_counts, under_out = await ramp(
            capacity_rps * 0.5, 2 * concurrency, under_ctrl,
            deadline_s=None,
        )
        under_snap = under_ctrl.snapshot()

        # 4× capacity: bounded queue, typed sheds, admitted work intact.
        over_ctrl = mk_ctrl()
        over_counts, over_out = await ramp(
            capacity_rps * 4.0, 6 * concurrency, over_ctrl,
            deadline_s=max(15.0, 2.5 * service_s),
        )
        over_snap = over_ctrl.snapshot()
        ok_complete = all(
            detail == osl for kind, detail in over_out if kind == "ok"
        )
        return {
            "model": cfg.name,
            "isl": isl,
            "osl": osl,
            "concurrency": concurrency,
            "calibrated_capacity_rps": round(capacity_rps, 2),
            "under_capacity": {
                "offered_x": 0.5,
                "outcomes": under_counts,
                "sheds": sum(under_snap["sheds"].values()),
                "brownout_transitions": sum(
                    under_snap["transitions"].values()
                ),
                "deadline_expired": under_snap["deadline_expired"],
                "peak_queue_depth": under_snap["peak_queue_depth"],
                # THE contract: zero activations off the saturation path.
                "zero_spurious": (
                    not under_snap["sheds"] and not under_snap["transitions"]
                ),
            },
            "over_capacity": {
                "offered_x": 4.0,
                "outcomes": over_counts,
                "sheds_by_reason": over_snap["sheds"],
                "deadline_expired": over_snap["deadline_expired"],
                "peak_queue_depth": over_snap["peak_queue_depth"],
                "queue_bounded": (
                    over_snap["peak_queue_depth"] <= 2 * concurrency
                ),
                "admitted_streams_complete": ok_complete,
                "engine_deadline_sheds": engine.deadline_sheds,
            },
            "fault_plane": _fault_plane_record(fault_activity0),
        }
    finally:
        await engine.stop()
        import gc

        del engine
        gc.collect()


async def run_drain_leg(isl: int = 64, osl: int = 48, concurrency: int = 8):
    """Rolling-restart measurement (ISSUE 9): SIGTERM a worker mid-load and
    prove users never see it. Two in-process engines (same seed/config —
    the rolling-restart fleet invariant) serve one Migration-wrapped client
    wave; mid-wave the process SIGTERMs ITSELF, the loop signal handler
    triggers the source's DrainController, live decodes hand off to the
    peer over the wire-v2 path, and the record carries the contract:
    ``dropped_requests == 0``, handoff bytes, re-prefill tokens (only the
    fallback rung pays any), and the worst mid-stream stall a client saw.
    """
    import signal as _signal

    from dynamo_tpu.disagg import HandoffHandler
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import qwen2_500m_config
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.drain import DrainController

    fault_activity0 = _fault_activity_start()
    cfg = qwen2_500m_config()

    def mk_engine():
        return JaxEngine(
            JaxEngineArgs(
                config=cfg,
                block_size=64,
                num_kv_blocks=2048,
                max_num_seqs=concurrency,
                max_model_len=isl + osl + 64,
                prefill_chunk=64,
                prefill_batch=concurrency,
                decode_steps=8,
            )
        )

    source, peer = mk_engine(), mk_engine()

    class _LocalHandoffClient:
        """Controller-facing view of the peer's handoff endpoint."""

        def __init__(self, handlers):
            self._handlers = handlers

        @property
        def instance_ids(self):
            return sorted(self._handlers)

        def direct(self, request, instance_id, context=None):
            return self._handlers[instance_id].generate(
                request, context or Context()
            )

        async def close(self):
            pass

    handoff_client = _LocalHandoffClient({2: HandoffHandler(peer)})

    async def handoff_client_factory():
        return handoff_client

    controller = DrainController(
        source,
        worker_id=1,
        handoff_client_factory=handoff_client_factory,
        deadline_s=60.0,
    )
    loop = asyncio.get_running_loop()
    loop.add_signal_handler(_signal.SIGTERM, controller.trigger)

    class _DrainAwareClient:
        """Stands in for the KV router: places on the source until its
        draining bit flips, then on the peer — exactly what KvScheduler
        does once the draining load report lands."""

        async def generate(self, request, context):
            eng = peer if source.draining else source
            async for out in eng.generate(request, context):
                yield out

    mig = Migration(migration_limit=3)
    client = _DrainAwareClient()
    rng = np.random.default_rng(23)

    def mk_req(i):
        return PreprocessedRequest(
            token_ids=rng.integers(10, cfg.vocab_size - 10, size=isl).tolist(),
            request_id=f"drain-{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def run_one(req):
        """→ (tokens, max inter-output stall seconds, error|None)."""
        n = 0
        last = time.monotonic()
        stall = 0.0
        try:
            async for out in mig.generate(req, Context(), client):
                now = time.monotonic()
                stall = max(stall, now - last)
                last = now
                err = out.get("error") if isinstance(out, dict) else out.error
                if err:
                    return (n, stall, str(err))
                toks = (
                    out.get("token_ids") if isinstance(out, dict)
                    else out.token_ids
                )
                n += len(toks or [])
        except Exception as exc:
            return (n, stall, f"{type(exc).__name__}: {exc}")
        return (n, stall, None)

    try:
        # Warm both engines (compiles must not masquerade as drain stall).
        await asyncio.gather(
            *(collect_silent(source, mk_req(10_000 + i)) for i in range(2)),
            *(collect_silent(peer, mk_req(20_000 + i)) for i in range(2)),
        )
        reprefill0 = mig.metrics.reprefill_tokens.value()
        t0 = time.monotonic()
        tasks = [
            asyncio.ensure_future(run_one(mk_req(i)))
            for i in range(2 * concurrency)
        ]
        # Let the first wave reach steady decode, then kill the worker.
        await asyncio.sleep(1.0)
        os.kill(os.getpid(), _signal.SIGTERM)
        results = await asyncio.gather(*tasks)
        await controller.drain()  # join (SIGTERM already triggered it)
        wall = time.monotonic() - t0
        dropped = sum(1 for _n, _s, err in results if err is not None)
        short = sum(1 for n, _s, err in results if err is None and n != osl)
        status = controller.status()
        return {
            "model": cfg.name,
            "isl": isl,
            "osl": osl,
            "concurrency": concurrency,
            "streams": len(results),
            "wall_s": round(wall, 3),
            # THE contract: a planned restart drops nothing.
            "dropped_requests": dropped + short,
            "handed_off": status["handoffs"],
            "handoff_bytes": status["handoff_bytes"],
            "reprefill_fallbacks": status["reprefill_fallbacks"],
            "requeued": status["requeued"],
            # Tokens the fallback rung re-prefilled (handoffs pay ZERO).
            "reprefill_tokens": int(
                mig.metrics.reprefill_tokens.value() - reprefill0
            ),
            "max_midstream_stall_s": round(
                max((s for _n, s, _e in results), default=0.0), 3
            ),
            "drain_duration_s": status.get("duration_s"),
            "fault_plane": _fault_plane_record(fault_activity0),
        }
    finally:
        loop.remove_signal_handler(_signal.SIGTERM)
        await source.stop()
        await peer.stop()
        import gc

        del source, peer
        gc.collect()


async def run_crash_leg(isl: int = 64, osl: int = 48, concurrency: int = 8,
                        config_fn=None):
    """Crash-plane measurement (ISSUE 10): an UNPLANNED worker death
    mid-load — no drain, no handoff, the worker simply goes silent the way
    a kill -9'd process does. The liveness tracker (missed load reports)
    declares it dead, evicts it, and aborts its in-flight streams with the
    typed worker_lost error; Migration re-prefills them on the peer. The
    record carries the contract: ``lost_requests == 0``, the measured
    detection-to-abort latency (bounded by dead_after × interval, nothing
    TCP), the re-prefilled tokens the unplanned path paid (unlike drain's
    zero-re-prefill handoff), and the warm-restart numbers — checkpoint
    restore wall time + the prefill tokens a shared-prefix request costs
    on the restarted worker (near-zero = warm rejoin works)."""
    import tempfile

    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import qwen2_500m_config
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.liveness import (
        LivenessConfig,
        LivenessTracker,
        WorkerLostError,
    )

    fault_activity0 = _fault_activity_start()
    cfg = (config_fn or qwen2_500m_config)()

    def mk_engine():
        return JaxEngine(
            JaxEngineArgs(
                config=cfg,
                # Small blocks so the warm shared prefix commits several
                # cache blocks: the restore half of the record
                # (restored_blocks / warm_prefill_tokens) needs a
                # non-empty checkpoint even at small ISL.
                block_size=16,
                num_kv_blocks=2048,
                max_num_seqs=concurrency,
                max_model_len=isl + osl + 64,
                prefill_chunk=64,
                prefill_batch=concurrency,
                decode_steps=8,
            )
        )

    source, peer = mk_engine(), mk_engine()
    rt = DistributedRuntime.detached()
    ckpt_dir = tempfile.mkdtemp(prefix="bench-crash-ckpt-")

    class _Crashable:
        """Engine front that goes SILENT when killed — exactly what the
        frontend observes of a kill -9'd worker (no FIN, no error)."""

        def __init__(self, engine):
            self.engine = engine
            self.dead = asyncio.Event()

        async def generate(self, request, context):
            async for out in self.engine.generate(request, context):
                if self.dead.is_set():
                    await asyncio.Event().wait()  # never returns
                yield out

    crash_src = _Crashable(source)
    ep = rt.namespace("bench").component("backend").endpoint("generate")
    served = [
        await ep.serve_endpoint(crash_src.generate, instance_id=1),
        await ep.serve_endpoint(peer.generate, instance_id=2),
    ]
    client = await ep.client()
    await client.wait_for_instances()
    client.enable_stream_aborts()

    kill_at = [0.0]
    detection = {}

    def on_dead(wid, _inc):
        # Order matters: evict BEFORE abort so migration re-dispatches
        # land on the peer, never back on the corpse.
        client.evict_instance(wid)
        n = client.abort_instance(
            wid, WorkerLostError(f"worker {wid} dead (missed reports)")
        )
        detection["latency_s"] = time.monotonic() - kill_at[0]
        detection["aborted_streams"] = n

    tracker = LivenessTracker(
        LivenessConfig(interval_s=0.1, suspect_after=2, dead_after=4),
        on_dead=on_dead,
    )
    alive = {1: True, 2: True}

    async def liveness_loop():
        while True:
            for wid, ok in alive.items():
                if ok:
                    tracker.observe_report(wid, 1000 + wid)
            tracker.evaluate()
            await asyncio.sleep(0.05)

    liveness_task = asyncio.ensure_future(liveness_loop())

    mig = Migration(migration_limit=3)
    rng = np.random.default_rng(29)
    shared_prefix = rng.integers(10, cfg.vocab_size - 10, size=isl).tolist()

    def mk_req(i, prefix=None):
        toks = list(prefix) if prefix else rng.integers(
            10, cfg.vocab_size - 10, size=isl
        ).tolist()
        return PreprocessedRequest(
            token_ids=toks,
            request_id=f"crash-{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def run_one(req):
        n = 0
        last = time.monotonic()
        stall = 0.0
        try:
            async for out in mig.generate(req, Context(), client):
                now = time.monotonic()
                stall = max(stall, now - last)
                last = now
                err = out.get("error") if isinstance(out, dict) else out.error
                if err:
                    return (n, stall, str(err))
                toks = (
                    out.get("token_ids") if isinstance(out, dict)
                    else out.token_ids
                )
                n += len(toks or [])
        except Exception as exc:
            return (n, stall, f"{type(exc).__name__}: {exc}")
        return (n, stall, None)

    try:
        # Warm both engines; seed the source's prefix cache with the
        # shared prefix so the restart checkpoint carries something warm.
        await asyncio.gather(
            collect_silent(source, mk_req(10_000, prefix=shared_prefix)),
            collect_silent(source, mk_req(10_001)),
            collect_silent(peer, mk_req(20_000)),
            collect_silent(peer, mk_req(20_001)),
        )
        await source.save_checkpoint(ckpt_dir)

        reprefill0 = mig.metrics.reprefill_tokens.value()
        t0 = time.monotonic()
        tasks = [
            asyncio.ensure_future(run_one(mk_req(i)))
            for i in range(2 * concurrency)
        ]
        await asyncio.sleep(1.0)  # first wave mid-decode
        # kill -9: the source goes silent and its reports stop. Nothing
        # cooperative happens from here on.
        alive[1] = False
        crash_src.dead.set()
        kill_at[0] = time.monotonic()
        results = await asyncio.gather(*tasks)
        wall = time.monotonic() - t0
        lost = sum(1 for n, _s, err in results if err is not None or n != osl)

        # Warm restart: a fresh engine restores the dead worker's
        # checkpoint, then serves a shared-prefix request.
        restarted = mk_engine()
        try:
            t_r = time.monotonic()
            restored_blocks = await restarted.load_checkpoint(ckpt_dir)
            restore_ms = (time.monotonic() - t_r) * 1000
            await collect_silent(
                restarted, mk_req(30_000, prefix=shared_prefix)
            )
            warm_prefill_tokens = restarted.stats().get("prefill_tokens", 0)
        finally:
            await restarted.stop()

        return {
            "model": cfg.name,
            "isl": isl,
            "osl": osl,
            "concurrency": concurrency,
            "streams": len(results),
            "wall_s": round(wall, 3),
            # THE contract: an unplanned death loses nothing.
            "lost_requests": lost,
            "detection_ms": round(detection.get("latency_s", 0.0) * 1000, 1),
            "detection_budget_ms": int(
                tracker.config.detection_budget_s * 1000
            ),
            "aborted_streams": detection.get("aborted_streams", 0),
            "reprefill_tokens": int(
                mig.metrics.reprefill_tokens.value() - reprefill0
            ),
            "max_midstream_stall_s": round(
                max((s for _n, s, _e in results), default=0.0), 3
            ),
            "restore_ms": round(restore_ms, 1),
            "restored_blocks": restored_blocks,
            "warm_prefill_tokens": int(warm_prefill_tokens),
            "fault_plane": _fault_plane_record(fault_activity0),
        }
    finally:
        liveness_task.cancel()
        from dynamo_tpu.runtime.tasks import reap_task

        await reap_task(liveness_task, "bench liveness loop")
        for s in served:
            await s.shutdown(grace_period=1)
        await rt.shutdown(grace_period=1)
        await source.stop()
        await peer.stop()
        import gc

        del source, peer
        gc.collect()


async def run_elasticity_leg(seed: int = 29):
    """Elasticity-loop measurement (ISSUE 13), sim-clocked
    (planner/simfleet.py — the REAL KvScheduler + LivenessTracker +
    Planner + ElasticController around simulated workers, so the leg is
    pure CPU arithmetic and lands on any backend):

      * ramp 1× → 4× → 1× open-loop load: adjustment intervals from each
        rate shift until desired == ready (convergence), both directions;
      * scale-down cost: drain-attributed re-prefilled tokens (the
        zero-re-prefill handoff contract — must be 0) + zero lost
        streams token-exact over the whole ramp;
      * per-request ``select_worker`` cost at 10 vs 100 workers, wall
        time AND candidates actually scored (the pruned-candidate path's
        sub-linear-growth contract).
    """
    from dynamo_tpu.planner import (
        ElasticConfig,
        ElasticController,
        Planner,
        PlannerConfig,
        SimConfig,
        SimFleet,
        profile_interpolators,
    )
    from dynamo_tpu.router.protocols import LoadSnapshot
    from dynamo_tpu.router.scheduler import KvScheduler
    from dynamo_tpu.tokens.radix import OverlapScores

    fault_activity0 = _fault_activity_start()
    cfg = SimConfig(seed=seed, worker_max_conc=4, base_itl_s=0.02,
                    base_ttft_s=0.1, isl=128, osl=32, launch_delay_s=0.6)
    base_rate = 30.0  # ≈ 5 SLA-sized workers; 4× ≈ 19
    shifts = (15.0, 35.0)

    def rate(t):
        if t < shifts[0]:
            return base_rate
        if t < shifts[1]:
            return base_rate * 4
        if t < 55.0:
            return base_rate
        return 0.0

    fleet = SimFleet(cfg, n_workers=5, rate_fn=rate)
    ctl = ElasticController(
        fleet,
        config=ElasticConfig(scale_up_after=1, scale_down_after=3,
                             cooldown_intervals=1,
                             actuation_deadline_s=20.0),
    )
    planner = Planner(
        PlannerConfig(adjustment_interval_s=1.0,
                      itl_target_s=cfg.base_itl_s * 2, ttft_target_s=2.0,
                      min_replicas=2, max_replicas=64,
                      total_chip_budget=128),
        *profile_interpolators(cfg),
        ctl, fleet.metrics_source, disagg=False, metrics=ctl.metrics,
    )
    timeline = []
    for _ in range(58):
        fleet.run(1.0)
        plan = await planner.step()
        timeline.append(
            (fleet.now, plan.decode if plan else None,
             fleet.ready_count("decode"))
        )
    fleet.settle(180.0)
    problems = fleet.verify_streams()

    def convergence_intervals(shift_t):
        """Intervals from the rate shift until desired == ready and it
        STAYS matched through the next 3 intervals (or the window end)."""
        idxs = [i for i, (t, _w, _h) in enumerate(timeline) if t > shift_t]
        for n, i in enumerate(idxs):
            window = timeline[i:i + 3]
            if all(w is not None and w == h for _t, w, h in window):
                return n + 1
        return None

    def probe(n_workers, requests=2000):
        sched = KvScheduler(seed=seed)
        for wid in range(1, n_workers + 1):
            sched.update_load(LoadSnapshot(
                worker_id=wid, active_blocks=(wid % 37) * 5,
                total_blocks=4096,
            ))
        cands = [(wid, 0) for wid in range(1, n_workers + 1)]
        t0 = time.perf_counter()
        for _ in range(requests):
            sched.select_worker(9, OverlapScores(), cands)
        wall = time.perf_counter() - t0
        return {
            "workers": n_workers,
            "us_per_request": round(wall / requests * 1e6, 2),
            "candidates_scored_per_request": round(
                sched.logit_evals / sched.selections, 2
            ),
        }

    small, large = probe(10), probe(100)
    return {
        "sim_seed": seed,
        "arrivals": fleet.arrivals,
        "lost_streams": len(problems),
        "convergence_intervals_up": convergence_intervals(shifts[0]),
        "convergence_intervals_down": convergence_intervals(shifts[1]),
        "peak_workers": max(h for _t, _w, h in timeline),
        "scale_ups": ctl.scale_ups,
        "scale_downs": ctl.scale_downs,
        "holds": ctl.holds,
        "workers_drained": len(ctl.drained_workers),
        "handoff_streams": fleet.handoff_streams,
        # THE elasticity contract: scaling down re-prefills NOTHING.
        "scale_down_reprefill_tokens": fleet.drain_reprefill_tokens,
        "reprefill_tokens_total": fleet.reprefill_tokens,
        "liveness_false_positives": len(fleet.false_positive_deaths),
        "correction_factor_itl": round(planner.feedback_itl.value, 3),
        "select_worker_cost": {"small": small, "large": large},
        "fault_plane": _fault_plane_record(fault_activity0),
    }


async def run_tool_call_leg(n_deltas: int = 48, delta_sleep_s: float = 0.002,
                            seed: int = 17):
    """Tool-call streaming leg (ISSUE 15), pure CPU — a scripted pipeline
    behind the REAL HttpService + incremental jail, so the leg lands on
    any backend:

      * time-to-first-tool-call-byte: one hermes call whose arguments
        span ``n_deltas`` paced deltas. Measured at the SSE wire: wall
        time to the first chunk carrying tool_calls argument bytes
        (incremental jail, O(delta)) vs wall time to stream end — the
        EARLIEST the old buffer-to-flush jail could have emitted the
        call (O(call length)). The ratio is the headline.
      * malformed recovery: seeded truncated/broken calls across the
        marker dialects — every stream must complete ([DONE] reached,
        degraded content or sealed call), zero dropped; plus one
        fault-armed stream proving the typed terminal error frame
        (error_kind=tool_call_parse).

    The clean sub-leg's fault_plane record extends the zero-spurious
    contract: parser_degraded / parser_exceptions must be ZERO there.
    """
    import random

    import aiohttp

    from dynamo_tpu.http import HttpService, ModelManager
    from dynamo_tpu.llm import ModelDeploymentCard
    from dynamo_tpu.llm.protocols.common import (
        FinishReason,
        PostprocessedOutput,
    )
    from dynamo_tpu.parsers.observe import parser_plane
    from dynamo_tpu.runtime import fault_names as fn
    from dynamo_tpu.runtime.faults import FaultPlan, armed

    fault_activity0 = _fault_activity_start()

    class PacedPipeline:
        def __init__(self, deltas, pace_s=0.0):
            self.deltas, self.pace_s = deltas, pace_s

        async def generate(self, request, context):
            yield {"annotation": "_prompt_tokens", "value": 3}
            for i, text in enumerate(self.deltas):
                if self.pace_s:
                    await asyncio.sleep(self.pace_s)
                yield PostprocessedOutput(
                    text=text, token_ids=[i], cumulative_tokens=i + 1,
                    finish_reason=(
                        FinishReason.EOS
                        if i == len(self.deltas) - 1 else None
                    ),
                )

    async def serve(deltas, pace_s=0.0):
        manager = ModelManager()
        manager.register(
            "bench-tools", PacedPipeline(deltas, pace_s),
            ModelDeploymentCard(name="bench-tools", context_length=512),
        )
        service = HttpService(manager, host="127.0.0.1", port=0)
        port = await service.start()
        return service, port

    async def stream_once(port, collect_first_args=True):
        t0 = time.perf_counter()
        first_args_t = None
        saw_done = False
        error_frame = None
        n_args_chunks = 0
        content_chars = 0
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json={
                    "model": "bench-tools",
                    "messages": [{"role": "user", "content": "x"}],
                    "tools": [{"type": "function",
                               "function": {"name": "f"}}],
                    "stream": True,
                },
            )
            async for line in r.content:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                if line == "data: [DONE]":
                    saw_done = True
                    continue
                payload = json.loads(line[6:])
                if "error" in payload:
                    error_frame = payload["error"]
                    continue
                delta = payload["choices"][0]["delta"]
                content_chars += len(delta.get("content", ""))
                for entry in delta.get("tool_calls", []):
                    if (entry.get("function") or {}).get("arguments"):
                        n_args_chunks += 1
                        if first_args_t is None:
                            first_args_t = time.perf_counter() - t0
        return {
            "first_args_s": first_args_t,
            "end_s": time.perf_counter() - t0,
            "saw_done": saw_done,
            "error_frame": error_frame,
            "args_chunks": n_args_chunks,
            "content_chars": content_chars,
        }

    # -- sub-leg 1: time-to-first-tool-call-byte ---------------------------
    args_body = ", ".join(f'"k{i}": {i}' for i in range(n_deltas))
    call_text = (
        '<tool_call>{"name": "f", "arguments": {' + args_body
        + '}}</tool_call>'
    )
    step = max(1, len(call_text) // n_deltas)
    deltas = [call_text[i:i + step] for i in range(0, len(call_text), step)]
    service, port = await serve(deltas, pace_s=delta_sleep_s)
    try:
        clean = await stream_once(port)
    finally:
        await service.stop(grace_period=1)
    assert clean["saw_done"] and clean["error_frame"] is None
    # The zero-spurious record is cut HERE: the clean sub-leg must show
    # zero parser-plane activations.
    clean_fault_record = _fault_plane_record(fault_activity0)

    # -- sub-leg 2: malformed recovery -------------------------------------
    malformed = [
        '<tool_call>{"name": "f", "arguments": {"a": [1, 2',
        '<tool_call>{"name": "f", "arguments": {"a": 1]]}',
        '[TOOL_CALLS]{"name": "f", "argu',
        '<｜DSML｜function_calls><｜DSML｜invoke name="x">'
        '<｜DSML｜parameter name="k" string="true">v',
        '<|channel|>commentary to=functions.f <|message|>{"a": ',
        '<tool_call><function=f><parameter=k>v',
    ]
    rng = random.Random(seed)
    completed = 0
    degrades_before = sum(parser_plane().degrades.values())
    for text in malformed:
        n = rng.randint(1, min(6, len(text) - 1))
        cuts = sorted(rng.sample(range(1, len(text)), n))
        parts, last = [], 0
        for c in cuts:
            parts.append(text[last:c])
            last = c
        parts.append(text[last:])
        service, port = await serve(parts)
        try:
            res = await stream_once(port)
        finally:
            await service.stop(grace_period=1)
        if res["saw_done"] and res["error_frame"] is None:
            completed += 1
    # MEASURED ladder activations (the parser plane's counters), not an
    # assumption — a regression that silently passed malformed text
    # through would read degraded < streams here.
    degraded = sum(parser_plane().degrades.values()) - degrades_before

    # -- sub-leg 3: injected parser death → typed frame --------------------
    service, port = await serve(["safe ", '<tool_call>{"name": "f"'])
    plan = FaultPlan.from_dict({
        "seed": seed,
        "rules": [{"point": fn.PARSER_JAIL_FEED, "kind": "error",
                   "at": [2]}],
    })
    try:
        with armed(plan):
            res = await stream_once(port)
    finally:
        await service.stop(grace_period=1)
    typed_frame_ok = (
        res["error_frame"] is not None
        and res["error_frame"].get("error_kind") == "tool_call_parse"
    )

    plane = parser_plane()
    return {
        # O(delta) vs O(call length): first argument byte vs stream end.
        "ttfcb_ms": round(clean["first_args_s"] * 1e3, 2),
        "stream_end_ms": round(clean["end_s"] * 1e3, 2),
        "ttfcb_speedup_vs_flush_jail": round(
            clean["end_s"] / max(clean["first_args_s"], 1e-9), 2
        ),
        "args_chunks_streamed": clean["args_chunks"],
        "call_deltas": len(deltas),
        "malformed_streams": len(malformed),
        "malformed_completed": completed,
        "malformed_dropped": len(malformed) - completed,
        "malformed_degraded": degraded,
        "parse_error_frame_typed": typed_frame_ok,
        "parser_plane": plane.snapshot(),
        # Zero-spurious contract (clean sub-leg only): parser_degraded
        # and parser_exceptions must both read 0 here.
        "fault_plane": clean_fault_record,
    }


async def run_kv_reuse_leg(n_prefixes: int = 6, requests: int = 36,
                           isl: int = 96, osl: int = 8, seed: int = 23):
    """KV-reuse leg (ISSUE 16): a tiny REAL engine (prefix caching on)
    under a shared-prefix traffic mix vs a cold-cache control — lands on
    any backend:

      * hit rate by tier + reused/recomputed prefill tokens + priced
        prefill-seconds-saved, read from the KV-reuse plane's counters
        (the same numbers /debug/kvcache serves);
      * p50 TTFT delta: shared-prefix wave vs the control wave of
        distinct random prompts (the cache's actual latency win);
      * top-prefix coherence: the sketch's hot anchors must cover the
        shared prefixes the leg just replayed.
    """
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import tiny_config
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.kv_reuse_observe import global_plane

    fault_activity0 = _fault_activity_start()
    block_size = 8
    rng = np.random.default_rng(seed)
    # Prefix length is a whole number of blocks so the replayed prefix
    # is fully matchable; the 2-block suffix keeps every request distinct.
    prefix_len = (isl - 2 * block_size) // block_size * block_size
    prefixes = [
        rng.integers(10, 200, size=prefix_len).tolist()
        for _ in range(n_prefixes)
    ]

    async def sub_leg(shared: bool) -> dict:
        engine = JaxEngine(
            JaxEngineArgs(
                config=tiny_config(),
                block_size=block_size,
                num_kv_blocks=1024,
                max_num_seqs=8,
                max_model_len=isl + osl + 2 * block_size,
                prefill_chunk=32,
                enable_prefix_caching=True,
                decode_steps=4,
            )
        )
        before = _kv_reuse_start()
        ttfts: list = []

        async def run_one(i: int) -> None:
            if shared:
                toks = (
                    prefixes[i % n_prefixes]
                    + rng.integers(10, 200, size=isl - prefix_len).tolist()
                )
            else:
                toks = rng.integers(10, 200, size=isl).tolist()
            request = PreprocessedRequest(
                token_ids=toks,
                request_id=f"kvreuse-{'warm' if shared else 'cold'}-{i}",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            t0 = time.monotonic()
            ttft = None
            async for out in engine.generate(request, Context()):
                if out.token_ids and ttft is None:
                    ttft = time.monotonic() - t0
            if ttft is not None:
                ttfts.append(ttft)

        sem = asyncio.Semaphore(4)

        async def limited(i: int) -> None:
            async with sem:
                await run_one(i)

        if shared:
            # Prime wave: first touch of each prefix is the unavoidable
            # cold miss — measured TTFTs start after it.
            await asyncio.gather(*(limited(i) for i in range(n_prefixes)))
            ttfts.clear()
        await asyncio.gather(
            *(limited(n_prefixes + i) for i in range(requests))
        )
        await engine.stop()
        record = _kv_reuse_record(before)
        record["p50_ttft_ms"] = round(
            1000 * sorted(ttfts)[len(ttfts) // 2], 2
        )
        return record

    async def tier_sub_leg() -> dict:
        """Speculative-vs-serialized onboard (ISSUE 17): prime the host
        tier, drop the device cache so every warm request must walk
        G2→G1, then replay the warm wave twice — once hintless (admission
        onboards serially, the pre-17 critical path) and once with the
        router hint stamped (the walk overlaps the queue wait). Columns:
        the TTFT pair, prefetch_hits/prefetch_wasted, and the measured
        onboard_overlap_ms the speculation bought. The serialized wave
        doubles as the zero-spurious control: no hint, no prefetch.

        max_num_seqs is deliberately small: the speculation's win IS the
        queue wait it overlaps — with no queue, both waves pay the same
        walk and the hint buys nothing."""
        from dynamo_tpu.kvbm import HostTier, TieredKvManager

        engine = JaxEngine(
            JaxEngineArgs(
                config=tiny_config(),
                block_size=block_size,
                num_kv_blocks=1024,
                max_num_seqs=2,
                max_model_len=isl + osl + 2 * block_size,
                prefill_chunk=32,
                enable_prefix_caching=True,
                decode_steps=4,
            )
        )
        kvbm = TieredKvManager(HostTier(4096))
        kvbm.attach(engine)

        def pv(outcome: str) -> int:
            return int(kvbm.metrics.prefetches.value(outcome=outcome))

        async def wave(tag: str, hint: bool) -> float:
            ttfts: list = []

            async def run_one(i: int) -> None:
                toks = (
                    prefixes[i % n_prefixes]
                    + rng.integers(10, 200, size=isl - prefix_len).tolist()
                )
                request = PreprocessedRequest(
                    token_ids=toks,
                    request_id=f"kvtier-{tag}-{i}",
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=osl, ignore_eos=True),
                )
                if hint:
                    request.estimated_prefix_hit_blocks = (
                        prefix_len // block_size
                    )
                t0 = time.monotonic()
                ttft = None
                async for out in engine.generate(request, Context()):
                    if out.token_ids and ttft is None:
                        ttft = time.monotonic() - t0
                if ttft is not None:
                    ttfts.append(ttft)

            # More offered concurrency than engine slots: requests QUEUE,
            # which is exactly the window speculation overlaps.
            sem = asyncio.Semaphore(8)

            async def limited(i: int) -> None:
                async with sem:
                    await run_one(i)

            await asyncio.gather(*(limited(i) for i in range(requests)))
            return round(1000 * sorted(ttfts)[len(ttfts) // 2], 2)

        try:
            # Prime: one pass commits every prefix; write-through offload
            # lands the blocks in the host tier.
            await wave("prime", hint=False)
            await asyncio.sleep(0.3)
            spurious = sum(
                pv(o) for o in ("claimed", "revoked", "skipped", "error")
            )
            engine.pool.clear()  # blocks now live ONLY in the tier
            serialized_ms = await wave("serial", hint=False)
            spurious += sum(
                pv(o) for o in ("claimed", "revoked", "skipped", "error")
            )
            engine.pool.clear()
            speculative_ms = await wave("spec", hint=True)
            n_overlap, overlap_s = kvbm.metrics.prefetch_overlap.snapshot_total()
            return {
                "tier_blocks": len(kvbm.tier),
                "p50_ttft_ms_serialized": serialized_ms,
                "p50_ttft_ms_speculative": speculative_ms,
                "speculative_ttft_delta_ms": round(
                    serialized_ms - speculative_ms, 2
                ),
                "prefetch_hits": pv("claimed"),
                "prefetch_wasted": int(
                    kvbm.metrics.prefetch_blocks.value(outcome="wasted")
                ),
                "onboard_overlap_ms": round(1000 * overlap_s, 2),
                "onboard_overlap_count": int(n_overlap),
                # Hintless traffic must never speculate: nonzero here is
                # the prefetch plane activating spuriously.
                "spurious_prefetches": int(spurious),
            }
        finally:
            await kvbm.close()
            await engine.stop()

    def eviction_ab_sub_leg(capacity: int = 64, n_keys: int = 256,
                            draws: int = 4000) -> dict:
        """Popularity-vs-LRU eviction A/B at equal capacity: the same
        zipf-skewed single-block stream against a plain-LRU host tier and
        against one scored by the REAL manager bridge (sketch → protected
        prefixes). The popularity side must hold the heavy hitters
        through cold-key bursts LRU lets evict them."""
        from dynamo_tpu.kvbm import HostTier, OffloadFilter, TieredKvManager
        from dynamo_tpu.runtime.kv_reuse_observe import KvReusePlane

        ab_rng = np.random.default_rng(seed + 1)
        ranks = np.minimum(ab_rng.zipf(1.2, size=draws), n_keys) - 1
        keys = (
            (np.arange(1, n_keys + 1, dtype=np.uint64)
             * np.uint64(0x9E3779B97F4A7C15))
            & np.uint64(0x7FFFFFFFFFFFFFFF)
        ).astype(np.int64)
        payload = np.zeros(1, dtype=np.int8)

        def run(policy: str) -> float:
            host = HostTier(capacity)
            plane = KvReusePlane(capacity=n_keys)
            kvbm = None
            if policy == "popularity":
                kvbm = TieredKvManager(
                    host, plane=plane,
                    filter=OffloadFilter(min_frequency=10**9),
                )
            hits = 0
            for j, r in enumerate(ranks):
                h = int(keys[r])
                if j == draws // 2:
                    # Let the protected-map rebuild throttle expire so
                    # the second half runs with a sketch-warmed scorer.
                    time.sleep(0.55)
                if host.contains(h):
                    hits += 1
                    host.get(h)
                    plane.sketch.touch(h, tokens=block_size)
                else:
                    host.put(h, payload, payload)
                    if kvbm is not None:
                        kvbm.notify_commit(h, 1)
            if kvbm is not None:
                for name in list(kvbm.metrics._tier_sources):
                    kvbm.metrics.unwatch_tier(name)
                plane.forget_tier_source(kvbm._plane_label)
            return hits / draws

        lru_rate = run("lru")
        pop_rate = run("popularity")
        return {
            "capacity_blocks": capacity,
            "distinct_keys": n_keys,
            "draws": draws,
            "hit_rate_lru": round(lru_rate, 4),
            "hit_rate_popularity": round(pop_rate, 4),
            "popularity_wins": bool(pop_rate > lru_rate),
        }

    warm = await sub_leg(shared=True)
    cold = await sub_leg(shared=False)
    tier = await tier_sub_leg()
    eviction_ab = eviction_ab_sub_leg()
    top = global_plane().sketch.top(n_prefixes)
    return {
        "n_prefixes": n_prefixes,
        "requests_per_sub_leg": requests,
        "isl": isl,
        "osl": osl,
        "hit_rate": warm["hit_rate"],
        "hit_rate_by_tier": warm["hit_rate_by_tier"],
        "prefill_tokens_saved": warm["tokens_saved"],
        "prefill_seconds_saved": warm["prefill_seconds_saved"],
        "p50_ttft_ms_warm": warm["p50_ttft_ms"],
        "p50_ttft_ms_cold": cold["p50_ttft_ms"],
        "ttft_delta_ms": round(
            cold["p50_ttft_ms"] - warm["p50_ttft_ms"], 2
        ),
        "cold_control": cold,
        "tier_onboard": tier,
        "eviction_ab": eviction_ab,
        "top_prefixes_tracked": len(top),
        "fault_plane": _fault_plane_record(fault_activity0),
    }


async def run_tick_budget_leg(decode_streams: int = 4, decode_isl: int = 64,
                              decode_osl: int = 512, wave_n: int = 3,
                              wave_isl: int = 2048, wave_osl: int = 16,
                              seed: int = 31):
    """Tick-budgeter leg (ISSUE 18): a prefill-heavy wave (ISL-2048) lands
    on a steady decode population (OSL-512) inside ONE tiny real engine —
    lands on any backend:

      * aggregated mode (budgeter off): each admission prefills to
        COMPLETION inside its tick, so the wave stalls every decode
        stream for the full multi-thousand-token prefill — p99 ITL blows
        through the SLA band;
      * budgeted mode (TickBudgeter on): per-tick prefill is capped at
        the live budget, the parked remainder resumes next tick behind a
        decode burst — p99 ITL holds inside the band at ≥0.9× aggregated
        throughput (the wave finishes a few ticks later; no work is
        dropped).

    The SLA band is derived from the leg's own measurements — steady
    p50 plus one prefill chunk-round stall amortized over a decode
    burst, ×3 slack — so the contract is about interleaving, not host
    speed: the band is the structural floor any intra-chip interleaver
    pays (one possibly-overdrawn round per tick), which budgeted mode
    holds and prefill-to-completion blows through by orders of
    magnitude.
    """
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import tiny_config
    from dynamo_tpu.runtime.context import Context

    fault_activity0 = _fault_activity_start()
    cfg = tiny_config()
    rng = np.random.default_rng(seed)
    decode_prompts = [
        rng.integers(10, 200, size=decode_isl).tolist()
        for _ in range(decode_streams)
    ]
    wave_prompts = [
        rng.integers(10, 200, size=wave_isl).tolist() for _ in range(wave_n)
    ]
    # Warmup-only long prompt: distinct tokens (the measured wave must not
    # ride the prefix cache) but the same SHAPE class — decoding at wave
    # context length compiles the wide block-table-bucket decode program
    # outside the measured window.
    warm_prompt = rng.integers(10, 200, size=wave_isl).tolist()

    def mk_args(**over):
        base = dict(
            config=cfg,
            block_size=16,
            num_kv_blocks=1024,
            max_num_seqs=decode_streams + wave_n,
            max_model_len=wave_isl + decode_osl + 64,
            prefill_chunk=64,
            prefill_batch=2,
            decode_steps=8,
        )
        base.update(over)
        return JaxEngineArgs(**base)

    # Prompts sized to a full prefill round (prefill_batch × chunk
    # rows' worth of tokens) — timed on the warmed aggregated engine to
    # calibrate the SLA band's chunk-round term. Distinct prompts per
    # pass so the second can't ride the prefix cache.
    calib_prompts = [
        rng.integers(10, 200, size=2 * 64).tolist() for _ in range(2)
    ]

    async def sub_leg(args, sla_s=None, calibrate=False):
        """One mixed-traffic pass → (itl samples, stats, wall, tokens).

        ITL samples are (t, seconds/token) reap-gap measurements taken
        client-side on the DECODE population only; the wave's streams
        contribute load, not samples."""
        engine = JaxEngine(args)
        samples: list = []  # (monotonic t, per-token gap s)
        total_tokens = [0]

        async def decode_one(i):
            req = PreprocessedRequest(
                token_ids=decode_prompts[i],
                request_id=f"tb-decode-{i}",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=decode_osl, ignore_eos=True),
            )
            last = None
            async for out in engine.generate(req, Context()):
                n = len(out.token_ids or [])
                now = time.monotonic()
                if n and last is not None:
                    samples.append((now, (now - last) / n))
                if n:
                    last = now
                    total_tokens[0] += n

        async def wave_one(i):
            req = PreprocessedRequest(
                token_ids=wave_prompts[i],
                request_id=f"tb-wave-{i}",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=wave_osl, ignore_eos=True),
            )
            async for out in engine.generate(req, Context()):
                total_tokens[0] += len(out.token_ids or [])

        try:
            # Warmup: trigger the compiles outside the measured window —
            # the decode-population shapes AND a wave-length stream (its
            # 2048-token context decodes in a wider block-table bucket;
            # without this the first wave join pays that compile inside
            # the measured wave, in both modes).
            warm_req = PreprocessedRequest(
                token_ids=warm_prompt,
                request_id="tb-warm-wave",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=8, ignore_eos=True),
            )

            async def warm_wave():
                async for _ in engine.generate(warm_req, Context()):
                    pass

            await asyncio.gather(decode_one(0), warm_wave())
            samples.clear()
            total_tokens[0] = 0
            t0 = time.monotonic()
            decoders = [
                asyncio.ensure_future(decode_one(i))
                for i in range(decode_streams)
            ]
            # Let the population reach steady state, then land the wave.
            await asyncio.sleep(0.0)
            while not samples:
                await asyncio.sleep(0.01)
            steady_until = time.monotonic() + 0.25
            while time.monotonic() < steady_until:
                await asyncio.sleep(0.01)
            wave_at = time.monotonic()
            await asyncio.gather(
                *(wave_one(i) for i in range(wave_n)), *decoders
            )
            wall = time.monotonic() - t0
            round_s = 0.0
            if calibrate:
                # Time one round-sized prefill on the warmed, now-idle
                # engine: the per-tick stall an interleaver cannot avoid.
                # Two passes — the first absorbs any compile this exact
                # ragged shape still owes; the second is the number.
                for attempt in range(2):
                    creq = PreprocessedRequest(
                        token_ids=calib_prompts[attempt],
                        request_id=f"tb-calib-{attempt}",
                        sampling=SamplingOptions(temperature=0.0),
                        stop=StopConditions(max_tokens=1, ignore_eos=True),
                    )
                    c0 = time.monotonic()
                    async for _ in engine.generate(creq, Context()):
                        pass
                    round_s = time.monotonic() - c0
            stats = engine.stats()
            return {
                "round_s": round_s,
                "steady": [s for t, s in samples if t < wave_at],
                "wave": [s for t, s in samples if t >= wave_at],
                "wall_s": wall,
                "tokens": total_tokens[0],
                "prefill_budget_tokens": stats.get(
                    "prefill_budget_tokens", 0
                ),
                "budget_state": stats.get("budget_state", 0),
                "budget_rollovers": stats.get("budget_rollovers", 0),
            }
        finally:
            await engine.stop()

    def pct(vals, q):
        if not vals:
            return 0.0
        vals = sorted(vals)
        return vals[min(int(q * len(vals)), len(vals) - 1)]

    # Aggregated control first: its pre-wave steady phase + a calibrated
    # chunk-round cost define the SLA band both modes are judged against.
    # Band = 3 × (steady p99 + one chunk-round stall). Steady p99 (not
    # p50) folds the host's scheduling-noise floor into the baseline — a
    # p99-vs-p99 contract. The round term is the structural ITL floor of
    # ANY intra-chip interleaver (the budget check runs before each
    # round, so one round may overdraw); it enters un-amortized as grace
    # for the under-load overheads an idle-engine calibration can't see,
    # and is still ~wave_isl/round ≈ 50× below the prefill-to-completion
    # stall, so the aggregated breach stays structural. Host-speed
    # independent: a slower host inflates both terms and the measured
    # gaps together.
    agg = await sub_leg(mk_args(), calibrate=True)
    sla_s = 3.0 * (pct(agg["steady"], 0.99) + agg["round_s"])
    bud = await sub_leg(
        mk_args(
            tick_budget_enabled=True,
            # Strict-ITL posture: start at the floor and let proven
            # headroom earn budget back, with the ceiling sized so even a
            # fully-grown budget admits at most ONE [prefill_batch, chunk]
            # round per tick — the budgeted run sits inside the band by
            # construction, not by racing the control loop. The AIMD
            # shrink path itself is proven by tests/test_tick_budget.py;
            # this leg's contract is the interleave.
            tick_budget_floor_tokens=64,
            tick_budget_ceiling_tokens=128,
            tick_budget_policy=0.0,
            tick_budget_itl_slo_s=sla_s,
        ),
        sla_s=sla_s,
    )
    agg_p99 = pct(agg["wave"], 0.99)
    bud_p99 = pct(bud["wave"], 0.99)
    agg_tps = agg["tokens"] / agg["wall_s"]
    bud_tps = bud["tokens"] / bud["wall_s"]
    ratio = bud_tps / agg_tps if agg_tps > 0 else 0.0
    return {
        "decode_streams": decode_streams,
        "decode_osl": decode_osl,
        "wave_n": wave_n,
        "wave_isl": wave_isl,
        "sla_itl_ms": round(1000 * sla_s, 3),
        "calib_round_ms": round(1000 * agg["round_s"], 3),
        "aggregated": {
            "p99_itl_ms": round(1000 * agg_p99, 3),
            "toks_per_s": round(agg_tps, 1),
            "itl_samples": len(agg["wave"]),
        },
        "budgeted": {
            "p99_itl_ms": round(1000 * bud_p99, 3),
            "toks_per_s": round(bud_tps, 1),
            "itl_samples": len(bud["wave"]),
            "prefill_budget_tokens": bud["prefill_budget_tokens"],
            "budget_state": bud["budget_state"],
            "budget_rollovers": bud["budget_rollovers"],
        },
        # THE contract: the budgeter holds the band the aggregated mode
        # blows through, at ≥0.9× the aggregated throughput.
        "sla_held": bool(bud_p99 <= sla_s),
        "aggregated_breached": bool(agg_p99 > sla_s),
        "throughput_ratio": round(ratio, 3),
        "throughput_ratio_ok": bool(ratio >= 0.9),
        "fault_plane": _fault_plane_record(fault_activity0),
    }


# v5e inter-chip ICI: public spec is 400 Gbps/chip each direction
# (~50 GB/s); 45 GB/s effective grants the usual ~90% achieved link rate.
# Used ONLY by the 70B tp8 projection's collective term (one chip cannot
# measure an 8-chip ring; every other projection input is measured).
V5E_ICI_BW = 45e9


def run_70b_projection_leg(batch: int = 64, ctx_tokens: int = 640,
                           tp: int = 8, block_size: int = 16):
    """Modeled Llama-3-70B tp8 decode projection (ROADMAP item 1: the
    v5e-64 north star finally gets a number attached). The model is

        step_s = L × per_layer_s  +  L × comms_s
        tok/s  = batch / step_s   (÷ tp for the per-chip figure)

    where ``per_layer_s`` is MEASURED on this chip by running the fused
    decode megakernel at the exact per-chip tp8 shard shape (d=8192
    activations resident, heads/kv-heads/d_ff divided by tp → H=8, KH=1,
    d_ff=3584, int8 weights ≈ 107 MB/layer, 80 layers ≈ 8.6 GB/chip) over
    a ``ctx_tokens`` history, and ``comms_s`` is the per-layer pair of
    tensor-parallel all-reduces ([batch, d] bf16 after o-proj and after
    down-proj) on the v5e ICI ring: 2 × 2(tp−1)/tp × bytes / ICI_BW —
    the one term a single tunneled chip cannot measure, taken from the
    public link rate and recorded next to the measured inputs.

    Off-TPU the per-layer time falls back to this chip-class's HBM
    roofline at the same shard shape (weights + KV bytes / 819 GB/s,
    flagged ``measured: false``) so the projection ALWAYS lands with its
    inputs recorded; the surrounding skipped-exit-0 contract is untouched.
    """
    import jax.numpy as jnp

    from dynamo_tpu.models.config import ModelConfig, llama3_70b_config
    from dynamo_tpu.ops.pallas.fused_layer import supports_reason

    full = llama3_70b_config()
    shard = ModelConfig(
        name="llama-3-70b-tp8-shard",
        vocab_size=1024,  # irrelevant to the per-layer measurement
        d_model=full.d_model,
        n_layers=1,
        n_heads=full.n_heads // tp,
        n_kv_heads=max(full.n_kv_heads // tp, 1),
        head_dim=full.head_dim_,
        d_ff=full.d_ff // tp,
        rope_theta=full.rope_theta,
        dtype=jnp.bfloat16,
    )
    assert supports_reason(shard, lora=False, quantized_weights=True) is None

    D = shard.head_dim_
    HD = shard.n_heads * D
    KHD = shard.n_kv_heads * D
    wbytes_layer = (
        shard.d_model * HD + 2 * shard.d_model * KHD + HD * shard.d_model
        + 3 * shard.d_model * shard.d_ff
    )  # int8 = 1 byte/param
    kv_bytes_layer = batch * ctx_tokens * shard.n_kv_heads * D * 2 * 2
    pages = ctx_tokens // block_size

    try:
        measured = jax.default_backend() == "tpu"
    except Exception:
        # Backend init failed (tunnel down): the modeled path below is
        # pure arithmetic and still produces the projection record.
        measured = False
    if measured:
        from dynamo_tpu.models.quantize import init_quantized_params
        from dynamo_tpu.ops.pallas.fused_layer import fused_decoder_layer
        from dynamo_tpu.ops.rope import rope_table

        params = init_quantized_params(shard, 0)
        lp = jax.tree.map(lambda a: a[0], params["layers"])
        NB = batch * pages + 8
        k_pool = jnp.zeros((NB, block_size, shard.n_kv_heads, D), jnp.bfloat16)
        v_pool = jnp.zeros_like(k_pool)
        tables = jnp.asarray(
            (np.arange(batch * pages, dtype=np.int32) % NB).reshape(
                batch, pages
            )
        )
        start_pos = jnp.full((batch,), ctx_tokens - 1, jnp.int32)
        cos, sin = rope_table(start_pos[:, None], D, shard.rope_theta)
        x = jnp.zeros((batch, shard.d_model), jnp.bfloat16)

        def run():
            return fused_decoder_layer(
                x, cos[:, 0], sin[:, 0], lp, k_pool, v_pool, tables,
                start_pos, eps=shard.rms_norm_eps, sm_scale=D**-0.5,
                batch_block=4,
            )

        jax.block_until_ready(run())  # compile
        n = 30
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = run()
        jax.block_until_ready(out)
        per_layer_s = (time.perf_counter() - t0) / n
    else:
        # Roofline fallback at the same shard shape: the decode step is
        # weight+KV bandwidth bound on this class of chip.
        per_layer_s = (wbytes_layer + kv_bytes_layer) / V5E_BW

    # Two per-layer TP all-reduces of the [batch, d] bf16 activations.
    ar_bytes = batch * shard.d_model * 2
    comms_s_layer = 2 * (2 * (tp - 1) / tp) * ar_bytes / V5E_ICI_BW
    L = full.n_layers
    step_s = L * (per_layer_s + comms_s_layer)
    toks_per_sec = batch / step_s
    return {
        "model": full.name,
        "tp": tp,
        "batch": batch,
        "ctx_tokens": ctx_tokens,
        "measured_per_layer": measured,
        "per_layer_ms": round(per_layer_s * 1000, 4),
        "comms_ms_per_layer": round(comms_s_layer * 1000, 4),
        "weight_bytes_per_layer": wbytes_layer,
        "kv_bytes_per_layer": kv_bytes_layer,
        "ici_bw_bytes_per_s": V5E_ICI_BW,
        "formula": (
            "step_s = 80 x (per_layer_s + 2 x 2(tp-1)/tp x "
            "batch*d*2 / ICI_BW); tok/s = batch / step_s"
        ),
        "projected_step_ms": round(step_s * 1000, 3),
        "projected_toks_per_sec": round(toks_per_sec, 1),
        "projected_toks_per_sec_per_chip": round(toks_per_sec / tp, 1),
        "anchor_toks_per_sec": round(
            _anchor_toks_per_sec(full, batch, ctx_tokens, "int8") / tp, 1
        ),
        "note": (
            "per-layer compute measured on ONE chip at the tp8 shard "
            "shape (comms term modeled from the public ICI rate)"
            if measured else
            "off-TPU: per-layer term is the v5e HBM roofline at the "
            "shard shape, NOT a measurement — rerun on silicon"
        ),
    }


async def collect_silent(engine, req):
    """Drain one warmup stream, ignoring its outputs."""
    from dynamo_tpu.runtime.context import Context

    async for _ in engine.generate(req, Context()):
        pass


async def run_bench():
    model_name = os.environ.get("BENCH_MODEL", "qwen2.5-0.5b")
    quant = os.environ.get("BENCH_QUANT") or None
    spec = os.environ.get("BENCH_SPEC") or None
    primary = await run_leg(model_name, quant, spec)

    secondary = None
    if (
        os.environ.get("BENCH_SECONDARY", "1") != "0"
        and model_name == "qwen2.5-0.5b"
        and jax.default_backend() == "tpu"
    ):
        # BASELINE config-2 proxy: the largest BASELINE-relevant dense shape
        # one 16 GB chip serves — Llama-3-8B weight-only int8. Concurrency
        # sized to the KV that fits beside 8 GB of weights.
        try:
            secondary = await run_leg(
                "llama3-8b", "int8", None, concurrency=64, requests=128
            )
        except Exception as exc:  # secondary must never kill the headline
            secondary = {"error": f"{type(exc).__name__}: {exc}"}

    value = primary["toks_per_sec_per_chip"]
    out = {
        "metric": (
            f"aggregated decode throughput ({primary['model']}-shape, "
            f"ISL={ISL}, OSL={OSL})"
        ),
        "value": value,
        "unit": "tokens/sec/chip",
        # vs the DERIVED anchor (see module docstring): A100-80G HBM
        # bandwidth roofline × 0.6 achieved-bandwidth for the same
        # model/batch/context — not an invented constant.
        "vs_baseline": round(value / primary["anchor_toks_per_sec"], 4),
        "anchor": {
            "source": (
                "derived A100-80G + vLLM-class decode estimate: per-step "
                "time = max(step_bytes / (2039 GB/s x 0.6 achieved), "
                "n_layers x 0.3ms kernel-launch floor) for the same "
                "model/batch/context; per-chip is bandwidth-lopsided "
                "(A100 HBM = 2.5x v5e), so vs_baseline_per_dollar uses "
                "public on-demand prices (A100 $3.67/hr, v5e $1.20/hr)"
            ),
            "formula": (
                "B / max((w_bytes + B*ctx*kv_bytes)/(BW*eff), L*3e-4)"
            ),
            "toks_per_sec": primary["anchor_toks_per_sec"],
        },
        "vs_baseline_per_dollar": round(
            (value / V5E_USD_HR)
            / (primary["anchor_toks_per_sec"] / A100_80G_USD_HR), 4,
        ),
        "total_tokens": primary["total_tokens"],
        "wall_s": primary["wall_s"],
        "p50_ttft_ms": primary["p50_ttft_ms"],
        "p50_itl_ms": primary["p50_itl_ms"],
        "pipeline_depth": primary["pipeline_depth"],
        "host_gap_ms": primary["host_gap_ms"],
        # Megakernel coverage fraction (see run_leg): a demotion-driven
        # slowdown is visible as coverage < 1 next to the tok/s headline.
        "fused_coverage": primary["fused_coverage"],
        "mk_fused_bursts": primary["mk_fused_bursts"],
        "mk_fallback_bursts": primary["mk_fallback_bursts"],
        "mk_demoted_variants": primary["mk_demoted_variants"],
        # Device-plane trajectory (ISSUE 4): compile + memory regressions
        # are perf regressions the tok/s headline can hide for one run.
        "compile_s": primary["compile_s"],
        "compiles": primary["compiles"],
        "compiled_programs": primary["compiled_programs"],
        "recompile_storms": primary["recompile_storms"],
        "hbm_ledger_bytes": primary["hbm_ledger_bytes"],
        "hbm_ledger_peak_bytes": primary["hbm_ledger_peak_bytes"],
        "mfu": primary["mfu"],
        "hbm_util": primary["hbm_util"],
        "n_chips": jax.device_count(),
        "backend": jax.default_backend(),
        **_record_stamp(model_name, quant),
        **{
            k: primary[k]
            for k in ("spec_proposed", "spec_accepted")
            if k in primary
        },
    }
    if secondary is not None:
        if "anchor_toks_per_sec" in secondary:
            secondary["vs_baseline"] = round(
                secondary["toks_per_sec_per_chip"]
                / secondary["anchor_toks_per_sec"], 4,
            )
        out["secondary"] = secondary

    if (
        os.environ.get("BENCH_SECONDARY_LONG", "1") != "0"
        and model_name == "qwen2.5-0.5b"
        and jax.default_backend() == "tpu"
    ):
        # Decode-dominated 8B leg (ISL 128 / OSL 512, int8 KV): the regime
        # the ITL SLA + decode anchor actually measure — at OSL 64 the
        # prefill wall alone caps ANY engine near ~2.7k tok/s/chip on this
        # hardware (docs/design_docs/performance.md "round-4 roofline").
        try:
            # requests = 2 FULL waves: a partial tail wave at OSL=512
            # decodes half-empty for ~13s and halves the reported rate
            long_leg = await run_leg(
                "llama3-8b", "int8", None, concurrency=64, requests=128,
                kv_quant="int8", osl=512,
            )
            if "anchor_toks_per_sec" in long_leg:
                long_leg["vs_baseline"] = round(
                    long_leg["toks_per_sec_per_chip"]
                    / long_leg["anchor_toks_per_sec"], 4,
                )
            out["secondary_long"] = long_leg
        except Exception as exc:
            out["secondary_long"] = {"error": f"{type(exc).__name__}: {exc}"}

    if (
        os.environ.get("BENCH_DISAGG", "1") != "0"
        and model_name == "qwen2.5-0.5b"
        and jax.default_backend() == "tpu"
    ):
        try:
            out["disagg"] = await run_disagg_leg()
        except Exception as exc:  # never kill the headline
            out["disagg"] = {"error": f"{type(exc).__name__}: {exc}"}
        # On-host ceiling companion (CPU subprocess, no tunnel in the
        # path): the framework's OWN transfer rate next to the tunneled
        # number, so the dev-tunnel RTT floor can't masquerade as
        # framework cost (VERDICT r4 item 5).
        try:
            import subprocess
            import sys as _sys

            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            proc = subprocess.run(
                [_sys.executable, os.path.abspath(__file__),
                 "--disagg-ceiling"],
                env=env, capture_output=True, text=True, timeout=900,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            line = (proc.stdout.strip().splitlines() or ["{}"])[-1]
            if isinstance(out.get("disagg"), dict):
                out["disagg"]["onhost"] = json.loads(line)
        except Exception as exc:
            if isinstance(out.get("disagg"), dict):
                out["disagg"]["onhost"] = {
                    "error": f"{type(exc).__name__}: {exc}"
                }

    if (
        os.environ.get("BENCH_OVERLOAD", "1") != "0"
        and model_name == "qwen2.5-0.5b"
        and jax.default_backend() == "tpu"
    ):
        # Overload-armor leg (ISSUE 8): open-loop ramp past calibrated
        # capacity; the under-capacity sub-leg carries the
        # zero-spurious-activation contract (no sheds, no brownout
        # transitions), the 4x sub-leg proves bounded queueing + typed
        # shedding. Never kills the headline.
        try:
            out["overload"] = await run_overload_leg()
        except Exception as exc:
            out["overload"] = {"error": f"{type(exc).__name__}: {exc}"}

    if (
        os.environ.get("BENCH_DRAIN", "1") != "0"
        and model_name == "qwen2.5-0.5b"
        and jax.default_backend() == "tpu"
    ):
        # Drain leg (ISSUE 9): SIGTERM a worker mid-load; dropped==0,
        # handoff bytes, re-prefill tokens, worst mid-stream stall.
        # Never kills the headline; skipped-exit-0 contract untouched.
        try:
            out["drain"] = await run_drain_leg()
        except Exception as exc:
            out["drain"] = {"error": f"{type(exc).__name__}: {exc}"}

    if os.environ.get("BENCH_PROJECTION", "1") != "0":
        # Modeled 70B tp8 projection (ROADMAP item 1): measured per-layer
        # megakernel step on TPU (roofline-modeled elsewhere) × 80-layer
        # arithmetic + ICI collective cost. Always recorded; never kills
        # the headline.
        try:
            out["projection_70b_tp8"] = run_70b_projection_leg()
        except Exception as exc:
            out["projection_70b_tp8"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }

    if (
        os.environ.get("BENCH_CRASH", "1") != "0"
        and model_name == "qwen2.5-0.5b"
        and jax.default_backend() == "tpu"
    ):
        # Crash leg (ISSUE 10): a worker goes silent mid-load (the kill -9
        # shape); lost_requests must be 0, detection latency bounded by the
        # missed-report budget, re-prefilled tokens + warm-restart
        # restore_ms recorded. Never kills the headline; skipped-exit-0
        # contract untouched.
        try:
            out["crash"] = await run_crash_leg()
        except Exception as exc:
            out["crash"] = {"error": f"{type(exc).__name__}: {exc}"}

    if os.environ.get("BENCH_TOOLCALL", "1") != "0":
        # Tool-call streaming leg (ISSUE 15): time-to-first-tool-call-byte
        # O(delta) vs the old O(call-length) flush jail, malformed-call
        # recovery with zero dropped streams, and the typed parse-error
        # frame — pure CPU through the real HttpService, lands on any
        # backend; never kills the headline.
        try:
            out["tool_call"] = await run_tool_call_leg()
        except Exception as exc:
            out["tool_call"] = {"error": f"{type(exc).__name__}: {exc}"}

    if os.environ.get("BENCH_KVREUSE", "1") != "0":
        # KV-reuse leg (ISSUE 16): shared-prefix traffic through a tiny
        # real engine — hit rate by tier, prefill tokens/seconds saved,
        # and the TTFT delta vs a cold-cache control. Lands on any
        # backend; never kills the headline.
        try:
            out["kv_reuse_leg"] = await run_kv_reuse_leg()
        except Exception as exc:
            out["kv_reuse_leg"] = {"error": f"{type(exc).__name__}: {exc}"}

    if os.environ.get("BENCH_TICKBUDGET", "1") != "0":
        # Tick-budgeter leg (ISSUE 18): ISL-2048 prefill wave over a
        # steady OSL-512 decode population — budgeted mode holds p99 ITL
        # inside the SLA band the aggregated mode blows through, at
        # ≥0.9× aggregated throughput. Tiny real engine; lands on any
        # backend; never kills the headline.
        try:
            out["tick_budget"] = await run_tick_budget_leg()
        except Exception as exc:
            out["tick_budget"] = {"error": f"{type(exc).__name__}: {exc}"}

    if os.environ.get("BENCH_ELASTICITY", "1") != "0":
        # Elasticity leg (ISSUE 13): sim-clocked planner ramp (1×→4×→1×
        # convergence intervals), zero-re-prefill scale-down, and
        # select_worker per-request cost at 10 vs 100 workers. Pure CPU
        # arithmetic driving the real control plane — lands on any
        # backend; never kills the headline.
        try:
            out["elasticity"] = await run_elasticity_leg()
        except Exception as exc:
            out["elasticity"] = {"error": f"{type(exc).__name__}: {exc}"}

    # Sentinel epilogue (ISSUE 19): judge this round against the previous
    # usable BENCH_*.json when one exists. Table to stderr, report into
    # the record; stdout stays one JSON line and rc stays the round's.
    _sentinel_epilogue(out)
    print(json.dumps(out))


async def run_disagg_ceiling():
    res = await run_disagg_leg(
        isl=512, osl=8, concurrency=2, ceiling_only=True, n_layers=4
    )
    print(json.dumps(res))


def _init_backend_or_skip() -> bool:
    """Force JAX backend initialization up front. Returns True when a
    backend is usable. On failure (the tunneled TPU plugin dying at init
    was a real r5 mode: the bench exited rc=1 with NO perf record), either
    re-exec on the CPU backend (BENCH_ALLOW_CPU=1 — a failed platform
    cannot be re-initialized in-process) or emit one PARSEABLE skip record
    and exit 0, so the driver always gets a JSON line instead of a dead
    process."""
    import sys as _sys

    try:
        jax.devices()  # first device call: initializes the platform
        return True
    except Exception as exc:
        if (
            os.environ.get("BENCH_ALLOW_CPU") == "1"
            and os.environ.get("JAX_PLATFORMS") != "cpu"
        ):
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            os.execve(_sys.executable, [_sys.executable] + _sys.argv, env)
        ceiling = "--disagg-ceiling" in _sys.argv
        metric = (
            "disagg on-host transfer ceiling"
            if ceiling
            else f"aggregated decode throughput (ISL={ISL}, OSL={OSL})"
        )
        plat = (os.environ.get("JAX_PLATFORMS") or "tpu").split(",")[0]
        record = {
            "metric": metric,
            "value": None,
            "unit": "MB/s" if ceiling else "tokens/sec/chip",
            "skipped": f"{plat}-unavailable",
            "error": f"{type(exc).__name__}: {exc}",
            "hint": (
                "CPU backend init failed — the jax install "
                "itself is broken"
                if plat == "cpu"
                else "backend init failed; set BENCH_ALLOW_CPU=1 "
                "to run the CPU leg instead"
            ),
            # Same provenance stamp as a real record so the driver's
            # archive stays schema-uniform (compare still skips it via
            # the "skipped" key).
            **_record_stamp(os.environ.get("BENCH_MODEL", "qwen2.5-0.5b"),
                            os.environ.get("BENCH_QUANT") or None),
        }
        if not ceiling and os.environ.get("BENCH_PROJECTION", "1") != "0":
            # The 70B tp8 projection's modeled path is pure arithmetic —
            # it lands even when no backend initializes, so every round
            # carries the projection with its inputs recorded.
            try:
                record["projection_70b_tp8"] = run_70b_projection_leg()
            except Exception as pexc:
                record["projection_70b_tp8"] = {
                    "error": f"{type(pexc).__name__}: {pexc}"
                }
        print(json.dumps(record))
        return False


if __name__ == "__main__":
    import sys as _sys

    if not _init_backend_or_skip():
        _sys.exit(0)
    if "--disagg-ceiling" in _sys.argv:
        asyncio.run(run_disagg_ceiling())
    else:
        asyncio.run(run_bench())
