import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
print("backend", jax.default_backend(), jax.devices())

# HBM read roofline: reduce a big bf16 array
for gb in (0.5, 1.0):
    n = int(gb * (1<<30) / 2)
    a = jnp.ones((n,), jnp.bfloat16)
    f = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
    f(a).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5): r = f(a)
    r.block_until_ready()
    dt = (time.perf_counter()-t0)/5
    print(f"HBM read {gb}GB: {dt*1000:.2f} ms -> {gb/dt:.0f} GB/s")

# MXU roofline: big matmul
for m,k,nn in ((4096,4096,4096), (8192,8192,8192)):
    a = jnp.ones((m,k), jnp.bfloat16); b = jnp.ones((k,nn), jnp.bfloat16)
    f = jax.jit(lambda x,y: x@y)
    f(a,b).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10): r = f(a,b)
    r.block_until_ready()
    dt = (time.perf_counter()-t0)/10
    print(f"matmul {m}: {dt*1000:.2f} ms -> {2*m*k*nn/dt/1e12:.1f} TFLOP/s")

# batch scaling of a layer-stack weight-stream: x[B,d] through 24 layers
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import qwen2_500m_config
cfg = qwen2_500m_config()
params = llama.init_params(cfg, jax.random.PRNGKey(0))
def stream(p_, x):
    def layer(x, lp):
        q = x @ lp["wq"]
        a = q @ lp["wo"]
        g = jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])
        return x + a + g @ lp["w_down"], None
    x, _ = jax.lax.scan(layer, x, p_["layers"])
    return x @ p_["embed"].T
f = jax.jit(stream)
for B in (32, 64, 128, 256):
    x = jnp.ones((B, cfg.d_model), jnp.bfloat16)
    f(params, x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10): r = f(params, x)
    r.block_until_ready()
    dt = (time.perf_counter()-t0)/10
    print(f"layer-stream B={B}: {dt*1000:.2f} ms -> {B/dt:.0f} tok/s")
