import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import qwen2_500m_config

cfg = qwen2_500m_config()
NB, BS = 2048, 16
params = llama.init_params(cfg, jax.random.PRNGKey(0))
k, v = llama.init_kv_cache(cfg, NB, BS)

def bench(fn, *args, n=10, label=""):
    out = fn(*args); jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n): out = fn(*args)
    jax.block_until_ready(out)
    print(f"{label}: {(time.perf_counter()-t0)/n*1000:.2f} ms")

for B in (1, 8, 16):
    C = 128
    toks = jnp.ones((B, C), jnp.int32)
    pos = jnp.zeros((B,), jnp.int32)
    lens = jnp.full((B,), C, jnp.int32)
    tables = jnp.asarray(np.arange(B*8, dtype=np.int32).reshape(B, 8))
    for uk in (True, False):
        f = jax.jit(lambda p_,k_,v_,t_: llama.forward_paged(p_, cfg, t_, pos, lens, tables, k_, v_, use_kernel=uk)[0])
        bench(f, params, k, v, toks, n=5, label=f"prefill B={B} C=128 kernel={uk}")
