"""Host-gap profiler: inter-burst device idle at pipeline_depth 1 vs 2.

The decode pipelining win (docs/design_docs/decode_pipelining.md) is the
host work the device no longer waits on between fused bursts: readback
RTT + stop-condition reconciliation + emit + scheduler tick. This script
runs the SAME decode-heavy workload at depth 1 and depth 2 on whatever
backend JAX sees and reports, per depth:

  - wall_per_burst_ms : end-to-end wall clock / reaped bursts
  - host_gap_ms       : mean of dynamo_tpu_engine_host_gap_seconds — the
                        measured host-injected device wait per dispatch
  - derived idle delta: wall_per_burst(d1) - wall_per_burst(d2) ≈ the
                        hidden per-burst host overhead

Env: PROF_ISL / PROF_OSL / PROF_CONCURRENCY / PROF_STEPS / PROF_MODEL
(tiny | qwen2.5-0.5b), PROF_ROUNDS.
"""

import asyncio
import json
import os
import time

import numpy as np


async def run_depth(depth: int):
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import qwen2_500m_config, tiny_config
    from dynamo_tpu.runtime.context import Context

    model = os.environ.get("PROF_MODEL", "tiny")
    cfg = tiny_config() if model == "tiny" else qwen2_500m_config()
    isl = int(os.environ.get("PROF_ISL", 32))
    osl = int(os.environ.get("PROF_OSL", 128))
    conc = int(os.environ.get("PROF_CONCURRENCY", 8))
    steps = int(os.environ.get("PROF_STEPS", 8))

    engine = JaxEngine(
        JaxEngineArgs(
            config=cfg,
            block_size=16,
            num_kv_blocks=max(256, conc * (isl + osl) // 16 + 64),
            max_num_seqs=conc,
            max_model_len=isl + osl + 32,
            prefill_chunk=min(128, isl),
            prefill_batch=conc,
            decode_steps=steps,
            pipeline_depth=depth,
        )
    )
    rng = np.random.default_rng(7)

    def mk_req(i):
        return PreprocessedRequest(
            token_ids=rng.integers(10, cfg.vocab_size - 10, size=isl).tolist(),
            request_id=f"gap-{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def one(i):
        n = 0
        async for out in engine.generate(mk_req(i), Context()):
            n += len(out.token_ids or [])
        return n

    try:
        # Warmup wave pays every compile; the measured wave is steady-state.
        await asyncio.gather(*(one(1000 + i) for i in range(conc)))
        g0, s0 = engine.step_metrics.host_gap_stats()
        steps0 = engine.steps
        t0 = time.monotonic()
        toks = sum(
            await asyncio.gather(*(one(i) for i in range(conc)))
        )
        wall = time.monotonic() - t0
        bursts = max(engine.steps - steps0, 1)
        g1, s1 = engine.step_metrics.host_gap_stats()
        return {
            "pipeline_depth": depth,
            "tokens": toks,
            "wall_s": round(wall, 3),
            "bursts": bursts,
            "wall_per_burst_ms": round(1000 * wall / bursts, 3),
            "host_gap_ms": round(
                1000 * (s1 - s0) / max(g1 - g0, 1), 3
            ),
            "toks_per_s": round(toks / wall, 1),
        }
    finally:
        await engine.stop()


async def main():
    rounds = int(os.environ.get("PROF_ROUNDS", 1))
    out = {"backend": None, "runs": []}
    import jax

    out["backend"] = jax.default_backend()
    for _ in range(rounds):
        d1 = await run_depth(1)
        d2 = await run_depth(2)
        d1["hidden_host_ms_per_burst"] = round(
            d1["wall_per_burst_ms"] - d2["wall_per_burst_ms"], 3
        )
        out["runs"].append({"depth1": d1, "depth2": d2})
    r = out["runs"][-1]
    out["summary"] = {
        "host_gap_ms_d1": r["depth1"]["host_gap_ms"],
        "host_gap_ms_d2": r["depth2"]["host_gap_ms"],
        "wall_per_burst_ms_d1": r["depth1"]["wall_per_burst_ms"],
        "wall_per_burst_ms_d2": r["depth2"]["wall_per_burst_ms"],
        "overlap_win_ms_per_burst": r["depth1"]["hidden_host_ms_per_burst"],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(main())
