"""Host-gap profiler: inter-burst device idle at pipeline_depth 1 vs 2.

The decode pipelining win (docs/design_docs/decode_pipelining.md) is the
host work the device no longer waits on between fused bursts: readback
RTT + stop-condition reconciliation + emit + scheduler tick. This script
runs the SAME decode-heavy workload at depth 1 and depth 2 on whatever
backend JAX sees and reports, per depth:

  - wall_per_burst_ms : end-to-end wall clock / reaped bursts
  - host_gap_ms       : mean of dynamo_tpu_engine_host_gap_seconds — the
                        measured host-injected device wait per dispatch
  - derived idle delta: wall_per_burst(d1) - wall_per_burst(d2) ≈ the
                        hidden per-burst host overhead

Env: PROF_ISL / PROF_OSL / PROF_CONCURRENCY / PROF_STEPS / PROF_MODEL
(tiny | qwen2.5-0.5b), PROF_ROUNDS.
"""

import asyncio
import json
import os
import time

import numpy as np


async def run_depth(depth: int):
    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import qwen2_500m_config, tiny_config
    from dynamo_tpu.runtime.context import Context

    model = os.environ.get("PROF_MODEL", "tiny")
    cfg = tiny_config() if model == "tiny" else qwen2_500m_config()
    isl = int(os.environ.get("PROF_ISL", 32))
    osl = int(os.environ.get("PROF_OSL", 128))
    conc = int(os.environ.get("PROF_CONCURRENCY", 8))
    steps = int(os.environ.get("PROF_STEPS", 8))

    engine = JaxEngine(
        JaxEngineArgs(
            config=cfg,
            block_size=16,
            num_kv_blocks=max(256, conc * (isl + osl) // 16 + 64),
            max_num_seqs=conc,
            max_model_len=isl + osl + 32,
            prefill_chunk=min(128, isl),
            prefill_batch=conc,
            decode_steps=steps,
            pipeline_depth=depth,
        )
    )
    rng = np.random.default_rng(7)

    def mk_req(i):
        return PreprocessedRequest(
            token_ids=rng.integers(10, cfg.vocab_size - 10, size=isl).tolist(),
            request_id=f"gap-{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )

    async def one(i):
        n = 0
        async for out in engine.generate(mk_req(i), Context()):
            n += len(out.token_ids or [])
        return n

    try:
        # Warmup wave pays every compile; the measured wave is steady-state.
        await asyncio.gather(*(one(1000 + i) for i in range(conc)))
        g0, s0 = engine.step_metrics.host_gap_stats()
        steps0 = engine.steps
        t0 = time.monotonic()
        toks = sum(
            await asyncio.gather(*(one(i) for i in range(conc)))
        )
        wall = time.monotonic() - t0
        bursts = max(engine.steps - steps0, 1)
        g1, s1 = engine.step_metrics.host_gap_stats()
        # Micro-time the per-reap stats-snapshot publish on THIS engine
        # (real slot count, real pool) for the observability-overhead
        # accounting below.
        t0 = time.perf_counter()
        for _ in range(1000):
            engine._publish_stats()
        stats_publish_us = (time.perf_counter() - t0) / 1000 * 1e6
        return {
            "pipeline_depth": depth,
            "tokens": toks,
            "wall_s": round(wall, 3),
            "bursts": bursts,
            "wall_per_burst_ms": round(1000 * wall / bursts, 3),
            "host_gap_ms": round(
                1000 * (s1 - s0) / max(g1 - g0, 1), 3
            ),
            "toks_per_s": round(toks / wall, 1),
            "stats_publish_us": round(stats_publish_us, 3),
        }
    finally:
        await engine.stop()


def observe_overhead(wall_per_burst_ms: float, stats_publish_us: float) -> dict:
    """Measure the device-plane observability cost a steady-state decode
    burst actually pays, by micro-timing the exact hot-path operations:

      - 1 watched_jit cache-hit dispatch wrapper (2 _cache_size C calls +
        2 perf_counter reads) per burst,
      - ~4 flight-recorder appends per burst (engine dispatch + reap,
        runner decode + its transfer_log mirror),
      - 1 stats-snapshot publish per reap (``stats_publish_us``, measured
        against the run's real engine in run_depth),
      - 1 KV-reuse feed per admitted request (engine note_request +
        router sketch touch + per-chunk prefill-cost EWMA), charged at
        the worst case of one admission per burst.

    Everything else (HBM ledger, metric rendering, compile bookkeeping)
    runs at scrape/compile time, off the tick path. The acceptance bar is
    overhead < 1% of the measured steady-state burst wall time."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.runtime.device_observe import FlightRecorder, watched_jit

    N = 20_000
    # watched wrapper delta: wrapped vs raw cache-hit dispatch of the same
    # trivial compiled program (device work subtracts out).
    raw = jax.jit(lambda x: x)
    wrapped = watched_jit("prof.overhead_probe", jax.jit(lambda x: x))
    x = jnp.zeros(8)
    raw(x), wrapped(x)  # compile both outside the timed window
    t0 = _time.perf_counter()
    for _ in range(N):
        raw(x)
    t_raw = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    for _ in range(N):
        wrapped(x)
    t_wrapped = _time.perf_counter() - t0
    watch_us = max(0.0, (t_wrapped - t_raw) / N * 1e6)

    fr = FlightRecorder("prof")
    t0 = _time.perf_counter()
    for i in range(N):
        fr.record("dispatch", nb=8, occupancy=4, inflight=2)
    record_us = (_time.perf_counter() - t0) / N * 1e6

    # Trajectory plane (runtime/trajectory.py): a traced request pays 3
    # retrospective export_span calls at STREAM END (queue/prefill/decode
    # — ring append + shipper enqueue via the tracer listener), never
    # inside the tick. Charged per burst at the worst case of one request
    # finishing every burst, so the <1% bar covers the trajectory delta.
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.utils.tracing import Tracer, export_span

    tracer = Tracer(path="", otlp=False)  # never ship synthetic spans
    listened = []
    tracer.add_listener(lambda s: listened.append(1))  # shipper-shaped tap
    ctx = Context(baggage={"traceparent": "00-" + "a" * 32 + "-" + "b" * 16 + "-01"})
    t0 = _time.perf_counter()
    M = N // 4
    for i in range(M):
        export_span(
            "engine.decode", ctx, start_mono=0.0, end_mono=0.001,
            tracer=tracer, generated=8,
        )
    span_us = (_time.perf_counter() - t0) / M * 1e6
    trajectory_request_us = 3 * span_us

    # KV-reuse plane (runtime/kv_reuse_observe.py): an ADMITTED request
    # pays one note_request (sketch touch + ROI counter bumps) on the
    # engine side and one sketch touch on the router side; the per-chunk
    # EWMA update (note_prefill_cost) rides the prefill path, charged
    # here too. The ROI trajectory event is the same ring-append +
    # shipper-enqueue shape the trajectory term above already prices.
    # Charged per burst at the worst case of one admission every burst.
    from dynamo_tpu.runtime.kv_reuse_observe import KvReusePlane

    plane = KvReusePlane(capacity=4096)
    t0 = _time.perf_counter()
    for i in range(M):
        plane.note_request(
            anchor=i & 0xFFF, cached_tokens=96, recomputed_tokens=32,
            tier="device", trace_id=None,
        )
    note_request_us = (_time.perf_counter() - t0) / M * 1e6
    t0 = _time.perf_counter()
    for i in range(M):
        plane.note_router_match(i & 0xFFF, tokens=96, worker=(1, 0))
    router_touch_us = (_time.perf_counter() - t0) / M * 1e6
    t0 = _time.perf_counter()
    for _ in range(M):
        plane.note_prefill_cost(0.01, 128)
    prefill_cost_us = (_time.perf_counter() - t0) / M * 1e6
    kv_reuse_request_us = note_request_us + router_touch_us + prefill_cost_us

    # Perf ledger (runtime/perf_ledger.py): every reap pays one
    # observe_decode (dict get + deque appends) plus the time-gate check
    # at the top of evaluate (the quantile/verdict work behind it runs at
    # most once per eval_interval_s, off the per-burst path). Priced on a
    # PRIVATE ledger with a fake clock so the probe never pollutes the
    # process-global fingerprint state.
    from dynamo_tpu.runtime.perf_ledger import PerfLedger, PerfLedgerConfig

    _t = [0.0]
    ledger = PerfLedger(
        PerfLedgerConfig(fingerprint_path=""), clock=lambda: _t[0]
    )
    ledger.configure(preset="prof", backend="cpu", host="prof")
    t0 = _time.perf_counter()
    for i in range(M):
        _t[0] += 0.001
        ledger.observe_decode(
            8, "w8", "fused", 0.001, 8, 4.0, 64.0, 0.0001, 0.0002, 0.0001
        )
        ledger.evaluate()
    perf_ledger_us = (_time.perf_counter() - t0) / M * 1e6

    per_burst_us = (
        watch_us + 4 * record_us + stats_publish_us + trajectory_request_us
        + kv_reuse_request_us + perf_ledger_us
    )
    return {
        "watched_dispatch_us": round(watch_us, 3),
        "flight_record_us": round(record_us, 3),
        "stats_publish_us": round(stats_publish_us, 3),
        "trajectory_span_us": round(span_us, 3),
        "trajectory_request_us": round(trajectory_request_us, 3),
        "kv_note_request_us": round(note_request_us, 3),
        "kv_router_touch_us": round(router_touch_us, 3),
        "kv_prefill_cost_us": round(prefill_cost_us, 3),
        "kv_reuse_request_us": round(kv_reuse_request_us, 3),
        "perf_ledger_us": round(perf_ledger_us, 3),
        "per_burst_us": round(per_burst_us, 3),
        "overhead_pct_of_burst": round(
            100 * per_burst_us / 1000 / max(wall_per_burst_ms, 1e-9), 4
        ),
    }


async def main():
    rounds = int(os.environ.get("PROF_ROUNDS", 1))
    out = {"backend": None, "runs": []}
    import jax

    from dynamo_tpu.runtime.device_observe import global_compile_watcher

    out["backend"] = jax.default_backend()
    compile_before = global_compile_watcher().totals()
    for _ in range(rounds):
        d1 = await run_depth(1)
        d2 = await run_depth(2)
        d1["hidden_host_ms_per_burst"] = round(
            d1["wall_per_burst_ms"] - d2["wall_per_burst_ms"], 3
        )
        out["runs"].append({"depth1": d1, "depth2": d2})
    r = out["runs"][-1]
    compile_after = global_compile_watcher().totals()
    out["compile"] = {
        "programs": compile_after["programs"],
        "compiles": compile_after["compiles"] - compile_before["compiles"],
        "compile_s": round(
            compile_after["compile_seconds"]
            - compile_before["compile_seconds"], 2
        ),
        "storms": compile_after["storms"] - compile_before["storms"],
    }
    out["observe_overhead"] = observe_overhead(
        r["depth2"]["wall_per_burst_ms"],
        r["depth2"]["stats_publish_us"],
    )
    out["summary"] = {
        "host_gap_ms_d1": r["depth1"]["host_gap_ms"],
        "host_gap_ms_d2": r["depth2"]["host_gap_ms"],
        "wall_per_burst_ms_d1": r["depth1"]["wall_per_burst_ms"],
        "wall_per_burst_ms_d2": r["depth2"]["wall_per_burst_ms"],
        "overlap_win_ms_per_burst": r["depth1"]["hidden_host_ms_per_burst"],
        "observe_overhead_pct": out["observe_overhead"][
            "overhead_pct_of_burst"
        ],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(main())
