"""Re-sweep decode-kernel batch_block with the LAYERED cache program."""
import os, sys, time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import qwen2_500m_config
import dynamo_tpu.ops.attention as A

cfg = qwen2_500m_config()
BS = 128; NB = 65536 // BS; B = 256; STEPS = 64
params = llama.init_params(cfg, jax.random.PRNGKey(0))
tokens = jnp.ones((B,), jnp.int32)
start_pos = jnp.full((B,), 160, jnp.int32)
active = jnp.ones((B,), jnp.int32)
tables = jnp.asarray((np.arange(B * 2, dtype=np.int32) % NB).reshape(B, 2))
rng = jax.random.PRNGKey(1)
temp = jnp.ones((B,), jnp.float32); topk = jnp.zeros((B,), jnp.int32); topp = jnp.full((B,), 0.95, jnp.float32)

BQ = int(sys.argv[1])
real = A._load_decode_kernel()
import functools
def patched_loader():
    return functools.partial(real, batch_block=BQ)
A._load_decode_kernel = patched_loader

def run(params, k, v):
    return llama.decode_multi(params, cfg, tokens, start_pos, active, tables, k, v,
        rng, temp, topk, topp, num_steps=STEPS, use_kernel=True, want_logprobs=False)
f = jax.jit(run, donate_argnums=(1, 2))
k, v = llama.init_kv_cache(cfg, NB, BS, layered=True)
out = f(params, k, v); k, v = out[-2], out[-1]; np.asarray(out[0])
n = 6; t0 = time.perf_counter()
for _ in range(n):
    out = f(params, k, v); k, v = out[-2], out[-1]; np.asarray(out[0])
dt = (time.perf_counter() - t0) / n
print(f"BQ={BQ}: {dt/STEPS*1000:.2f} ms/step ({B*STEPS/dt:.0f} tok/s)", flush=True)
