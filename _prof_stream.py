"""Diagnose pallas weight-streaming rate vs XLA: a CHAIN of 16 matmuls
(distinct weights, one jit) so device time ≫ the tunnel's enqueue floor.
Decides the r5 fused-layer plan."""
import functools, time, sys
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

D, FF, B, NW = 4096, 14336, 128, 8
CHAIN = 16  # matmuls per dispatch (weights cycled)
GB = CHAIN * D * FF / 1e9

rng = np.random.default_rng(0)
ws = [
    np.ascontiguousarray(rng.integers(-127, 127, size=(D, FF)).astype(np.int8))
    for _ in range(NW)
]
x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32)).astype(jnp.bfloat16)


def bench(label, f, *a, n=4):
    r = f(*a)
    _ = np.asarray(jax.tree.leaves(r)[0]).ravel()[:4]
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*a)
    _ = np.asarray(jax.tree.leaves(r)[0]).ravel()[:4]
    dt = (time.perf_counter() - t0) / n
    print(f"{label}: {dt*1000:.2f} ms/chain -> {GB/dt:.0f} GB/s", flush=True)


# 0) XLA chain (the model's current path shape)
wj = [jnp.asarray(w) for w in ws]


@jax.jit
def xla_chain(x_, *w_):
    acc = jnp.zeros((B,), jnp.float32)
    for i in range(CHAIN):
        w = w_[i % NW]
        y = jax.lax.dot_general(
            x_, w.astype(x_.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc + y[:, 0] + y[:, -1]
    return acc


bench("XLA int8 chain", xla_chain, x, *wj)


# 1) pallas chain: pre-tiled weights, contiguous DMA per grid step
def mk_pallas(BN):
    NT = FF // BN

    def _k(wt_ref, x_ref, o_ref):
        w = wt_ref[0].astype(jnp.bfloat16)
        o_ref[...] = jax.lax.dot_general(
            x_ref[...], w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    wt = [
        jnp.asarray(
            np.ascontiguousarray(w.reshape(D, NT, BN).transpose(1, 0, 2))
        )
        for w in ws
    ]

    def one(x_, w_):
        return pl.pallas_call(
            _k,
            grid=(NT,),
            in_specs=[
                pl.BlockSpec((1, D, BN), lambda i: (i, 0, 0)),
                pl.BlockSpec((B, D), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((B, BN), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((B, FF), jnp.float32),
        )(w_, x_)

    @jax.jit
    def chain(x_, *w_):
        acc = jnp.zeros((B,), jnp.float32)
        for i in range(CHAIN):
            y = one(x_, w_[i % NW])
            acc = acc + y[:, 0] + y[:, -1]
        return acc

    def run(x_):
        return chain(x_, *wt)

    return run


for BN in (512, 1024):
    bench(f"pallas int8 chain BN={BN}", mk_pallas(BN), x)
