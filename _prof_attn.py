"""Prototype: packed block-diagonal decode attention kernel (v2) vs v1.

v2 design: cache viewed as [NB, bs, KH*D] (free bitcast); per sequence the
whole-page QK product is ONE MXU dot  k[bs, KD] @ qd[KD, R]  where qd is the
block-diagonal packing of the R = KH*G query rows (built in-kernel from a
[D, R] query slice with an iota mask — ~3 vector ops); scores live in a
single [R, bs] lane-major tile so the online softmax is ~10 dense VPU ops
instead of KH*G tiny ones; PV is one [R, bs] @ [bs, KD] dot; the per-head
output blocks are sliced out of the accumulator only at finalize.
"""
import functools, time, sys
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

NEG_INF = -1e30


def _decode_kernel_packed(
    block_tables_ref, start_pos_ref, window_ref,
    qdr_ref,  # [BQ, D, R]  (rows d, cols (h, g) h-major)
    *refs,  # k_0, v_0, ..., k_{BQ-1}, v_{BQ-1}, o_ref, mask, qd, m, l, acc
    sm_scale, block_size, batch_block, n_kv_heads, logit_cap=0.0,
):
    BQ = batch_block
    kv_refs = refs[: 2 * BQ]
    o_ref = refs[2 * BQ]
    mask_ref, qd_ref, m_ref, l_ref, acc_ref = refs[2 * BQ + 1 :]

    bb = pl.program_id(0)
    p = pl.program_id(1)
    num_steps = pl.num_programs(1)
    KH = n_kv_heads
    D = qdr_ref.shape[1]
    R = qdr_ref.shape[2]
    G = R // KH
    KD = KH * D
    bs = block_size

    @pl.when((bb == 0) & (p == 0))
    def _init_mask():
        # Block-diag selector: mask[(h', d), (h, g)] = 1 iff h' == h.
        row_h = jax.lax.broadcasted_iota(jnp.int32, (KD, R), 0) // D
        col_h = jax.lax.broadcasted_iota(jnp.int32, (KD, R), 1) // G
        mask_ref[...] = (row_h == col_h).astype(mask_ref.dtype)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)
        # qd[(h', d), (h, g)] = q[d, (h,g)] iff h' == h (block-diagonal).
        for j in range(BQ):
            tiled = jnp.concatenate([qdr_ref[j]] * KH, axis=0)  # [KD, R]
            qd_ref[j] = tiled * mask_ref[...]

    win = window_ref[0]
    for j in range(BQ):
        start = start_pos_ref[bb * BQ + j]
        last_needed = start // bs
        first_needed = jnp.where(
            win > 0, jnp.maximum(start - win + 1, 0) // bs, 0
        )

        @pl.when((p >= first_needed) & (p <= last_needed))
        def _compute(j=j, start=start):
            k = kv_refs[2 * j][0]  # [bs, KD] bf16
            s = jax.lax.dot_general(
                k, qd_ref[j], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # [bs, R] f32 — t on sublanes, (h,g) on lanes
            if logit_cap > 0.0:
                s = logit_cap * jnp.tanh(s / logit_cap)
            t_idx = p * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
            visible = (t_idx <= start) & ((win <= 0) | (t_idx > start - win))
            s = jnp.where(visible, s, NEG_INF)
            m_prev = m_ref[j]  # [1, R]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s - m_new).astype(jnp.bfloat16)  # [bs, R]
            l_ref[j] = l_ref[j] * alpha + jnp.sum(
                probs.astype(jnp.float32), 0, keepdims=True
            )
            v = kv_refs[2 * j + 1][0]  # [bs, KD] bf16
            for h in range(KH):
                pv = jax.lax.dot_general(
                    probs[:, h * G : (h + 1) * G],
                    v[:, h * D : (h + 1) * D],
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [G, D]
                acc_ref[j, h] = acc_ref[j, h] * alpha[0, h * G : (h + 1) * G][
                    :, None
                ] + pv
            m_ref[j] = m_new

    @pl.when(p == num_steps - 1)
    def _finalize():
        for j in range(BQ):
            for h in range(KH):
                l = l_ref[j, :, h * G : (h + 1) * G]  # [1, G]
                o_ref[j, h] = (
                    acc_ref[j, h] / jnp.maximum(l[0][:, None], 1e-30)
                ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "batch_block", "logit_cap")
)
def decode_packed(
    q,  # [B, 1, H, D]
    k_cache,  # [NB, bs, KH, D]
    v_cache,
    block_tables,  # [B, P]
    start_pos,  # [B]
    window=0,
    *,
    sm_scale=None,
    batch_block: int = 8,
    logit_cap: float = 0.0,
):
    B, C, H, D = q.shape
    NB, bs, KH, _ = k_cache.shape
    G = H // KH
    R = KH * G
    KD = KH * D
    scale = sm_scale if sm_scale is not None else D**-0.5
    BQ = max(min(batch_block, B), 1)
    B_pad = ((B + BQ - 1) // BQ) * BQ
    if B_pad != B:
        q = jnp.pad(q, ((0, B_pad - B), (0, 0), (0, 0), (0, 0)))
        block_tables = jnp.pad(block_tables, ((0, B_pad - B), (0, 0)))
        start_pos = jnp.pad(start_pos, (0, B_pad - B))
    P = block_tables.shape[1]
    win = jnp.asarray(window, jnp.int32).reshape(1)

    # [B, 1, H, D] -> [B, D, R(h-major,g)]
    qdr = (
        q.reshape(B_pad, KH, G, D).transpose(0, 3, 1, 2).reshape(B_pad, D, R)
    ).astype(k_cache.dtype)
    k2 = k_cache.reshape(NB, bs, KD)
    v2 = v_cache.reshape(NB, bs, KD)

    def q_map(bb, p, bt, sp, w):
        return (bb, 0, 0)

    def kv_map_for(j):
        def kv_map(bb, p, bt, sp, w):
            return (bt[bb * BQ + j, p], 0, 0)
        return kv_map

    in_specs = [pl.BlockSpec((BQ, D, R), q_map)]
    kv_args = []
    for j in range(BQ):
        spec = pl.BlockSpec((1, bs, KD), kv_map_for(j))
        in_specs.extend([spec, spec])
        kv_args.extend([k2, v2])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B_pad // BQ, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (BQ, KH, G, D), lambda bb, p, bt, sp, w: (bb, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((KD, R), k2.dtype),
            pltpu.VMEM((BQ, KD, R), k2.dtype),
            pltpu.VMEM((BQ, 1, R), jnp.float32),
            pltpu.VMEM((BQ, 1, R), jnp.float32),
            pltpu.VMEM((BQ, KH, G, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel_packed, sm_scale=scale, block_size=bs,
        batch_block=BQ, n_kv_heads=KH, logit_cap=logit_cap,
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B_pad, KH, G, D), q.dtype),
    )(
        block_tables.astype(jnp.int32), start_pos.astype(jnp.int32), win,
        qdr, *kv_args,
    )
    out = out[:B].reshape(B, KH, 1, G, D).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, 1, H, D)


if __name__ == "__main__":
    from dynamo_tpu.ops.attention import _paged_attention_xla
    from dynamo_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_kernel,
    )

    B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    KH, G, D, bs, P = 8, 4, 128, 128, 2
    H = KH * G
    NB = B * P + 8
    CTX = 160
    L = 32

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)).astype(np.float32)).astype(jnp.bfloat16)
    k_c = jnp.asarray(rng.standard_normal((NB, bs, KH, D)).astype(np.float32)).astype(jnp.bfloat16)
    v_c = jnp.asarray(rng.standard_normal((NB, bs, KH, D)).astype(np.float32)).astype(jnp.bfloat16)
    tables = jnp.asarray(rng.permutation(NB)[: B * P].reshape(B, P).astype(np.int32))
    pos = jnp.full((B,), CTX, jnp.int32)
    ones = jnp.ones((B,), jnp.int32)

    # parity
    ref = _paged_attention_xla(q, k_c, v_c, tables, pos, ones)
    out2 = decode_packed(q, k_c, v_c, tables, pos)
    err = jnp.abs(out2.astype(jnp.float32) - ref.astype(jnp.float32)).max()
    print("packed vs oracle max err:", float(err), flush=True)
    out1 = paged_attention_decode_kernel(q, k_c, v_c, tables, pos)
    err1 = jnp.abs(out1.astype(jnp.float32) - ref.astype(jnp.float32)).max()
    print("v1 vs oracle max err:", float(err1), flush=True)

    # timing: scan over 32 layer-calls in one dispatch
    def bench(label, fn, n=5):
        def outer(q_, k_, v_):
            def one(c, _):
                o = fn(q_ + (c * 0.001).astype(q_.dtype), k_, v_, tables, pos)
                return c + o.astype(jnp.float32).mean() * 0.0, ()
            y, _ = jax.lax.scan(one, jnp.float32(0), None, length=L)
            return y
        f = jax.jit(outer)
        _ = np.asarray(f(q, k_c, v_c))
        t0 = time.perf_counter()
        for _ in range(n):
            r = f(q, k_c, v_c)
        _ = np.asarray(r)
        dt = (time.perf_counter() - t0) / n
        print(f"{label}: {dt*1000:.2f} ms for {L} layers", flush=True)

    bench("v1 kernel", lambda q_, k_, v_, t_, p_: paged_attention_decode_kernel(q_, k_, v_, t_, p_))
    bench("v2 packed", lambda q_, k_, v_, t_, p_: decode_packed(q_, k_, v_, t_, p_))


# --- v1 variant: bf16 operands (no f32 casts) ---
def _decode_kernel_bf16(
    block_tables_ref, start_pos_ref, window_ref,
    q_ref, *refs, sm_scale, block_size, batch_block, logit_cap=0.0,
):
    BQ = batch_block
    kv_refs = refs[: 2 * BQ]
    o_ref = refs[2 * BQ]
    m_ref, l_ref, acc_ref = refs[2 * BQ + 1 :]
    bb = pl.program_id(0)
    p = pl.program_id(1)
    num_steps = pl.num_programs(1)
    KH = q_ref.shape[1]
    G = q_ref.shape[2]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    win = window_ref[0]
    for j in range(BQ):
        start = start_pos_ref[bb * BQ + j]
        last_needed_page = start // block_size
        first_needed_page = jnp.where(
            win > 0, jnp.maximum(start - win + 1, 0) // block_size, 0
        )

        @pl.when((p >= first_needed_page) & (p <= last_needed_page))
        def _compute(j=j, start=start):
            t_idx = p * block_size + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_size), 1
            )
            visible = t_idx <= start
            visible = visible & ((win <= 0) | (t_idx > start - win))
            for h in range(KH):
                q = q_ref[j, h]  # [G, D] bf16
                k = kv_refs[2 * j][0, :, h, :]  # [bs, D] bf16
                v = kv_refs[2 * j + 1][0, :, h, :]
                s_mat = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * sm_scale
                if logit_cap > 0.0:
                    s_mat = logit_cap * jnp.tanh(s_mat / logit_cap)
                s_mat = jnp.where(visible, s_mat, NEG_INF)
                m_prev = m_ref[j, h]
                m_new = jnp.maximum(m_prev, jnp.max(s_mat, axis=-1, keepdims=True))
                alpha = jnp.exp(m_prev - m_new)
                probs = jnp.exp(s_mat - m_new).astype(jnp.bfloat16)
                l_ref[j, h] = l_ref[j, h] * alpha + jnp.sum(
                    probs.astype(jnp.float32), axis=-1, keepdims=True
                )
                acc_ref[j, h] = acc_ref[j, h] * alpha + jax.lax.dot_general(
                    probs, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                m_ref[j, h] = m_new

    @pl.when(p == num_steps - 1)
    def _finalize():
        for j in range(BQ):
            for h in range(KH):
                out = acc_ref[j, h] / jnp.maximum(l_ref[j, h], 1e-30)
                o_ref[j, h] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "batch_block", "logit_cap"))
def decode_bf16(q, k_cache, v_cache, block_tables, start_pos, window=0, *,
                sm_scale=None, batch_block=8, logit_cap=0.0):
    B, C, n_heads, head_dim = q.shape
    _, block_size, n_kv_heads, _ = k_cache.shape
    G = n_heads // n_kv_heads
    scale = sm_scale if sm_scale is not None else head_dim**-0.5
    BQ = max(min(batch_block, B), 1)
    B_pad = ((B + BQ - 1) // BQ) * BQ
    if B_pad != B:
        q = jnp.pad(q, ((0, B_pad - B), (0, 0), (0, 0), (0, 0)))
        block_tables = jnp.pad(block_tables, ((0, B_pad - B), (0, 0)))
        start_pos = jnp.pad(start_pos, (0, B_pad - B))
    q4 = q.reshape(B_pad, n_kv_heads, G, head_dim)
    P = block_tables.shape[1]
    win = jnp.asarray(window, jnp.int32).reshape(1)
    def q_map(bb, p, bt, sp, w):
        return (bb, 0, 0, 0)
    def kv_map_for(j):
        def kv_map(bb, p, bt, sp, w):
            return (bt[bb * BQ + j, p], 0, 0, 0)
        return kv_map
    in_specs = [pl.BlockSpec((BQ, n_kv_heads, G, head_dim), q_map)]
    kv_args = []
    for j in range(BQ):
        spec = pl.BlockSpec((1, block_size, n_kv_heads, head_dim), kv_map_for(j))
        in_specs.extend([spec, spec])
        kv_args.extend([k_cache, v_cache])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B_pad // BQ, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((BQ, n_kv_heads, G, head_dim), q_map),
        scratch_shapes=[
            pltpu.VMEM((BQ, n_kv_heads, G, 1), jnp.float32),
            pltpu.VMEM((BQ, n_kv_heads, G, 1), jnp.float32),
            pltpu.VMEM((BQ, n_kv_heads, G, head_dim), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel_bf16, sm_scale=scale, block_size=block_size,
        batch_block=BQ, logit_cap=logit_cap,
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B_pad, n_kv_heads, G, head_dim), q.dtype),
    )(block_tables.astype(jnp.int32), start_pos.astype(jnp.int32), win, q4, *kv_args)
    out = out[:B].reshape(B, n_kv_heads, 1, G, head_dim).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, 1, n_heads, head_dim)
