"""Worker load monitoring + busy-threshold load shedding for the frontend.

Reference parity: lib/llm/src/discovery/worker_monitor.rs (per-worker load
tracking from published stats) and lib/llm/src/http/service/busy_threshold.rs
(per-model thresholds on KV-block utilization and prefill pressure; when ALL
workers for a model exceed them, new requests are rejected 503).

The monitor subscribes to the same load topic the KV router consumes
(router/publisher.py LoadPublisher snapshots) — no new worker-side wiring.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from dynamo_tpu.router.protocols import LoadSnapshot, load_topic
from dynamo_tpu.runtime.liveness import LivenessTracker
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class BusyThresholds:
    """(ref: busy_threshold.rs BusyThresholdRequest fields)"""

    # fraction of KV blocks in use above which a worker counts as busy
    active_decode_blocks_threshold: Optional[float] = None
    # queued (not yet admitted) requests above which a worker counts as busy
    # (the prefill-pressure analog of the reference's prefill-token gauges)
    waiting_requests_threshold: Optional[int] = None

    @property
    def configured(self) -> bool:
        return (
            self.active_decode_blocks_threshold is not None
            or self.waiting_requests_threshold is not None
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "active_decode_blocks_threshold": self.active_decode_blocks_threshold,
            "waiting_requests_threshold": self.waiting_requests_threshold,
        }


class WorkerLoadMonitor:
    """Latest load snapshot per (worker, dp_rank) for one component."""

    def __init__(
        self,
        event_plane: Any,
        namespace: str,
        component: str,
        *,
        stale_after_s: float = 10.0,
        liveness: Optional[LivenessTracker] = None,
    ) -> None:
        self._plane = event_plane
        self._topic = load_topic(namespace, component)
        self.stale_after_s = stale_after_s
        self._loads: Dict[Tuple[int, int], Tuple[LoadSnapshot, float]] = {}
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        # Crash plane (runtime/liveness.py): the monitor already consumes
        # every load report, so it is where missed-report liveness lives —
        # the pump feeds the tracker (fencing stale incarnations out of
        # ``_loads`` too) and an evaluation task runs detection sweeps on
        # a fraction of the report cadence.
        self.liveness = liveness
        self._eval_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._sub = self._plane.subscribe(self._topic)
        self._task = asyncio.get_running_loop().create_task(
            self._pump(), name=f"worker-monitor:{self._topic}"
        )
        if self.liveness is not None:
            self._eval_task = asyncio.get_running_loop().create_task(
                self._evaluate_loop(), name=f"liveness:{self._topic}"
            )

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.aclose()
            self._sub = None
        for task, what in (
            (self._task, "worker-load monitor pump"),
            (self._eval_task, "liveness evaluate loop"),
        ):
            if task is not None:
                task.cancel()
                await reap_task(task, what, logger)
        self._task = None
        self._eval_task = None

    async def _evaluate_loop(self) -> None:
        # Half the report interval: detection latency error from sweep
        # granularity stays well inside the missed-report budget.
        interval = max(self.liveness.config.interval_s / 2.0, 0.01)
        while True:
            await asyncio.sleep(interval)
            try:
                self.liveness.evaluate()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("liveness evaluation sweep failed")

    async def _pump(self) -> None:
        async for _topic, payload in self._sub:
            try:
                snap = LoadSnapshot.from_dict(payload)
            except Exception:
                logger.exception("bad load snapshot payload")
                continue
            if self.liveness is not None:
                try:
                    verdict = self.liveness.observe_report(
                        snap.worker_id, snap.incarnation
                    )
                except Exception:
                    # The liveness.report chaos seam (or a real tracker
                    # bug) fired: the report is LOST before admission —
                    # exactly the condition detection exists for. Drop it;
                    # enough consecutive losses trip suspect/dead.
                    logger.debug(
                        "load report from %#x lost at the liveness seam",
                        snap.worker_id, exc_info=True,
                    )
                    continue
                if verdict == "stale":
                    continue  # a zombie incarnation's late publish
            self._loads[(snap.worker_id, snap.dp_rank)] = (snap, time.monotonic())

    def fresh_loads(self) -> Dict[Tuple[int, int], LoadSnapshot]:
        cutoff = time.monotonic() - self.stale_after_s
        return {k: s for k, (s, ts) in self._loads.items() if ts >= cutoff}

    def drop_worker(self, worker_id: int) -> None:
        for key in [k for k in self._loads if k[0] == worker_id]:
            self._loads.pop(key, None)

    # -- busy gating --------------------------------------------------------

    def _is_busy(self, snap: LoadSnapshot, th: BusyThresholds) -> bool:
        if th.active_decode_blocks_threshold is not None and snap.total_blocks:
            if snap.active_blocks / snap.total_blocks >= th.active_decode_blocks_threshold:
                return True
        if th.waiting_requests_threshold is not None:
            if snap.waiting >= th.waiting_requests_threshold:
                return True
        return False

    def all_busy(self, thresholds: BusyThresholds) -> bool:
        """True only when thresholds are configured, we have fresh data, and
        EVERY fresh worker exceeds them (ref: busy_threshold.rs middleware).
        No data ⇒ can't tell ⇒ don't shed."""
        if not thresholds.configured:
            return False
        loads = self.fresh_loads()
        if not loads:
            return False
        return all(self._is_busy(s, thresholds) for s in loads.values())
