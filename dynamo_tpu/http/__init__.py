"""OpenAI-compatible HTTP frontend (ref: lib/llm/src/http)."""

from dynamo_tpu.http.metrics import FrontendMetrics
from dynamo_tpu.http.model_manager import ModelEntry, ModelManager
from dynamo_tpu.http.service import HttpService

__all__ = ["FrontendMetrics", "HttpService", "ModelEntry", "ModelManager"]
