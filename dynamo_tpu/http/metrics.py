"""Frontend request metrics.

Reference parity: lib/llm/src/http/service/metrics.rs (request counters,
TTFT/ITL/duration histograms, in-flight gauges) with the canonical naming
scheme of lib/runtime/src/metrics/prometheus_names.rs.

Exemplars (tentpole part 3): the TTFT and request-duration histograms carry
the request's trace id as an OpenMetrics exemplar — rendered when the
scraper negotiates ``application/openmetrics-text`` — so a latency spike on
a dashboard links straight to ``/debug/traces?trace_id=…`` and the
``/debug/requests/{id}`` timeline captured for that request.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.openmetrics.exposition import (
    generate_latest as generate_openmetrics,
)

_SECONDS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)


class FrontendMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None) -> None:
        from dynamo_tpu.runtime import metric_names as mn

        self.registry = registry or CollectorRegistry()
        self.requests_total = Counter(
            mn.FRONTEND_REQUESTS_TOTAL,
            "HTTP requests by model/endpoint/status",
            ["model", "endpoint", "status"],
            registry=self.registry,
        )
        self.inflight = Gauge(
            mn.FRONTEND_INFLIGHT,
            "Currently executing requests",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            mn.FRONTEND_REQUEST_DURATION,
            "End-to-end request duration",
            ["model", "endpoint"],
            buckets=_SECONDS_BUCKETS,
            registry=self.registry,
        )
        self.ttft = Histogram(
            mn.FRONTEND_TTFT,
            "Time to first token (streaming requests)",
            ["model"],
            buckets=_SECONDS_BUCKETS,
            registry=self.registry,
        )
        self.itl = Histogram(
            mn.FRONTEND_ITL,
            "Latency between streamed tokens",
            ["model"],
            buckets=_SECONDS_BUCKETS,
            registry=self.registry,
        )
        self.output_tokens = Counter(
            mn.FRONTEND_OUTPUT_TOKENS_TOTAL,
            "Generated tokens",
            ["model"],
            registry=self.registry,
        )
        self.input_tokens = Counter(
            mn.FRONTEND_INPUT_TOKENS_TOTAL,
            "Prompt tokens",
            ["model"],
            registry=self.registry,
        )

    def render(self, openmetrics: bool = False) -> bytes:
        if openmetrics:
            return generate_openmetrics(self.registry)
        return generate_latest(self.registry)


class RequestTimer:
    """Per-request observation helper feeding FrontendMetrics.

    ``bind_context`` (called once the request's root span exists) attaches
    the trace id — from then on TTFT/duration observations carry it as an
    exemplar, and first-token/done lifecycle events are stamped onto the
    request's /debug timeline."""

    def __init__(
        self, metrics: FrontendMetrics, model: str, endpoint: str,
        *, itl_observer=None,
    ) -> None:
        self._m = metrics
        self._model = model
        self._endpoint = endpoint
        self._start = time.monotonic()
        self._last_token: Optional[float] = None
        self._done = False
        self._request_id: Optional[str] = None
        self._trace_id: Optional[str] = None
        # SLO inputs (runtime/trajectory.py SloTracker): the stream's TTFT
        # and summed ITL deltas, judged once at done().
        self._ttft_s: Optional[float] = None
        self._itl_sum = 0.0
        self._itl_n = 0
        # Optional tap on the same deltas the ITL histogram observes —
        # the overload controller's brownout machine reads its p50 SLA
        # signal here (runtime/overload.py observe_itl).
        self._itl_observer = itl_observer
        self._m.inflight.labels(model, endpoint).inc()

    def bind_context(self, context) -> None:
        """Adopt the request's id + active trace (runtime Context whose
        baggage carries a traceparent)."""
        from dynamo_tpu.runtime import lifecycle

        self._request_id = getattr(context, "id", None)
        self._trace_id = lifecycle.trace_id_of(context)
        lifecycle.record(
            self._request_id, "received",
            trace_id=self._trace_id,
            model=self._model, endpoint=self._endpoint,
        )

    def _exemplar(self) -> Optional[dict]:
        return {"trace_id": self._trace_id} if self._trace_id else None

    def on_token(self, count: int = 1) -> None:
        now = time.monotonic()
        if self._last_token is None:
            self._ttft_s = now - self._start
            self._m.ttft.labels(self._model).observe(
                now - self._start, exemplar=self._exemplar()
            )
            if self._request_id:
                from dynamo_tpu.runtime import lifecycle

                lifecycle.record(
                    self._request_id, "first_token",
                    trace_id=self._trace_id,
                    ttft_ms=round((now - self._start) * 1000, 3),
                )
        else:
            self._itl_sum += now - self._last_token
            self._itl_n += 1
            self._m.itl.labels(self._model).observe(now - self._last_token)
            if self._itl_observer is not None:
                self._itl_observer(now - self._last_token)
        self._last_token = now
        self._m.output_tokens.labels(self._model).inc(count)

    def count_tokens(self, count: int) -> None:
        """Output-token accounting WITHOUT latency observations — secondary
        n>1 choice streams would corrupt TTFT/ITL with cross-stream deltas."""
        self._m.output_tokens.labels(self._model).inc(count)

    def on_input_tokens(self, count: int) -> None:
        self._m.input_tokens.labels(self._model).inc(count)

    def done(self, status: int) -> None:
        if self._done:  # idempotent: double-finish must not skew gauges
            return
        self._done = True
        self._m.inflight.labels(self._model, self._endpoint).dec()
        self._m.requests_total.labels(self._model, self._endpoint, str(status)).inc()
        self._m.request_duration.labels(self._model, self._endpoint).observe(
            time.monotonic() - self._start, exemplar=self._exemplar()
        )
        if self._request_id:
            from dynamo_tpu.runtime import lifecycle

            lifecycle.record(
                self._request_id, "done",
                trace_id=self._trace_id, status=status,
            )
        if status != 499 and (self._ttft_s is not None or status >= 429):
            # SLO verdict (no-op while no SLA is configured): one stream,
            # did TTFT and mean ITL land inside the SLA. Token-less
            # failures count too — sheds (429/503/504) and errors never
            # met the SLA, and skipping them would leave goodput reading
            # 1.0 through a total outage. Token-less 2xx (embeddings,
            # unary helpers) stay out: they have no latency SLA. Client
            # aborts (499) stay out entirely — a user walking away says
            # nothing about the server's SLA, and counting them would
            # burn error budget during perfectly healthy serving.
            from dynamo_tpu.runtime.trajectory import global_slo

            global_slo().note_stream(
                self._trace_id,
                ttft_s=self._ttft_s,
                mean_itl_s=(
                    self._itl_sum / self._itl_n if self._itl_n else None
                ),
                status=status,
            )
