"""OpenAI-compatible HTTP frontend (aiohttp).

Reference parity: lib/llm/src/http/service/{service_v2.rs,openai.rs} — the
axum server with /v1/chat/completions (:865), /v1/completions (:327),
/v1/models (:1530), /v1/embeddings (:641), SSE streaming with disconnect
handling (disconnect.rs), and the system routes /health /live /metrics
(runtime/src/system_status_server.rs). aiohttp replaces axum (no fastapi in
this environment; aiohttp's streaming response maps 1:1 onto SSE).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, AsyncIterator, Dict, Optional

from aiohttp import web

from dynamo_tpu.llm.protocols.common import FinishReason, PostprocessedOutput
from dynamo_tpu.llm.protocols.openai import (
    OpenAIError,
    chat_chunk,
    chat_logprobs_block,
    completion_chunk,
    completion_envelope,
    completion_logprobs_block,
    gen_id,
    model_list,
    parse_n,
    usage_block,
)
from dynamo_tpu.http.metrics import FrontendMetrics, RequestTimer
from dynamo_tpu.http.model_manager import ModelManager
from dynamo_tpu.http.worker_monitor import BusyThresholds
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.overload import (
    AdmissionTicket,
    OverloadController,
    OverloadShedError,
)
from dynamo_tpu.runtime.tasks import TaskTracker

logger = logging.getLogger(__name__)


def _error_kind_of(exc: BaseException) -> Optional[str]:
    """Structured ``error_kind`` for an exception the pipeline raised —
    only for failure classes with a meaningful taxonomy label (transfer,
    transport, deadline); generic programming errors stay unlabeled
    rather than masquerading as ``decode``."""
    from dynamo_tpu.disagg.errors import DisaggTransferError, classify_failure
    from dynamo_tpu.parsers.incremental import ToolCallParseError
    from dynamo_tpu.runtime.component import NoInstancesError

    if isinstance(exc, DisaggTransferError):
        return "disagg"
    if isinstance(exc, NoInstancesError):
        return "no_instances"
    if isinstance(exc, ToolCallParseError):
        # Tool-call parser BUG (parsers/jail.py wraps anything escaping
        # the dialect machines): terminal typed frame, never a dropped
        # stream — and never disguised as an upstream failure.
        return "tool_call_parse"
    if isinstance(exc, (ConnectionError, TimeoutError, asyncio.TimeoutError)):
        return classify_failure(exc)
    return None


def _status_of_kind(kind: Optional[str]) -> int:
    """HTTP status for a terminal engine error carrying ``error_kind``:
    an expired budget is the client's 504, an upstream worker/link
    failure a 502 — neither is the frontend's own 500."""
    if kind == "timeout":
        return 504
    if kind in ("connection", "disagg", "no_instances"):
        return 502
    return 500


def _err_type_of_kind(kind: Optional[str]) -> str:
    if kind == "timeout":
        return "deadline_exceeded"
    if kind in ("connection", "disagg", "no_instances"):
        return "upstream_error"
    return "internal_error"


class HttpService:
    """The frontend server. Construct, then ``await start()`` / ``run()``."""

    def __init__(
        self,
        model_manager: Optional[ModelManager] = None,
        *,
        host: str = "0.0.0.0",
        port: int = 8000,
        metrics: Optional[FrontendMetrics] = None,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        overload: Optional[OverloadController] = None,
    ) -> None:
        # TLS termination (ref: service_v2.rs enable_tls + rustls config).
        self._ssl_context = None
        if tls_cert and tls_key:
            import ssl

            self._ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_context.load_cert_chain(tls_cert, tls_key)
        # NOT `or`: an empty ModelManager is falsy (__len__ == 0) and would be
        # silently replaced, detaching the caller's manager from the server.
        self.models = model_manager if model_manager is not None else ModelManager()
        self.host = host
        self.port = port
        self.metrics = metrics or FrontendMetrics()
        # Overload armor (runtime/overload.py): bounded EDF admission +
        # brownout. None = unguarded (the pre-PR 8 behavior); the frontend
        # entrypoint constructs one by default.
        self.overload = overload
        self.tracker = TaskTracker("http")
        # model name → busy thresholds (ref: busy_threshold.rs; checked
        # against the model's WorkerLoadMonitor when one is attached)
        self.busy_thresholds: Dict[str, BusyThresholds] = {}
        from dynamo_tpu.http.audit import AuditBus

        # Request auditing (ref: lib/llm/src/audit): DYN_TPU_AUDIT policy.
        self.audit = AuditBus.from_env()
        self._runner: Optional[web.AppRunner] = None
        self._site: Optional[web.TCPSite] = None
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat_completions)
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_post("/v1/embeddings", self._embeddings)
        app.router.add_get("/v1/models", self._models_route)
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics_route)
        app.router.add_get("/busy_threshold", self._busy_threshold_list)
        app.router.add_post("/busy_threshold", self._busy_threshold_route)
        app.router.add_post("/v1/responses", self._responses)
        app.router.add_post("/v1/images/generations", self._images)
        app.router.add_post("/clear_kv_blocks", self._clear_kv_blocks)
        app.router.add_get("/debug/overload", self._debug_overload)
        app.router.add_get("/debug/parser", self._debug_parser)
        app.router.add_get("/debug/trajectory", self._debug_trajectories)
        app.router.add_get(
            "/debug/trajectory/{trace_id}", self._debug_trajectory
        )
        app.router.add_get("/openapi.json", self._openapi)
        return app

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> int:
        """Bind and serve; returns the bound port (useful with port=0)."""
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(
            self._runner, self.host, self.port, ssl_context=self._ssl_context
        )
        await self._site.start()
        sockets = self._site._server.sockets  # type: ignore[union-attr]
        self.port = sockets[0].getsockname()[1]
        logger.info("HTTP frontend listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self, grace_period: float = 30.0) -> None:
        await self.tracker.drain(grace_period)
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- system routes -----------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "healthy" if len(self.models) else "no_models", "models": self.models.names()}
        )

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics_route(self, request: web.Request) -> web.Response:
        openmetrics = "application/openmetrics-text" in request.headers.get(
            "Accept", ""
        )
        from dynamo_tpu.parsers.observe import parser_plane
        from dynamo_tpu.runtime.trajectory import render_trajectory_metrics

        if openmetrics:
            # OpenMetrics exposition carries trace-id exemplars on the TTFT
            # and request-duration histograms (see http/metrics.py).
            body = self.metrics.render(openmetrics=True)
            # Splice the overload + parser + SLO families in BEFORE the
            # # EOF terminator prometheus_client already appended.
            extra = (
                parser_plane().metrics.render(openmetrics=True)
                + "\n" + render_trajectory_metrics(openmetrics=True)
            )
            if self.overload is not None:
                extra = (
                    self.overload.metrics.render(openmetrics=True)
                    + "\n" + extra
                )
            stripped = body.rstrip()
            if stripped.endswith(b"# EOF"):
                stripped = stripped[: -len(b"# EOF")].rstrip()
            body = stripped + b"\n" + extra.encode() + b"\n# EOF\n"
            return web.Response(
                body=body, content_type="application/openmetrics-text",
            )
        body = self.metrics.render()
        if self.overload is not None:
            # The frontend's controller is the one that actually admits
            # and sheds — its families must be on THIS scrape surface.
            body = body + self.overload.metrics.render().encode() + b"\n"
        # Parser plane (ALL_PARSER): the jail runs inside THIS process's
        # SSE handlers — tool-call streaming health scrapes here.
        body = body + parser_plane().metrics.render().encode() + b"\n"
        # SLO plane (ALL_SLO): goodput/burn-rate/phase gauges are fed by
        # THIS process's finished streams — they belong on this scrape.
        body = body + render_trajectory_metrics().encode() + b"\n"
        return web.Response(body=body, content_type="text/plain")

    async def _models_route(self, request: web.Request) -> web.Response:
        return web.json_response(model_list(self.models.openai_model_list()))

    async def _debug_trajectories(self, request: web.Request) -> web.Response:
        """Fleet trajectory index (the frontend has no system server; same
        body as runtime/system_server.py's route — one shared helper)."""
        from dynamo_tpu.runtime.trajectory import trajectory_index

        return web.json_response(trajectory_index())

    async def _debug_trajectory(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.trajectory import trajectory_view

        tid = request.match_info["trace_id"]
        stitched = trajectory_view(tid)
        if stitched is None:
            return web.json_response(
                {"error": f"no trajectory for trace {tid!r}"}, status=404
            )
        return web.json_response(stitched)

    async def _debug_overload(self, request: web.Request) -> web.Response:
        """Overload-plane snapshot + the 'overload' flight ring (the
        frontend has no system server; this is its /debug/flight slice)."""
        if self.overload is None:
            return web.json_response({"enabled": False})
        try:
            limit = int(request.query.get("limit", 256))
        except ValueError:
            limit = 256
        return web.json_response(
            {
                "enabled": True,
                **self.overload.snapshot(),
                "events": self.overload.flight.snapshot(limit=limit),
            }
        )

    async def _debug_parser(self, request: web.Request) -> web.Response:
        """Parser-plane snapshot + the 'parser' flight ring (the frontend
        has no system server; this is its /debug/flight slice — same
        shape as /debug/overload)."""
        from dynamo_tpu.parsers.observe import parser_plane

        try:
            limit = int(request.query.get("limit", 256))
        except ValueError:
            limit = 256
        plane = parser_plane()
        return web.json_response(
            {
                **plane.snapshot(),
                "events": plane.flight.snapshot(limit=limit),
            }
        )

    async def _busy_threshold_list(self, request: web.Request) -> web.Response:
        """(ref: busy_threshold.rs GET — list configured thresholds)"""
        return web.json_response(
            {
                "thresholds": [
                    {"model": m, **t.to_dict()}
                    for m, t in sorted(self.busy_thresholds.items())
                ]
            }
        )

    async def _busy_threshold_route(self, request: web.Request) -> web.Response:
        """Get or set one model's thresholds (ref: busy_threshold.rs POST)."""
        body, err = await self._read_json(request)
        if err is not None:
            return err
        model = body.get("model")
        if not model:
            return _error_response(OpenAIError("'model' is required"))
        has_values = (
            "active_decode_blocks_threshold" in body
            or "waiting_requests_threshold" in body
        )
        if has_values:
            self.busy_thresholds[model] = BusyThresholds(
                active_decode_blocks_threshold=body.get(
                    "active_decode_blocks_threshold"
                ),
                waiting_requests_threshold=body.get("waiting_requests_threshold"),
            )
        th = self.busy_thresholds.get(model, BusyThresholds())
        return web.json_response({"model": model, **th.to_dict()})

    async def _clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Flush worker prefix caches (ref: clear_kv_blocks.rs). Body may
        scope to one model: {"model": "..."}; default = every model."""
        body = {}
        if request.can_read_body:
            body, err = await self._read_json(request)
            if err is not None:
                return err
        model = (body or {}).get("model")
        names = [model] if model else self.models.names()
        results: Dict[str, Any] = {}
        for name in names:
            entry = self.models.get(name)
            if entry is None:
                results[name] = {"error": "model not found"}
                continue
            clear = entry.admin.get("clear_kv")
            if clear is None:
                results[name] = {"error": "no clear_kv hook (local pipeline)"}
                continue
            try:
                results[name] = {"cleared_blocks": await clear()}
            except Exception as exc:
                logger.exception("clear_kv_blocks for %s failed", name)
                results[name] = {"error": str(exc)}
        return web.json_response({"results": results})

    async def _responses(self, request: web.Request) -> web.StreamResponse:
        """OpenAI Responses API over the chat pipeline (ref: openai.rs:1179
        — the reference also converts Responses → chat internally;
        text-only input, unsupported fields rejected 501)."""
        body, err = await self._read_json(request)
        if err is not None:
            return err
        for field in ("tools", "previous_response_id", "reasoning"):
            if body.get(field):
                return _error_response(
                    OpenAIError(
                        f"'{field}' is not supported on /v1/responses",
                        status=501, err_type="not_implemented",
                    )
                )
        inp = body.get("input")
        if isinstance(inp, str):
            messages = [{"role": "user", "content": inp}]
        elif isinstance(inp, list) and all(
            isinstance(m, dict) and isinstance(m.get("content"), str) for m in inp
        ):
            messages = [
                {"role": m.get("role", "user"), "content": m["content"]} for m in inp
            ]
        else:
            return _error_response(
                OpenAIError(
                    "'input' must be a string or a list of text messages "
                    "(non-text input is not supported)",
                    status=501, err_type="not_implemented",
                )
            )
        chat_body: Dict[str, Any] = {
            "model": body.get("model", ""),
            "messages": messages,
            "stream": False,
        }
        if body.get("max_output_tokens") is not None:
            chat_body["max_tokens"] = body["max_output_tokens"]
        for k in ("temperature", "top_p"):
            if body.get(k) is not None:
                chat_body[k] = body[k]
        model = chat_body["model"]
        entry = self.models.get(model)
        if entry is None:
            return _error_response(
                OpenAIError(f"model '{model}' not found", status=404,
                            err_type="not_found_error")
            )
        # The Responses API rides the same chat generation pipeline, so it
        # gets the same overload armor: client deadline, EDF admission,
        # and the brownout output clamp (a saturating burst must not
        # tunnel past the plane through this endpoint).
        deadline, derr = self._parse_deadline(request, body)
        if derr is not None:
            return derr
        timer = RequestTimer(
            self.metrics, model, "responses",
            itl_observer=(
                self.overload.observe_itl if self.overload is not None else None
            ),
        )
        ctx = Context(baggage={"model": model}, deadline=deadline)
        stream = bool(body.get("stream", False))
        rid = gen_id("resp")
        ticket: Optional[AdmissionTicket] = None
        if self.overload is not None:
            self.overload.apply_default_deadline(ctx)
            try:
                ticket = await self.overload.admit(ctx)
            except OverloadShedError as exc:
                timer.done(exc.status)
                return _shed_response(exc)

        def envelope(status: str, output=None, usage=None) -> Dict[str, Any]:
            resp: Dict[str, Any] = {
                "id": rid, "object": "response", "status": status,
                "model": model, "output": output or [],
            }
            if usage is not None:
                resp["usage"] = usage
            return resp

        ok = False
        try:
            if self.overload is not None:
                clamped = self.overload.clamp_max_tokens(
                    chat_body.get("max_tokens")
                )
                if clamped is not None and clamped != chat_body.get("max_tokens"):
                    chat_body["max_tokens"] = clamped
            with self.tracker.guard():
                if stream:
                    resp = await self._responses_stream(
                        request, chat_body, entry, ctx, timer, envelope
                    )
                    ok = True
                    return resp
                text_parts: list = []
                prompt_tokens = 0
                completion_tokens = 0
                async for item in entry.engine.generate(chat_body, ctx):
                    if isinstance(item, dict):
                        if item.get("annotation") == "_prompt_tokens":
                            prompt_tokens = item["value"]
                            timer.on_input_tokens(prompt_tokens)
                        continue
                    out: PostprocessedOutput = item
                    if out.error:
                        ekind = getattr(out, "error_kind", None)
                        raise OpenAIError(
                            out.error, status=_status_of_kind(ekind),
                            err_type=_err_type_of_kind(ekind), kind=ekind,
                        )
                    if out.text:
                        text_parts.append(out.text)
                    if out.token_ids:
                        completion_tokens += len(out.token_ids)
                        timer.on_token(len(out.token_ids))
                timer.done(200)
                ok = True
                return web.json_response(
                    envelope(
                        "completed",
                        output=[
                            {
                                "type": "message",
                                "role": "assistant",
                                "content": [
                                    {
                                        "type": "output_text",
                                        "text": "".join(text_parts),
                                    }
                                ],
                            }
                        ],
                        usage={
                            "input_tokens": prompt_tokens,
                            "output_tokens": completion_tokens,
                            "total_tokens": prompt_tokens + completion_tokens,
                        },
                    )
                )
        except OpenAIError as exc:
            timer.done(exc.status)
            return _error_response(exc)
        except asyncio.CancelledError:
            ctx.kill()
            timer.done(499)
            raise
        except Exception as exc:
            error_kind = _error_kind_of(exc)
            logger.exception("responses failed")
            status = _status_of_kind(error_kind)
            timer.done(status)
            return _error_response(
                OpenAIError(
                    str(exc), status=status,
                    err_type=_err_type_of_kind(error_kind), kind=error_kind,
                )
            )
        finally:
            if ticket is not None:
                self.overload.release(ticket, ok=ok)

    async def _responses_stream(
        self, request: web.Request, chat_body, entry, ctx: Context,
        timer: RequestTimer, envelope,
    ) -> web.StreamResponse:
        """Responses API streaming: typed SSE events
        (response.created → response.output_text.delta* →
        response.output_text.done → response.completed), each framed as
        ``event: <type>`` + ``data: <json>`` with sequence numbers."""
        response = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": ctx.id,
            },
        )
        await response.prepare(request)
        seq = 0

        async def send(event_type: str, payload: Dict[str, Any]) -> None:
            nonlocal seq
            payload = {"type": event_type, "sequence_number": seq, **payload}
            seq += 1
            with _suppress_conn_errors():
                await response.write(
                    f"event: {event_type}\ndata: {json.dumps(payload)}\n\n".encode()
                )

        await send("response.created", {"response": envelope("in_progress")})
        text_parts: list = []
        prompt_tokens = 0
        completion_tokens = 0
        status = 200
        try:
            async for item in entry.engine.generate(chat_body, ctx):
                if isinstance(item, dict):
                    if item.get("annotation") == "_prompt_tokens":
                        prompt_tokens = item["value"]
                        timer.on_input_tokens(prompt_tokens)
                    continue
                out: PostprocessedOutput = item
                if out.error:
                    await send(
                        "error",
                        {
                            "message": out.error,
                            "code": _err_type_of_kind(
                                getattr(out, "error_kind", None)
                            ),
                            "error_kind": getattr(out, "error_kind", None),
                        },
                    )
                    # Terminal event so SDK consumers waiting on a final
                    # response.* event resolve instead of hanging.
                    await send(
                        "response.failed", {"response": envelope("failed")}
                    )
                    status = 500
                    break
                if out.token_ids:
                    completion_tokens += len(out.token_ids)
                    timer.on_token(len(out.token_ids))
                if out.text:
                    text_parts.append(out.text)
                    await send(
                        "response.output_text.delta",
                        {"item_id": "msg_0", "output_index": 0,
                         "content_index": 0, "delta": out.text},
                    )
            if status == 200:
                full = "".join(text_parts)
                await send(
                    "response.output_text.done",
                    {"item_id": "msg_0", "output_index": 0,
                     "content_index": 0, "text": full},
                )
                await send(
                    "response.completed",
                    {
                        "response": envelope(
                            "completed",
                            output=[
                                {
                                    "type": "message",
                                    "role": "assistant",
                                    "content": [
                                        {"type": "output_text", "text": full}
                                    ],
                                }
                            ],
                            usage={
                                "input_tokens": prompt_tokens,
                                "output_tokens": completion_tokens,
                                "total_tokens": prompt_tokens + completion_tokens,
                            },
                        )
                    },
                )
        except asyncio.CancelledError:
            ctx.kill()
            timer.done(499)
            raise
        except Exception as exc:
            # The SSE response is already prepared and partially written —
            # returning a fresh JSON response here would corrupt the stream.
            # Emit a terminal error + response.failed pair and end the stream
            # cleanly instead (mirrors _stream_response's contract).
            logger.exception("responses stream failed")
            await send("error", {"message": str(exc), "code": "internal_error"})
            await send("response.failed", {"response": envelope("failed")})
            status = 500
        finally:
            if not ctx.stopped:
                ctx.stop_generating(reason="response-stream-finished")
        timer.done(status)
        with _suppress_conn_errors():
            await response.write_eof()
        return response

    async def _openapi(self, request: web.Request) -> web.Response:
        """Minimal OpenAPI description of the served routes (ref: the
        reference's RouteDoc/OpenAPI surface)."""
        from dynamo_tpu._version import __version__

        def op(summary, *, body=False):
            doc: Dict[str, Any] = {"summary": summary, "responses": {"200": {"description": "OK"}}}
            if body:
                doc["requestBody"] = {
                    "content": {"application/json": {"schema": {"type": "object"}}}
                }
            return doc

        paths = {
            "/v1/chat/completions": {"post": op("OpenAI chat completions (SSE streaming via stream=true)", body=True)},
            "/v1/completions": {"post": op("OpenAI text completions", body=True)},
            "/v1/responses": {"post": op("OpenAI Responses API (text-only)", body=True)},
            "/v1/embeddings": {"post": op("Embeddings", body=True)},
            "/v1/models": {"get": op("List served models")},
            "/health": {"get": op("Readiness: healthy when ≥1 model is served")},
            "/live": {"get": op("Liveness")},
            "/metrics": {"get": op("Prometheus metrics")},
            "/busy_threshold": {
                "get": op("List busy thresholds"),
                "post": op("Get/set one model's busy thresholds", body=True),
            },
            "/clear_kv_blocks": {"post": op("Flush worker KV prefix caches", body=True)},
            "/debug/parser": {"get": op("Tool-call parser plane: stream outcomes, degrades, parser flight ring")},
            "/debug/trajectory": {"get": op("Fleet trajectory index (recent + slow/error, SLO snapshot)")},
            "/debug/trajectory/{trace_id}": {"get": op("One stitched cross-worker request trajectory")},
        }
        return web.json_response(
            {
                "openapi": "3.0.0",
                "info": {"title": "dynamo_tpu frontend", "version": __version__},
                "paths": paths,
            }
        )

    def _model_busy(self, model: str, entry) -> bool:
        th = self.busy_thresholds.get(model)
        if th is None or entry.monitor is None:
            return False
        return entry.monitor.all_busy(th)

    # -- OpenAI routes -----------------------------------------------------

    async def _chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_generation(request, kind="chat")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_generation(request, kind="completion")

    async def _embeddings(self, request: web.Request) -> web.Response:
        body, err = await self._read_json(request)
        if err is not None:
            return err
        model = body.get("model", "")
        entry = self.models.get(model)
        if entry is None or entry.card.model_type != "embedding":
            return _error_response(
                OpenAIError(f"model '{model}' does not support embeddings", status=404, err_type="not_found_error")
            )
        timer = RequestTimer(self.metrics, model, "embeddings")
        try:
            ctx = Context()
            result = None
            async for item in entry.engine.generate(body, ctx):
                result = item
            timer.done(200)
            return web.json_response(result)
        except OpenAIError as exc:
            timer.done(exc.status)
            return _error_response(exc)
        except Exception as exc:  # pragma: no cover
            logger.exception("embeddings failed")
            timer.done(500)
            return _error_response(OpenAIError(str(exc), status=500, err_type="internal_error"))

    async def _images(self, request: web.Request) -> web.Response:
        """OpenAI images API (ref: openai.rs:1552 images route) — routes to
        a model of type 'image' (e.g. a diffusion engine worker); the engine
        yields {b64_json | url} items, folded into the images response."""
        body, err = await self._read_json(request)
        if err is not None:
            return err
        model = body.get("model", "")
        prompt = body.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            return _error_response(OpenAIError("'prompt' is required"))
        entry = self.models.get(model)
        if entry is None or entry.card.model_type != "image":
            return _error_response(
                OpenAIError(
                    f"model '{model}' does not support image generation",
                    status=404, err_type="not_found_error",
                )
            )
        timer = RequestTimer(self.metrics, model, "images")
        try:
            ctx = Context()
            data = []
            async for item in entry.engine.generate(body, ctx):
                if isinstance(item, dict) and "error" in item:
                    raise OpenAIError(
                        str(item["error"]), status=500, err_type="internal_error"
                    )
                data.append(item)
            timer.done(200)
            return web.json_response({"created": int(time.time()), "data": data})
        except OpenAIError as exc:
            timer.done(exc.status)
            return _error_response(exc)
        except Exception as exc:  # pragma: no cover
            logger.exception("image generation failed")
            timer.done(500)
            return _error_response(
                OpenAIError(str(exc), status=500, err_type="internal_error")
            )

    async def _read_json(self, request: web.Request):
        try:
            return await request.json(), None
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None, _error_response(OpenAIError("invalid JSON body"))

    async def _serve_generation(self, request: web.Request, kind: str) -> web.StreamResponse:
        body, err = await self._read_json(request)
        if err is not None:
            return err
        if not isinstance(body, dict):
            return _error_response(OpenAIError("request body must be a JSON object"))
        model = body.get("model", "")
        entry = self.models.get(model)
        if entry is None:
            return _error_response(
                OpenAIError(f"model '{model}' not found", status=404, err_type="not_found_error")
            )
        stream = bool(body.get("stream", False))
        try:
            n = parse_n(body)
        except OpenAIError as exc:
            return _error_response(exc)
        if stream and n > 1:
            return _error_response(
                OpenAIError(
                    "streaming with n > 1 is not supported; request unary "
                    "or n=1", status=400,
                )
            )
        endpoint = "chat_completions" if kind == "chat" else "completions"
        # W3C trace propagation (ref: logging.rs:72): an incoming
        # traceparent joins the caller's trace; spans flow via baggage.
        traceparent = request.headers.get("traceparent")
        # Gateway pin (EPP header hint, gateway/epp.py): the inference
        # gateway already ran KV-aware selection — carry the pin to the
        # request-plane picker. The body key is trusted-infra-only: strip
        # anything a client smuggled into the JSON before honoring the
        # header (otherwise any client could steer load to one worker).
        body.pop("_pinned_worker", None)
        pin = request.headers.get("x-dynamo-worker")
        if pin:
            try:
                body["_pinned_worker"] = int(pin.split(":", 1)[0])
            except ValueError:
                pass
        if self._model_busy(model, entry):
            # All workers over threshold: shed before any work is queued
            # (ref: busy_threshold.rs middleware → 503).
            resp = _error_response(
                OpenAIError(
                    f"all workers for model '{model}' are busy; retry later",
                    status=503,
                    err_type="service_unavailable",
                )
            )
            resp.headers["Retry-After"] = "1"
            return resp
        # Client deadline (overload armor): header wins over the body key;
        # the budget lands in Context.deadline and rides the request plane
        # end to end — engine admission sheds it expired, the disagg pull
        # timeouts shrink to it.
        deadline, err = self._parse_deadline(request, body)
        if err is not None:
            return err
        timer = RequestTimer(
            self.metrics, model, endpoint,
            itl_observer=(
                self.overload.observe_itl if self.overload is not None else None
            ),
        )
        baggage: Dict[str, Any] = {"model": model}
        if traceparent:
            baggage["traceparent"] = traceparent
        ctx = Context(baggage=baggage, deadline=deadline)
        from dynamo_tpu.utils.tracing import span

        ticket: Optional[AdmissionTicket] = None
        ok = False
        try:
            # Root span opens BEFORE admission so the overload queue wait
            # is a child span inside the trace (the trajectory plane's
            # "queue" phase) instead of invisible pre-trace time.
            with self.tracker.guard(), span(
                f"http.{endpoint}", ctx, model=model, stream=stream
            ):
                # The root span just wrote its traceparent into the context
                # baggage: binding here gives the timer (exemplars) and the
                # lifecycle timeline the request's trace id.
                timer.bind_context(ctx)
                if self.overload is not None:
                    self.overload.apply_default_deadline(ctx)
                    with span("overload.queue", ctx) as qsp:
                        ticket = await self.overload.admit(ctx)
                        qsp.attributes["queued_s"] = round(
                            ticket.queue_delay_s, 4
                        )
                    # Brownout output clamp: under pressure nobody gets an
                    # unbounded completion (no-op while healthy). Inside
                    # the try so NOTHING between admit and release can
                    # leak the admission slot.
                    clamped = self.overload.clamp_max_tokens(
                        body.get("max_tokens")
                    )
                    if clamped is not None and clamped != body.get("max_tokens"):
                        body["max_tokens"] = clamped
                if stream:
                    resp = await self._stream_response(
                        request, body, entry, ctx, kind, timer
                    )
                else:
                    resp = await self._unary_response(
                        body, entry, ctx, kind, timer, n
                    )
                ok = True
                return resp
        except OverloadShedError as exc:
            timer.done(exc.status)
            return _shed_response(exc)
        except OpenAIError as exc:
            timer.done(exc.status)
            return _error_response(exc)
        except asyncio.CancelledError:
            ctx.kill()
            timer.done(499)
            raise
        except Exception as exc:
            # Typed upstream failures (strict-disagg transfer death, a
            # worker link dropping, a deadline blown inside the stack)
            # carry their taxonomy label instead of a bare 500.
            error_kind = _error_kind_of(exc)
            logger.exception("generation failed")
            status = _status_of_kind(error_kind)
            timer.done(status)
            return _error_response(
                OpenAIError(
                    str(exc), status=status,
                    err_type=_err_type_of_kind(error_kind), kind=error_kind,
                )
            )
        finally:
            if ticket is not None:
                self.overload.release(ticket, ok=ok)

    def _parse_deadline(self, request: web.Request, body: Dict[str, Any]):
        """(absolute monotonic deadline | None, error response | None).
        ``x-dynamo-deadline-ms`` header wins; the ``deadline_ms`` body key
        is accepted for clients that can't set headers and is stripped
        either way so it never reaches preprocessing."""
        raw = request.headers.get("x-dynamo-deadline-ms")
        body_raw = body.pop("deadline_ms", None)
        if raw is None:
            raw = body_raw
        if raw is None:
            return None, None
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            ms = -1.0
        if ms <= 0 or not ms == ms:  # rejects NaN too
            return None, _error_response(
                OpenAIError(
                    "'deadline_ms' must be a positive number of "
                    "milliseconds (header x-dynamo-deadline-ms or body "
                    "key deadline_ms)"
                )
            )
        return time.monotonic() + ms / 1000.0, None

    # -- unary -------------------------------------------------------------

    async def _collect_one(
        self, body: Dict[str, Any], entry, ctx: Context, timer: RequestTimer,
        *, primary: bool = True,
    ):
        """Fold one engine stream → (text, finish, prompt_tokens,
        completion_tokens, logprob_entries). Only the primary stream feeds
        latency histograms (secondary n>1 streams would corrupt TTFT/ITL)."""
        text_parts = []
        finish: Optional[FinishReason] = None
        prompt_tokens = 0
        completion_tokens = 0
        logprob_entries: list = []
        async for item in entry.engine.generate(body, ctx):
            if isinstance(item, dict) and item.get("annotation") == "_prompt_tokens":
                prompt_tokens = item["value"]
                continue
            if isinstance(item, dict):
                continue  # other annotations are streaming-only
            out: PostprocessedOutput = item
            if out.error:
                kind = getattr(out, "error_kind", None)
                raise OpenAIError(
                    out.error, status=_status_of_kind(kind),
                    err_type=_err_type_of_kind(kind), kind=kind,
                )
            if out.text:
                text_parts.append(out.text)
            if out.token_ids:
                if primary:
                    timer.on_token(len(out.token_ids))
                else:
                    timer.count_tokens(len(out.token_ids))
            if out.logprobs:
                logprob_entries.extend(out.logprobs)
            completion_tokens = out.cumulative_tokens or completion_tokens
            if out.finish_reason is not None:
                finish = out.finish_reason
        return (
            "".join(text_parts), finish, prompt_tokens, completion_tokens,
            logprob_entries,
        )

    def _chat_choice(
        self, entry, body: Dict[str, Any], text: str, finish_str: str, index: int,
        logprob_entries=None,
    ) -> Dict[str, Any]:
        """Parse one completed chat message into an OpenAI choice entry
        (reasoning tags + tool-call dialects; ref: lib/parsers)."""
        from dynamo_tpu.parsers import detect_and_parse_tool_calls, split_reasoning

        reasoning, content = split_reasoning(
            text, style=entry.card.reasoning_style
        )
        message: Dict[str, Any] = {"role": "assistant", "content": content}
        if body.get("tools"):
            # Same dialect pin as the streaming jail (unary/stream parity).
            calls, content = detect_and_parse_tool_calls(
                content, dialect=getattr(entry.card, "tool_call_dialect", None)
            )
            message["content"] = content
            if calls:
                message["tool_calls"] = [c.to_openai() for c in calls]
                finish_str = "tool_calls"
        if reasoning:
            message["reasoning_content"] = reasoning
        return {
            "index": index,
            "message": message,
            "logprobs": (
                chat_logprobs_block(logprob_entries) if logprob_entries else None
            ),
            "finish_reason": finish_str,
        }

    async def _unary_response(
        self,
        body: Dict[str, Any],
        entry,
        ctx: Context,
        kind: str,
        timer: RequestTimer,
        n: int,
    ) -> web.Response:
        rid = gen_id("chatcmpl" if kind == "chat" else "cmpl")
        if n <= 1:
            results = [await self._collect_one(body, entry, ctx, timer)]
        else:
            # n > 1: n independent engine requests (shared-prefix prefill is
            # served from the cache; sampling diverges per slot). OpenAI
            # usage counts the prompt once and sums completions. Child
            # contexts inherit the request's deadline and hard-kill.
            contexts = [ctx.child() for _ in range(n)]
            tasks = [
                asyncio.ensure_future(
                    self._collect_one(
                        dict(body), entry, c, timer, primary=(i == 0)
                    )
                )
                for i, c in enumerate(contexts)
            ]
            try:
                results = await asyncio.gather(*tasks)
            except BaseException:
                # One choice failed/cancelled: tear the siblings down and
                # WAIT for them — they must not outlive the tracker guard.
                for c in contexts:
                    c.stop_generating(reason="sibling-choice-failed")
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                raise
        prompt_tokens = results[0][2]
        timer.on_input_tokens(prompt_tokens)
        completion_tokens = sum(r[3] for r in results)
        usage = usage_block(prompt_tokens, completion_tokens)
        text = results[0][0]  # primary choice (audit record)
        choices = []
        for i, (choice_text, finish, _pt, _ct, lp_entries) in enumerate(results):
            finish_str = (finish or FinishReason.EOS).to_openai()
            if kind == "chat":
                choices.append(
                    self._chat_choice(
                        entry, body, choice_text, finish_str, i, lp_entries
                    )
                )
            else:
                choices.append(
                    {
                        "index": i, "text": choice_text,
                        "logprobs": (
                            completion_logprobs_block(lp_entries)
                            if lp_entries else None
                        ),
                        "finish_reason": finish_str,
                    }
                )
        finish_str = choices[0]["finish_reason"]
        payload = completion_envelope(
            rid, entry.name,
            object_="chat.completion" if kind == "chat" else "text_completion",
            choices=choices, usage=usage,
        )
        timer.done(200)
        if self.audit.enabled:
            from dynamo_tpu.http.audit import AuditRecord

            self.audit.publish(
                AuditRecord(
                    request_id=ctx.id, model=entry.name, endpoint=kind,
                    requested_streaming=False, request=body,
                    response_text=text, finish_reason=finish_str, status=200,
                )
            )
        return web.json_response(payload)

    # -- streaming ---------------------------------------------------------

    async def _stream_response(
        self,
        request: web.Request,
        body: Dict[str, Any],
        entry,
        ctx: Context,
        kind: str,
        timer: RequestTimer,
    ) -> web.StreamResponse:
        rid = gen_id("chatcmpl" if kind == "chat" else "cmpl")
        include_usage = bool((body.get("stream_options") or {}).get("include_usage"))

        # Pull the first stream item BEFORE sending headers: preprocessing
        # (validation, templating, tokenization) raises on the first item, and
        # those failures must surface as a proper HTTP 4xx, not an in-band
        # frame after a 200 (the unary path already behaves this way).
        stream = entry.engine.generate(body, ctx).__aiter__()
        try:
            first_item = await stream.__anext__()
        except StopAsyncIteration:
            first_item = None
        except OpenAIError as exc:
            timer.done(exc.status)
            return _error_response(exc)

        response = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                "X-Request-Id": ctx.id,
            },
        )
        await response.prepare(request)

        from dynamo_tpu.parsers import ReasoningParser

        prompt_tokens = 0
        completion_tokens = 0
        sent_role = False
        status = 200
        lp_offset = 0  # running char offset for completions text_offset
        finish_seen: Optional[str] = None
        audit_parts: Optional[list] = [] if self.audit.enabled else None
        reasoning_parser = ReasoningParser(style=entry.card.reasoning_style)
        # Incremental tool-call jail (parsers/jail.py): when the request
        # declared tools, dialect text surfaces as tool_calls ARGUMENT
        # DELTAS while the model is still generating the call; malformed
        # calls degrade via the typed ladder, never a dropped stream.
        jail = None
        parse_error = False
        if kind == "chat" and body.get("tools"):
            from dynamo_tpu.parsers.jail import ToolCallJail

            jail = ToolCallJail(
                dialect=getattr(entry.card, "tool_call_dialect", None)
            )
        try:
            async for item in _prepend(first_item, stream):
                if isinstance(item, dict) and "annotation" in item:
                    if item["annotation"] == "_prompt_tokens":
                        prompt_tokens = item["value"]
                        timer.on_input_tokens(prompt_tokens)
                    else:
                        # Public annotations ride as SSE comments (ref:
                        # preprocessor.rs annotations → SSE comment frames).
                        await _sse_comment(response, item)
                    continue
                out: PostprocessedOutput = item
                if out.error:
                    # Terminal typed SSE error event (headers are long
                    # sent): error_kind lets an SDK distinguish a
                    # migration-exhausted link failure from a real bug.
                    kind = getattr(out, "error_kind", None)
                    frame: Dict[str, Any] = {
                        "message": out.error,
                        "type": _err_type_of_kind(kind),
                    }
                    if kind:
                        frame["error_kind"] = kind
                    await _sse_send(response, {"error": frame})
                    status = _status_of_kind(kind)
                    break
                completion_tokens = out.cumulative_tokens or completion_tokens
                if out.token_ids:
                    timer.on_token(len(out.token_ids))
                if audit_parts is not None and out.text:
                    audit_parts.append(out.text)
                finish_str = out.finish_reason.to_openai() if out.finish_reason else None
                if finish_str:
                    finish_seen = finish_str
                if kind == "chat":
                    base: Dict[str, Any] = {}
                    if not sent_role:
                        base["role"] = "assistant"
                        sent_role = True
                    text = out.text
                    if out.finish_reason is not None:
                        reasoning, content = reasoning_parser.feed(text or "")
                        r_tail, c_tail = reasoning_parser.flush()
                        reasoning += r_tail
                        content += c_tail
                    elif text:
                        reasoning, content = reasoning_parser.feed(text)
                    else:
                        reasoning = content = ""
                    if reasoning:
                        # Streamed reasoning rides the nonstandard-but-common
                        # reasoning_content delta field (ref: jail.rs stream
                        # rewriting for <think> sections).
                        base["reasoning_content"] = reasoning
                    if jail is not None:
                        events = jail.feed(content) if content else []
                        if out.finish_reason is not None:
                            events = events + jail.finish()
                            if jail.calls_started:
                                # ANY emitted call — including one the
                                # ladder sealed — finishes as tool_calls
                                # (the seal's structured error field says
                                # which calls are suspect).
                                finish_str = "tool_calls"
                                finish_seen = finish_str
                        deltas = _fold_jail_events(base, events)
                    else:
                        if content:
                            base["content"] = content
                        deltas = [base]
                    # OpenAI semantics: content logprobs correspond to emitted
                    # content. When the reasoning parser withheld this chunk's
                    # text (or routed it into reasoning_content), attaching the
                    # token logprobs would describe tokens absent from the
                    # delta — suppress them for those chunks.
                    last = len(deltas) - 1
                    for di, delta in enumerate(deltas):
                        await _sse_send(response, chat_chunk(
                            rid, entry.name, delta=delta,
                            finish_reason=(
                                finish_str if di == last else None
                            ),
                            logprobs=(
                                chat_logprobs_block(out.logprobs)
                                if out.logprobs and di == 0
                                and (delta.get("content")
                                     or delta.get("tool_calls"))
                                else None
                            ),
                        ))
                    continue
                else:
                    lp_block = None
                    if out.logprobs:
                        lp_block = completion_logprobs_block(
                            out.logprobs, text_offset=lp_offset
                        )
                        lp_offset = (
                            lp_block["text_offset"][-1]
                            + len(lp_block["tokens"][-1])
                        )
                    chunk = completion_chunk(
                        rid, entry.name, text=out.text, finish_reason=finish_str,
                        logprobs=lp_block,
                    )
                await _sse_send(response, chunk)
            if kind == "chat" and status == 200 and finish_seen is None:
                # Stream ended without a finish chunk (the unary path
                # defaults to EOS here): release anything the reasoning
                # parser or the jail still holds — buffered text must not
                # vanish, and a call mid-generation is sealed by the
                # jail's finish (truncated, typed).
                base = {}
                r_tail, c_tail = reasoning_parser.flush()
                if r_tail:
                    base["reasoning_content"] = r_tail
                if jail is not None:
                    events = jail.feed(c_tail) if c_tail else []
                    events = events + jail.finish()
                    deltas = _fold_jail_events(base, events)
                    if jail.calls_started:
                        finish_seen = "tool_calls"
                else:
                    if c_tail:
                        base["content"] = c_tail
                    deltas = [base]
                finish_seen = finish_seen or FinishReason.EOS.to_openai()
                last = len(deltas) - 1
                for di, delta in enumerate(deltas):
                    await _sse_send(
                        response,
                        chat_chunk(
                            rid, entry.name, delta=delta,
                            finish_reason=(
                                finish_seen if di == last else None
                            ),
                        ),
                    )
            if include_usage and status == 200:
                usage = usage_block(prompt_tokens, completion_tokens)
                if kind == "chat":
                    final = chat_chunk(rid, entry.name, delta={}, usage=usage)
                    final["choices"] = []
                else:
                    final = completion_chunk(rid, entry.name, text="", usage=usage)
                    final["choices"] = []
                await _sse_send(response, final)
            await _sse_done(response)
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: kill the context so the engine frees the slot
            # (ref: http/service/disconnect.rs).
            ctx.kill()
            status = 499
        except Exception as exc:
            # Headers already sent: report in-band on the SSE stream; a second
            # HTTP response is impossible at this point. Typed: a strict-mode
            # DisaggTransferError (no Migration operator to absorb it) lands
            # here and must not read as a dropped stream or anonymous 500.
            error_kind = _error_kind_of(exc)
            parse_error = error_kind == "tool_call_parse"
            logger.exception("engine failed mid-stream")
            status = _status_of_kind(error_kind)
            frame = {
                "message": str(exc), "type": _err_type_of_kind(error_kind),
            }
            if error_kind:
                frame["error_kind"] = error_kind
            with _suppress_conn_errors():
                await _sse_send(response, {"error": frame})
        finally:
            timer.done(status)
            if jail is not None:
                # Per-stream outcome for ALL_PARSER: clean | degraded |
                # error (a wrapped parser exception = error — the client
                # saw the typed frame above, not a dropped stream).
                from dynamo_tpu.parsers.observe import parser_plane

                parser_plane().note_stream(
                    "error" if parse_error else jail.outcome()
                )
            if audit_parts is not None:
                from dynamo_tpu.http.audit import AuditRecord

                self.audit.publish(
                    AuditRecord(
                        request_id=ctx.id, model=entry.name, endpoint=kind,
                        requested_streaming=True, request=body,
                        response_text="".join(audit_parts),
                        finish_reason=finish_seen, status=status,
                    )
                )
        with _suppress_conn_errors():
            await response.write_eof()
        return response


def _fold_jail_events(base: Dict[str, Any], events) -> list:
    """Fold incremental-jail events (parsers/incremental.py) into an
    ordered list of chat ``delta`` dicts for one engine item.

    ``base`` seeds the first delta (role / reasoning_content). Content
    may share a delta with tool_calls entries that FOLLOW it, but content
    arriving AFTER a tool_calls entry opens a new delta — OpenAI clients
    replay deltas in order, and reordering content around a call would
    corrupt the transcript (two back-to-back calls with content between
    them is a supported shape). Consecutive argument deltas for the same
    call index merge into one wire entry."""
    from dynamo_tpu.parsers.incremental import (
        ArgsDelta,
        CallEnd,
        CallStart,
        ContentDelta,
    )

    deltas: list = [dict(base)]
    for ev in events:
        cur = deltas[-1]
        if isinstance(ev, ContentDelta):
            if not ev.text:
                continue
            if "tool_calls" in cur:
                deltas.append({"content": ev.text})
            else:
                cur["content"] = cur.get("content", "") + ev.text
        elif isinstance(ev, CallStart):
            cur.setdefault("tool_calls", []).append(
                {
                    "index": ev.index,
                    "id": ev.call_id,
                    "type": "function",
                    "function": {"name": ev.name, "arguments": ""},
                }
            )
        elif isinstance(ev, ArgsDelta):
            tcs = cur.setdefault("tool_calls", [])
            if tcs and tcs[-1]["index"] == ev.index and "function" in tcs[-1]:
                tcs[-1]["function"]["arguments"] += ev.text
            else:
                tcs.append(
                    {"index": ev.index, "function": {"arguments": ev.text}}
                )
        elif isinstance(ev, CallEnd):
            if ev.error is None and not ev.degraded:
                continue
            # Sealed / lossy call: the structured error field rides the
            # call's last tool_calls entry (clients that ignore unknown
            # fields see a normal, possibly truncated-args call).
            entry: Dict[str, Any] = {"index": ev.index}
            if ev.error is not None:
                entry["error"] = {"reason": ev.error}
            if ev.degraded:
                entry["degraded"] = True
            cur.setdefault("tool_calls", []).append(entry)
    return deltas


def _error_response(exc: OpenAIError) -> web.Response:
    return web.json_response(exc.to_body(), status=exc.status)


def _shed_response(exc: OverloadShedError) -> web.Response:
    """Typed overload shed: 429 (load) / 503 (brownout) / 504 (dead
    deadline), with Retry-After carrying the predicted drain time."""
    dead = exc.reason == "deadline_expired"
    resp = _error_response(
        OpenAIError(
            str(exc), status=exc.status,
            err_type="deadline_exceeded" if dead else "overloaded",
            kind="timeout" if dead else exc.reason,
        )
    )
    if exc.retry_after is not None:
        resp.headers["Retry-After"] = str(
            max(1, int(exc.retry_after + 0.999))
        )
    return resp


async def _prepend(first, rest):
    if first is not None:
        yield first
    async for item in rest:
        yield item


async def _sse_send(response: web.StreamResponse, payload: Dict[str, Any]) -> None:
    await response.write(b"data: " + json.dumps(payload, separators=(",", ":")).encode() + b"\n\n")


async def _sse_comment(response: web.StreamResponse, payload: Dict[str, Any]) -> None:
    await response.write(b": " + json.dumps(payload, separators=(",", ":")).encode() + b"\n\n")


async def _sse_done(response: web.StreamResponse) -> None:
    await response.write(b"data: [DONE]\n\n")


class _suppress_conn_errors:
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return exc_type is not None and issubclass(
            exc_type, (ConnectionResetError, ConnectionAbortedError, RuntimeError)
        )
