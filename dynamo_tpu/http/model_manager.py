"""ModelManager: the frontend's registry of servable models.

Reference parity: lib/llm/src/discovery/model_manager.rs — maps model name →
assembled pipeline engine + deployment card. Fed either statically (tests,
single-process serving) or dynamically by the ModelWatcher as workers
register/deregister on the discovery plane.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.openai import model_entry
from dynamo_tpu.runtime.engine import AsyncEngine


@dataclass
class ModelEntry:
    name: str
    engine: AsyncEngine  # full pipeline: OpenAI dict request in
    card: ModelDeploymentCard
    registered_at: float = field(default_factory=time.time)
    # optional per-model operational attachments (worker_monitor.py / health.py)
    monitor: Optional[Any] = None  # WorkerLoadMonitor
    health: Optional[Any] = None  # CanaryHealthChecker
    # admin hooks, e.g. {"clear_kv": async () -> int} (clear_kv_blocks route)
    admin: Dict[str, Any] = field(default_factory=dict)


class ModelManager:
    def __init__(self) -> None:
        self._models: Dict[str, ModelEntry] = {}

    def register(
        self,
        name: str,
        engine: AsyncEngine,
        card: ModelDeploymentCard,
        *,
        monitor: Optional[Any] = None,
        health: Optional[Any] = None,
        admin: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._models[name] = ModelEntry(
            name=name, engine=engine, card=card, monitor=monitor, health=health,
            admin=dict(admin or {}),
        )

    def unregister(self, name: str) -> None:
        self._models.pop(name, None)

    def get(self, name: str) -> Optional[ModelEntry]:
        return self._models.get(name)

    def names(self) -> List[str]:
        return sorted(self._models)

    def openai_model_list(self) -> List[Dict[str, Any]]:
        return [
            model_entry(e.name, created=int(e.registered_at))
            for e in sorted(self._models.values(), key=lambda e: e.name)
        ]

    def __len__(self) -> int:
        return len(self._models)
