"""Request auditing: capture full request/response pairs per policy.

Reference parity: lib/llm/src/audit/ (AuditRecord + bus + sinks: stderr /
JetStream; policy from env). Here: an in-process bus with pluggable sinks
(stderr JSONL, file JSONL); policy via ``DYN_TPU_AUDIT`` env
(off | stderr | file:<path>). Aggregated AND streamed responses are
captured — the frontend assembles the final text either way.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Protocol

from dynamo_tpu import config
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Declared in the canonical registry (config.py).
AUDIT_POLICY = config.AUDIT_POLICY

SCHEMA_VERSION = 1


@dataclass
class AuditRecord:
    """(ref: audit/handle.rs AuditRecord)"""

    request_id: str
    model: str
    requested_streaming: bool
    endpoint: str
    ts: float = field(default_factory=time.time)
    request: Optional[Dict[str, Any]] = None
    response_text: Optional[str] = None
    finish_reason: Optional[str] = None
    status: int = 0
    schema_version: int = SCHEMA_VERSION

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "ts": self.ts,
            "request_id": self.request_id,
            "model": self.model,
            "endpoint": self.endpoint,
            "requested_streaming": self.requested_streaming,
            "request": self.request,
            "response_text": self.response_text,
            "finish_reason": self.finish_reason,
            "status": self.status,
        }


class AuditSink(Protocol):
    def emit(self, record: AuditRecord) -> None: ...


class StderrSink:
    def emit(self, record: AuditRecord) -> None:
        print(json.dumps({"audit": record.to_dict()}), file=sys.stderr, flush=True)


class FileSink:
    def __init__(self, path: str) -> None:
        self.path = path

    def emit(self, record: AuditRecord) -> None:
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(record.to_dict()) + "\n")
        except OSError:
            logger.exception("audit file sink failed; disabling")
            self.path = ""


class MemorySink:
    """Test/introspection sink (the bus 'subscribe' role)."""

    def __init__(self, limit: int = 1024) -> None:
        self.records: List[AuditRecord] = []
        self.limit = limit

    def emit(self, record: AuditRecord) -> None:
        self.records.append(record)
        if len(self.records) > self.limit:
            del self.records[: len(self.records) - self.limit]


class AuditBus:
    """(ref: audit/bus.rs) — fan records out to registered sinks."""

    def __init__(self) -> None:
        self.sinks: List[AuditSink] = []

    @classmethod
    def from_env(cls) -> "AuditBus":
        bus = cls()
        policy = AUDIT_POLICY.get()
        if policy == "stderr":
            bus.sinks.append(StderrSink())
        elif policy.startswith("file:"):
            bus.sinks.append(FileSink(policy.split(":", 1)[1]))
        return bus

    @property
    def enabled(self) -> bool:
        return bool(self.sinks)

    def publish(self, record: AuditRecord) -> None:
        for sink in self.sinks:
            try:
                sink.emit(record)
            except Exception:
                logger.exception("audit sink %r failed", sink)
