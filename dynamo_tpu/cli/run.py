"""``dynamo_tpu.cli run``: drive an engine without a cluster.

Reference parity: lib/llm/src/entrypoint/input.rs (Input::Text :31 —
interactive REPL; Input::Stdin — one prompt per line; Input::Batch — JSONL
file in, JSONL out with latency stats; Input::Http — OpenAI server over the
local pipeline). The engine is in-process: the mocker, a builtin random-init
config, or a local HF checkpoint directory.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Any, Optional, Tuple

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger(__name__)


def add_run_args(parser: argparse.ArgumentParser) -> None:
    from dynamo_tpu import config

    parser.add_argument(
        "--input", default="text",
        help="text (REPL) | stdin | batch:FILE.jsonl | http",
    )
    parser.add_argument(
        "--model", default="mock",
        help="'mock', a builtin config name (tiny, qwen2.5-0.5b, ...), or a "
        "local HF model directory",
    )
    parser.add_argument("--served-model-name", default=None)
    parser.add_argument("--max-tokens", type=int, default=64)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--http-port", type=int, default=8080)
    parser.add_argument(
        "--block-size", type=int, default=config.KV_BLOCK_SIZE.get()
    )
    parser.add_argument("--num-kv-blocks", type=int, default=512)
    parser.add_argument("--max-model-len", type=int, default=2048)
    parser.add_argument("--out", default=None,
                        help="batch mode: output JSONL path (default stdout)")


def build_engine_and_card(args) -> Tuple[Any, ModelDeploymentCard, Any]:
    """Returns (engine, card, tokenizer)."""
    from dynamo_tpu.llm.tokenizer import tiny_tokenizer

    name = args.served_model_name or args.model
    if args.model == "mock":
        from dynamo_tpu.engines.mock import MockEngine, MockEngineArgs

        engine = MockEngine(MockEngineArgs(speedup_ratio=10.0))
        card = ModelDeploymentCard(name=name, context_length=args.max_model_len)
        return engine, card, tiny_tokenizer()

    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.worker.__main__ import BUILTIN_CONFIGS

    model_path = None
    if args.model in BUILTIN_CONFIGS:
        config = BUILTIN_CONFIGS[args.model]()
        params = None
        tokenizer = tiny_tokenizer()
    else:
        from dynamo_tpu.llm.tokenizer import HFTokenizer
        from dynamo_tpu.models.config import ModelConfig
        from dynamo_tpu.models.hf_loader import load_hf_checkpoint

        model_path = args.model
        config = ModelConfig.from_model_dir(args.model)
        params = load_hf_checkpoint(args.model, config)
        tokenizer = HFTokenizer.from_pretrained_dir(args.model)
    engine = JaxEngine(
        JaxEngineArgs(
            config=config,
            block_size=args.block_size,
            num_kv_blocks=args.num_kv_blocks,
            max_model_len=args.max_model_len,
        ),
        params,
    )
    card = ModelDeploymentCard(
        name=name, model_path=model_path, context_length=args.max_model_len,
        kv_block_size=args.block_size,
        eos_token_ids=list(config.eos_token_ids),
    )
    return engine, card, tokenizer


async def _generate_text(pipeline, model: str, prompt: str, args) -> Tuple[str, int, float]:
    """One completion through the pipeline; returns (text, tokens, seconds)."""
    body = {
        "model": model,
        "prompt": prompt,
        "max_tokens": args.max_tokens,
        "temperature": args.temperature,
        "stream": True,
    }
    start = time.monotonic()
    parts = []
    n = 0
    async for item in pipeline.generate(body, Context()):
        if isinstance(item, dict):
            continue  # annotations
        if item.error:
            raise RuntimeError(item.error)
        parts.append(item.text)
        n += len(item.token_ids)
    return "".join(parts), n, time.monotonic() - start


async def run_text(pipeline, model: str, args) -> None:
    """Interactive REPL (ref: Input::Text)."""
    print(f"dynamo-tpu REPL — model {model}; Ctrl-D to exit", flush=True)
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, input, "> ")
        except EOFError:
            break
        if not line.strip():
            continue
        try:
            text, n, dt = await _generate_text(pipeline, model, line, args)
        except RuntimeError as exc:
            print(f"error: {exc}", file=sys.stderr, flush=True)
            continue
        print(text, flush=True)
        print(f"  [{n} tokens in {dt:.2f}s]", file=sys.stderr, flush=True)


async def run_stdin(pipeline, model: str, args) -> None:
    """One prompt per stdin line, completion per line out (ref: Input::Stdin)."""
    for line in sys.stdin:
        line = line.rstrip("\n")
        if not line:
            continue
        text, _, _ = await _generate_text(pipeline, model, line, args)
        print(text, flush=True)


async def run_batch(pipeline, model: str, args, batch_path: str) -> None:
    """JSONL in ({'text': ...} or {'prompt': ...}), JSONL out with stats
    (ref: Input::Batch)."""
    out_f = open(args.out, "w") if args.out else sys.stdout
    total_tokens = 0
    start = time.monotonic()
    n_requests = 0
    try:
        with open(batch_path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                doc = json.loads(line)
                prompt = doc.get("text") or doc.get("prompt") or ""
                text, n, dt = await _generate_text(pipeline, model, prompt, args)
                total_tokens += n
                n_requests += 1
                out_f.write(
                    json.dumps(
                        {"prompt": prompt, "text": text, "tokens": n,
                         "latency_s": round(dt, 4)}
                    )
                    + "\n"
                )
                out_f.flush()
    finally:
        if args.out:
            out_f.close()
    wall = time.monotonic() - start
    print(
        f"batch done: {n_requests} requests, {total_tokens} tokens in "
        f"{wall:.2f}s ({total_tokens / max(wall, 1e-9):.1f} tok/s)",
        file=sys.stderr, flush=True,
    )


async def run_http(pipeline, card: ModelDeploymentCard, args) -> None:
    """Single-process OpenAI server over the local pipeline (in=http)."""
    from dynamo_tpu.http import HttpService, ModelManager
    from dynamo_tpu.runtime.trajectory import global_store
    from dynamo_tpu.utils.tracing import set_service

    # Trajectory plane, dev-mode wiring: attach the store to the tracer
    # BEFORE the first request so /debug/trajectory sees every span (the
    # worker/frontend mains do the same eagerly).
    set_service("dev-http")
    global_store()
    manager = ModelManager()
    manager.register(card.name, pipeline, card)
    service = HttpService(manager, host="0.0.0.0", port=args.http_port)
    port = await service.start()
    print(f"http server on :{port} serving {card.name}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop(grace_period=5)


# -- observe: device-plane snapshot of a running worker ----------------------


def add_observe_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "what", nargs="?", default=None,
        choices=[None, "trajectory", "kvcache", "perf"],
        help="optional sub-view: 'trajectory' pretty-prints one stitched "
        "request trajectory (GET /debug/trajectory/{trace_id}); 'kvcache' "
        "pretty-prints the KV-reuse plane (GET /debug/kvcache); 'perf' "
        "pretty-prints the perf ledger — per-shape decode attribution, "
        "roofline fractions, and the sentinel's verdicts "
        "(GET /debug/perf)",
    )
    parser.add_argument(
        "trace_id", nargs="?", default=None,
        help="trace id for the trajectory sub-view (omit to list "
        "recent + slow trajectories)",
    )
    parser.add_argument("--top-k", type=int, default=15,
                        help="ranked prefixes to show in the kvcache view")
    parser.add_argument("--host", default="127.0.0.1",
                        help="system-server host of the running worker")
    parser.add_argument("--port", type=int, default=None,
                        help="system-server port (default: DYN_TPU_SYSTEM_PORT)")
    parser.add_argument("--flight-limit", type=int, default=24,
                        help="newest flight-recorder events to show")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw endpoint JSON instead of tables")


def add_drain_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--host", default="127.0.0.1",
                        help="system-server host of the running worker")
    parser.add_argument("--port", type=int, default=None,
                        help="system-server port (default: DYN_TPU_SYSTEM_PORT)")
    parser.add_argument("--deadline-s", type=float, default=None,
                        help="drain budget override (default: the worker's "
                        "DYN_TPU_DRAIN_DEADLINE_S)")
    parser.add_argument("--status", action="store_true",
                        help="report drain state only; do not trigger")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw status JSON")


async def main_drain(args) -> None:
    """Operator-facing drain trigger: POST /drain on a running worker's
    system server and wait for the live-handoff drain to finish (the same
    path SIGTERM and the k8s preStop hook take). With --status, report
    the current state without triggering."""
    import aiohttp

    from dynamo_tpu import config

    port = args.port if args.port is not None else config.SYSTEM_PORT.get()
    base = f"http://{args.host}:{port}"
    timeout = aiohttp.ClientTimeout(total=None, sock_connect=10)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        try:
            if args.status:
                resp = await session.get(f"{base}/drain")
            else:
                body = {}
                if args.deadline_s is not None:
                    body["deadline_s"] = args.deadline_s
                resp = await session.post(f"{base}/drain", json=body)
            async with resp:
                if resp.status != 200:
                    raise SystemExit(
                        f"{'GET' if args.status else 'POST'} {base}/drain -> "
                        f"{resp.status}: {await resp.text()}"
                    )
                status = await resp.json()
        except aiohttp.ClientError as exc:
            raise SystemExit(f"cannot reach system server at {base}: {exc}")

    if args.json:
        print(json.dumps(status, indent=2))
        return
    print(f"state: {status.get('state')}")
    for key in (
        "handoffs", "reprefill_fallbacks", "requeued", "peer_refusals",
        "handoff_bytes", "live_relays", "checkpointed", "duration_s",
    ):
        if key in status:
            print(f"  {key:<20} {status[key]}")


def _fmt_bytes(n) -> str:
    if not isinstance(n, (int, float)):
        return "?"
    # Negative values are meaningful (unaccounted_bytes < 0 = the ledger
    # overcounts the allocator) — keep the sign visible.
    sign, n = ("-", -n) if n < 0 else ("", n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return (
                f"{sign}{int(n)} B" if unit == "B" else f"{sign}{n:.1f} {unit}"
            )
        n /= 1024
    return f"{sign}{n:.1f} TiB"


async def main_observe_trajectory(args) -> None:
    """Pretty-print one stitched request trajectory (or the recent/slow
    index): phases, per-hop spans across processes, retries, skew flags,
    and the dominant phase — 'why was THIS request slow' in one command."""
    import aiohttp

    from dynamo_tpu import config

    port = args.port if args.port is not None else config.SYSTEM_PORT.get()
    base = f"http://{args.host}:{port}"
    path = (
        f"/debug/trajectory/{args.trace_id}"
        if args.trace_id else "/debug/trajectory"
    )
    async with aiohttp.ClientSession() as session:
        try:
            async with session.get(base + path) as r:
                if r.status != 200:
                    raise SystemExit(
                        f"GET {base}{path} -> {r.status}: {await r.text()}"
                    )
                doc = await r.json()
        except aiohttp.ClientError as exc:
            raise SystemExit(f"cannot reach system server at {base}: {exc}")
    if args.json:
        print(json.dumps(doc, indent=2))
        return
    if not args.trace_id:
        print(f"== trajectories ({base}{path})")
        for row in doc.get("traces") or []:
            print(
                f"  {row['trace_id']}  {row['total_ms']:>9.1f} ms  "
                f"dominant={row['dominant_phase']:<13} "
                f"procs={len(row['processes'])} spans={row['span_count']}"
                f"{'  SKEW' if row.get('skew_flagged') else ''}"
            )
        slow = doc.get("slow") or []
        if slow:
            print("  -- slow/error ring --")
            for row in slow:
                print(
                    f"  {row['trace_id']}  {row['total_ms']:>9.1f} ms  "
                    f"dominant={row['dominant_phase']} "
                    f"[{row.get('retained', 'slow')}]"
                )
        return
    print(f"== trajectory {doc.get('trace_id')} ({base}{path})")
    print(
        f"  total {doc.get('total_ms', 0):.1f} ms across "
        f"{len(doc.get('processes') or [])} processes "
        f"({', '.join(doc.get('processes') or [])})"
        f"{'  [residual clock skew flagged]' if doc.get('skew_flagged') else ''}"
    )
    phases = doc.get("phases") or {}
    print("  phases:")
    for phase, ms in phases.items():
        marker = "  <- dominant" if phase == doc.get("dominant_phase") else ""
        print(f"    {phase:<14} {ms:>9.1f} ms{marker}")
    if doc.get("summary"):
        # Slow-ring hit: the full span set aged out of the recent ring;
        # the retained summary still names the bottleneck.
        print(
            f"  (summary only — {doc.get('span_count', 0)} spans aged out "
            "of the recent ring)"
        )
        return
    print("  spans:")
    for s in doc.get("spans") or []:
        attrs = s.get("attributes") or {}
        detail = " ".join(
            f"{k}={v}" for k, v in attrs.items()
            if k in ("worker", "src", "peer", "attempts", "retries",
                     "overlap_blocks", "candidates_scored", "queued_s",
                     "outcome", "adopted", "model")
        )
        flags = []
        if s.get("skew_flagged"):
            flags.append(f"skew={s.get('skew_ms')}ms")
        if str(s.get("status", "ok")) != "ok":
            flags.append(str(s["status"]))
        print(
            f"    {s.get('offset_ms', 0):>9.1f} +{s.get('duration_ms', 0):>8.1f} ms"
            f"  [{s.get('proc', '?'):<16}] {s.get('name', '?'):<22} "
            f"{detail}{('  ' + ' '.join(flags)) if flags else ''}"
        )
    events = doc.get("events") or []
    if events:
        print("  events:")
        for ev in events:
            detail = " ".join(
                f"{k}={v}" for k, v in ev.items()
                if k not in ("trace_id", "ring", "kind", "t_wall", "offset_ms")
            )
            print(
                f"    {ev.get('offset_ms', 0):>9.1f} ms  "
                f"{ev.get('ring', '?')}/{ev.get('kind', '?')} {detail}"
            )


async def main_observe_kvcache(args) -> None:
    """Pretty-print the KV-reuse plane of a running worker: hit rate by
    tier, cache ROI (reused vs recomputed prefill tokens, prefill seconds
    saved), sketch health, and the ranked hot-prefix table — 'is the
    prefix cache earning its memory' in one command."""
    import aiohttp

    from dynamo_tpu import config

    port = args.port if args.port is not None else config.SYSTEM_PORT.get()
    base = f"http://{args.host}:{port}"
    top_k = max(int(getattr(args, "top_k", 15) or 15), 1)
    async with aiohttp.ClientSession() as session:
        async def get(path):
            async with session.get(base + path) as r:
                if r.status != 200:
                    raise SystemExit(
                        f"GET {base}{path} -> {r.status}: {await r.text()}"
                    )
                return await r.json()

        try:
            doc = await get(f"/debug/kvcache?top_k={top_k}")
            prefixes = await get(f"/debug/kvcache/prefixes?k={top_k}")
        except aiohttp.ClientError as exc:
            raise SystemExit(f"cannot reach system server at {base}: {exc}")

    if args.json:
        print(json.dumps({"kvcache": doc, "prefixes": prefixes}, indent=2))
        return

    print(f"== kv reuse ({base}/debug/kvcache)")
    hits = doc.get("hits") or {}
    misses = doc.get("misses", 0)
    total = sum(hits.values()) + misses
    overall = (sum(hits.values()) / total) if total else 0.0
    per_tier = " ".join(
        f"{t}={r:.3f}" for t, r in (doc.get("hit_rate") or {}).items()
    )
    print(
        f"  hit rate {overall:.3f}  "
        f"(hits={sum(hits.values())} misses={misses}"
        f"{'; by tier: ' + per_tier if per_tier else ''})"
    )
    print(
        f"  prefill tokens  reused={doc.get('reused_prefill_tokens', 0)}  "
        f"recomputed={doc.get('recomputed_prefill_tokens', 0)}"
    )
    print(
        f"  prefill saved   {doc.get('prefill_seconds_saved', 0.0):.3f} s  "
        f"(cost/token {doc.get('prefill_cost_per_token_s', 0.0):.2e} s)"
    )
    sketch = doc.get("sketch") or {}
    print(
        f"  sketch          {sketch.get('tracked', 0)}/"
        f"{sketch.get('capacity', 0)} tracked  "
        f"replacements={sketch.get('replacements', 0)}  "
        f"half_life={sketch.get('half_life_s', 0.0):.0f}s"
    )
    tiers = doc.get("tiers") or {}
    for label, view in tiers.items():
        print(f"  [{label}]")
        for tier, stats in (view or {}).items():
            if not isinstance(stats, dict):
                continue
            detail = " ".join(
                f"{k}={stats[k]}" for k in
                ("blocks", "stored", "hits", "misses", "evicted")
                if k in stats
            )
            print(f"    {tier:<8} {detail}")
    rows = prefixes.get("prefixes") or []
    print(f"\n== hot prefixes (top {top_k}; {base}/debug/kvcache/prefixes)")
    if not rows:
        print("  (no tracked prefixes)")
    for row in rows:
        tier_mix = ",".join(
            f"{t}:{n}" for t, n in (row.get("tiers") or {}).items()
        )
        print(
            f"  {row.get('anchor', '?')}  score={row.get('score', 0.0):>10.2f} "
            f"(+/-{row.get('score_error', 0.0):.2f})  hits={row.get('hits', 0):>6} "
            f"tokens={row.get('tokens_from_cache', 0):>9} "
            f"age={row.get('age_s', 0.0):>7.1f}s  {tier_mix}"
        )


async def main_observe_perf(args) -> None:
    """Pretty-print the perf ledger of a running worker: per-shape decode
    attribution (step p50/p99, host gap, dispatch/reap split, tok/s,
    roofline fraction), prefill tokens/s per chunk bucket, and the live
    sentinel's fingerprint verdicts — 'did this engine get slower than it
    used to be on this exact shape' in one command."""
    import aiohttp

    from dynamo_tpu import config

    port = args.port if args.port is not None else config.SYSTEM_PORT.get()
    base = f"http://{args.host}:{port}"
    async with aiohttp.ClientSession() as session:
        try:
            async with session.get(f"{base}/debug/perf") as r:
                if r.status != 200:
                    raise SystemExit(
                        f"GET {base}/debug/perf -> {r.status}: "
                        f"{await r.text()}"
                    )
                doc = await r.json()
        except aiohttp.ClientError as exc:
            raise SystemExit(f"cannot reach system server at {base}: {exc}")

    if args.json:
        print(json.dumps(doc, indent=2))
        return

    ident = doc.get("identity") or {}
    print(
        f"== perf ledger ({base}/debug/perf)  "
        f"preset={ident.get('preset', '?')} "
        f"backend={ident.get('backend', '?')} host={ident.get('host', '?')}"
    )
    rows = doc.get("decode") or []
    if not rows:
        print("  (no decode samples yet)")
    else:
        print(
            f"  {'shape':<28} {'n':>5} {'step p50':>10} {'p99':>10} "
            f"{'gap p50':>9} {'disp':>8} {'reap':>8} {'tok/s':>9} "
            f"{'roofline':>8}"
        )
        for row in rows:
            shape = (
                f"w{row.get('width')}/{row.get('variant')}/"
                f"{row.get('path')}"
            )
            frac = row.get("roofline_fraction")
            print(
                f"  {shape:<28} {row.get('samples', 0):>5} "
                f"{row.get('step_p50_s', 0.0) * 1e3:>8.2f}ms "
                f"{row.get('step_p99_s', 0.0) * 1e3:>8.2f}ms "
                f"{row.get('host_gap_p50_s', 0.0) * 1e3:>7.2f}ms "
                f"{row.get('dispatch_p50_s', 0.0) * 1e3:>6.2f}ms "
                f"{row.get('reap_p50_s', 0.0) * 1e3:>6.2f}ms "
                f"{row.get('toks_per_sec', 0.0):>9.1f} "
                f"{'' if frac is None else f'{frac:>7.1%}':>8}"
            )
    prefill = doc.get("prefill") or {}
    if prefill:
        print("  prefill tok/s by chunk bucket: " + "  ".join(
            f"{b}={v.get('toks_per_sec_p50', 0.0):.0f}"
            for b, v in prefill.items()
        ))
    print(
        f"\n== sentinel  fingerprints_loaded="
        f"{doc.get('fingerprints_loaded', 0)}  "
        f"anomalies_total={doc.get('anomalies_total', 0)}"
    )
    verdicts = doc.get("verdicts") or {}
    if not verdicts:
        print("  (no verdicts yet — sentinel has not evaluated)")
    for key, v in sorted(verdicts.items()):
        line = (
            f"  {key:<40} {v.get('verdict', '?'):<12} "
            f"n={v.get('samples', 0)} "
            f"step_p50={v.get('step_p50_s', 0.0) * 1e3:.2f}ms "
            f"tok/s={v.get('toks_per_sec', 0.0):.1f}"
        )
        print(line)
        for anom in v.get("anomalies") or []:
            print(
                f"    ! {anom.get('kind')}  x{anom.get('ratio', 0.0):.3f} "
                f"(live {anom.get('live'):.6g} vs baseline "
                f"{anom.get('baseline'):.6g}, streak {anom.get('streak')})"
            )


async def main_observe(args) -> None:
    """One-shot pretty snapshot of /debug/memory, /debug/compiles and
    /debug/flight from a running worker's system server — the operator's
    'what is the device plane doing right now' view without curl + jq."""
    import aiohttp

    from dynamo_tpu import config

    if getattr(args, "what", None) == "trajectory":
        await main_observe_trajectory(args)
        return
    if getattr(args, "what", None) == "kvcache":
        await main_observe_kvcache(args)
        return
    if getattr(args, "what", None) == "perf":
        await main_observe_perf(args)
        return

    port = args.port if args.port is not None else config.SYSTEM_PORT.get()
    base = f"http://{args.host}:{port}"
    async with aiohttp.ClientSession() as session:
        async def get(path):
            async with session.get(base + path) as r:
                if r.status != 200:
                    raise SystemExit(
                        f"GET {base}{path} -> {r.status}: {await r.text()}"
                    )
                return await r.json()

        try:
            memory = await get("/debug/memory")
            compiles = await get("/debug/compiles")
            flight = await get(f"/debug/flight?limit={args.flight_limit}")
        except aiohttp.ClientError as exc:
            raise SystemExit(f"cannot reach system server at {base}: {exc}")

    if args.json:
        print(json.dumps(
            {"memory": memory, "compiles": compiles, "flight": flight},
            indent=2,
        ))
        return

    print(f"== device memory ({base}/debug/memory)")
    for source, cats in (memory.get("sources") or {}).items():
        print(f"  [{source}]")
        for category, nbytes in sorted(cats.items()):
            print(f"    {category:<16} {_fmt_bytes(nbytes):>12}")
    print(f"  ledger total       {_fmt_bytes(memory.get('ledger_total_bytes')):>12}")
    if "device_bytes_in_use" in memory:
        print(f"  device in use      {_fmt_bytes(memory['device_bytes_in_use']):>12}")
        print(f"  unaccounted        {_fmt_bytes(memory['unaccounted_bytes']):>12}")
    hwc = memory.get("host_weight_cache") or {}
    for tier, usage in hwc.items():
        print(
            f"  weight cache {tier:<5} {_fmt_bytes(usage.get('bytes')):>12}"
            f"  ({usage.get('entries', 0)} entries)"
        )

    print(f"\n== compiled programs ({base}/debug/compiles)")
    header = f"  {'program':<32} {'compiles':>8} {'sigs':>6} {'storms':>6} {'seconds':>9}"
    print(header)
    for name, st in (compiles.get("programs") or {}).items():
        print(
            f"  {name:<32} {st['compiles']:>8} {st['signatures']:>6} "
            f"{st['storms']:>6} {st['compile_seconds']:>9.2f}"
        )
    totals = compiles.get("totals") or {}
    print(
        f"  {'TOTAL':<32} {totals.get('compiles', 0):>8} "
        f"{totals.get('signatures', 0):>6} {totals.get('storms', 0):>6} "
        f"{totals.get('compile_seconds', 0.0):>9.2f}"
    )

    print(f"\n== flight recorder (newest {args.flight_limit}; {base}/debug/flight)")
    events = flight.get("events") or []
    if not events:
        print("  (no events)")
    for ev in events:
        extras = {
            k: v for k, v in ev.items()
            if k not in ("seq", "t_mono", "ring", "kind")
        }
        detail = " ".join(f"{k}={v}" for k, v in extras.items())
        print(
            f"  {ev.get('t_mono', 0):>14.3f} {ev.get('ring', '?'):<7} "
            f"{ev.get('kind', '?'):<12} {detail}"
        )


async def main_run(args) -> None:
    configure_logging()
    from dynamo_tpu.llm.entrypoint import build_local_pipeline

    engine, card, tokenizer = build_engine_and_card(args)
    pipeline = build_local_pipeline(card, engine, tokenizer=tokenizer)
    mode = args.input
    try:
        if mode == "text":
            await run_text(pipeline, card.name, args)
        elif mode == "stdin":
            await run_stdin(pipeline, card.name, args)
        elif mode.startswith("batch:"):
            await run_batch(pipeline, card.name, args, mode.split(":", 1)[1])
        elif mode == "http":
            await run_http(pipeline, card, args)
        else:
            raise SystemExit(
                f"unknown --input {mode!r} (text | stdin | batch:FILE | http)"
            )
    finally:
        stop = getattr(engine, "stop", None)
        if stop is not None:
            await stop()
