"""``python -m dynamo_tpu.cli`` — the unified entrypoint.

Reference parity: launch/dynamo-run/src/opt.rs (one binary fronting every
input/output pairing) plus the service launchers under components/. Service
subcommands re-exec the dedicated module mains so flags stay in one place.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from dynamo_tpu import config
from dynamo_tpu.cli.run import (
    add_drain_args,
    add_observe_args,
    add_run_args,
    main_drain,
    main_observe,
    main_run,
)

# One source of truth for service kinds (deploy specs use the same table);
# the CLI adds hyphen aliases and the deploy controller itself.
from dynamo_tpu.deploy.spec import KIND_MODULES

_SERVICES = {
    **KIND_MODULES,
    "global-router": KIND_MODULES["global_router"],
    "deploy": "dynamo_tpu.deploy",
}


def cmd_env(markdown: bool = False) -> None:
    """Print the DYN_* registry (config.py advertises this command)."""
    import os

    if markdown:
        # The docs/design_docs/config_knobs.md body; a tier-1 test pins
        # the checked-in file to this output.
        print(config.render_markdown())
        return
    rows = sorted(config.registry().items())
    width = max(len(n) for n, _ in rows)
    for name, var in rows:
        current = os.environ.get(name)
        state = f" [set: {current}]" if current is not None else ""
        print(f"{name:<{width}}  default={var.default!r}{state}")
        if var.doc:
            print(f"{'':<{width}}  {var.doc}")


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in _SERVICES:
        # Delegate: `dynamo_tpu.cli worker --model tiny` ≡
        # `python -m dynamo_tpu.worker --model tiny`.
        module = _SERVICES[argv[0]]
        sys.argv = [f"{module}"] + argv[1:]
        import runpy

        runpy.run_module(module, run_name="__main__")
        return

    parser = argparse.ArgumentParser(
        "dynamo-tpu",
        description="unified CLI: run engines locally, inspect config, "
        f"or launch services ({', '.join(_SERVICES)})",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    run_p = sub.add_parser("run", help="drive a local engine (text/stdin/batch/http)")
    add_run_args(run_p)
    observe_p = sub.add_parser(
        "observe",
        help="snapshot a running worker's device plane "
        "(/debug/memory /debug/compiles /debug/flight); sub-views: "
        "trajectory, kvcache, perf",
    )
    add_observe_args(observe_p)
    # Lazy import: bench compare is jax-free stdlib (it judges JSON
    # records), so it can't ride cli.run's imports either.
    from dynamo_tpu.bench.compare import add_compare_args

    bench_p = sub.add_parser(
        "bench",
        help="bench-record tooling (compare: typed per-leg regression "
        "verdicts over BENCH_*.json records, nonzero exit on regression)",
    )
    bench_sub = bench_p.add_subparsers(dest="bench_command", required=True)
    compare_p = bench_sub.add_parser(
        "compare",
        help="judge the newest bench record against the previous usable "
        "one with noise bands",
    )
    add_compare_args(compare_p)
    drain_p = sub.add_parser(
        "drain",
        help="live-handoff drain a running worker (POST /drain; in-flight "
        "decodes migrate to peers with zero re-prefill)",
    )
    add_drain_args(drain_p)
    # Lazy import: lint is jax-free and must stay that way (it runs on
    # boxes where the serving deps don't), so it can't ride cli.run's
    # imports.
    from dynamo_tpu.analysis.cli import add_lint_args

    lint_p = sub.add_parser(
        "lint",
        help="run the dynlint static-analysis passes over the package "
        "(exit 1 on non-baselined findings)",
    )
    add_lint_args(lint_p)
    env_p = sub.add_parser(
        "env", help="print the environment-variable registry"
    )
    env_p.add_argument(
        "--markdown", action="store_true",
        help="emit the docs/design_docs/config_knobs.md reference table",
    )
    args = parser.parse_args(argv)

    if args.command == "env":
        cmd_env(markdown=args.markdown)
    elif args.command == "run":
        asyncio.run(main_run(args))
    elif args.command == "observe":
        asyncio.run(main_observe(args))
    elif args.command == "drain":
        asyncio.run(main_drain(args))
    elif args.command == "lint":
        from dynamo_tpu.analysis.cli import main_lint

        raise SystemExit(main_lint(args))
    elif args.command == "bench":
        from dynamo_tpu.bench.compare import main_compare

        raise SystemExit(main_compare(args))


if __name__ == "__main__":
    main()
