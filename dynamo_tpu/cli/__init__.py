"""Unified CLI (the ``dynamo-run`` role).

Reference parity: launch/dynamo-run/src/opt.rs (``dynamo-run in=X out=Y``
input/output matrix) and lib/llm/src/entrypoint/input.rs:31 (Text / Stdin /
Batch / Http inputs over an engine). Subcommands:

  run       drive a local engine: --input text|stdin|batch:FILE|http
  env       print the DYN_* environment-variable registry
  frontend / worker / mocker / discd / planner / grpc
            dispatch to the corresponding service entrypoints
"""
