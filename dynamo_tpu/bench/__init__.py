"""Benchmarking: AIPerf-style load generation against live endpoints.

Reference parity: benchmarks/ + docs/benchmarks/benchmarking.md — the
reference ships a benchmarking harness as a first-class component; here it
is `python -m dynamo_tpu.bench` (loadgen.py) driving any OpenAI-compatible
frontend (ours or not) with fixed ISL/OSL/concurrency workloads.
"""

from dynamo_tpu.bench.loadgen import (
    LoadReport,
    RequestResult,
    WorkloadSpec,
    reports_to_markdown,
    run_load,
    run_sweep,
)

__all__ = [
    "LoadReport",
    "RequestResult",
    "WorkloadSpec",
    "reports_to_markdown",
    "run_load",
    "run_sweep",
]
