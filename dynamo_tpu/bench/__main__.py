"""CLI: `python -m dynamo_tpu.bench --url http://HOST:PORT --model NAME ...`

Fixed ISL/OSL workload against an OpenAI-compatible frontend; pass several
--concurrency values for a sweep. One JSON line per run on stdout;
--markdown prints the sweep table afterwards (the tuning-guide shape).
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from dynamo_tpu.bench.loadgen import (
    WorkloadSpec,
    reports_to_markdown,
    run_sweep,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dynamo_tpu.bench",
        description="AIPerf-style ISL/OSL/concurrency load generator",
    )
    parser.add_argument("--url", default="http://127.0.0.1:8080")
    parser.add_argument("--model", required=True)
    parser.add_argument("--isl", type=int, default=128)
    parser.add_argument("--osl", type=int, default=64)
    parser.add_argument(
        "--concurrency", type=int, nargs="+", default=[8],
        help="one value per sweep point",
    )
    parser.add_argument("--requests", type=int, default=32,
                        help="measured requests per sweep point")
    parser.add_argument("--warmup", type=int, default=0)
    parser.add_argument("--prefix-len", type=int, default=0,
                        help="shared prompt prefix tokens (prefix-cache hit path)")
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--markdown", action="store_true",
                        help="print the sweep as a markdown table too")
    args = parser.parse_args(argv)

    spec = WorkloadSpec(
        model=args.model, isl=args.isl, osl=args.osl,
        requests=args.requests, warmup_requests=args.warmup,
        prefix_len=args.prefix_len, vocab=args.vocab,
        temperature=args.temperature, seed=args.seed,
    )
    reports = asyncio.run(run_sweep(args.url, spec, args.concurrency))
    for rep in reports:
        print(rep.to_json_line(), flush=True)
    if args.markdown:
        print(reports_to_markdown(reports))
    return 1 if any(r.errors == len(r.results) for r in reports) else 0


if __name__ == "__main__":
    sys.exit(main())
