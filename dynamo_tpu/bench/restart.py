"""Restart benchmark: SIGKILL a serving worker → replacement's first token.

The chrek role (ref: deploy/chrek/pkg/checkpoint/criu.go:1 — process-image
checkpoint so a worker restart skips cold init). A TPU worker's process
image cannot be CRIU'd meaningfully (HBM state dies with the process), so
warm restart here is the sum of the framework's durable tiers, and this
bench puts ONE NUMBER on it:

  cold  = fresh spawn: HF safetensors ingest + every jit compile
  warm  = replacement spawn after SIGKILL: weights mmap'd from the tmpfs
          tier (models/weight_cache.py — the GMS role), jit compiles served
          from the persistent XLA compilation cache, KV restored from the
          checkpoint when one exists (engines/tpu/kv_checkpoint.py)

Usage:
  python -m dynamo_tpu.bench.restart --model-dir /path/to/hf-model
  → one JSON line {"cold_s", "warm_s", "speedup", ...}

The measured interval is spawn→first-token: it includes process start,
jax init, weight load, engine build, prefill+decode compile, and the
first generated token — the full kill→recovery a supervisor sees.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional


def _worker_body(model_dir: str, workdir: str) -> None:
    """Subprocess: load via the tiered cache, serve one token, report,
    then hold (the parent SIGKILLs us — crash, not graceful exit)."""
    import dataclasses

    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(workdir, "jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import asyncio

    import jax.numpy as jnp

    from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.weight_cache import load_checkpoint_cached
    from dynamo_tpu.runtime.context import Context

    config = ModelConfig.from_model_dir(model_dir)
    if jax.default_backend() == "cpu":
        config = dataclasses.replace(config, dtype=jnp.float32)
    t_load0 = time.perf_counter()
    params, hit = load_checkpoint_cached(
        model_dir, config,
        cache_dir=os.path.join(workdir, "disk"),
        shm_dir=os.path.join(workdir, "shm"),
    )
    load_s = time.perf_counter() - t_load0
    engine = JaxEngine(
        JaxEngineArgs(
            config=config, block_size=16, num_kv_blocks=64, max_num_seqs=2,
            max_model_len=256, decode_steps=4,
        ),
        params,
    )

    async def first_token() -> float:
        req = PreprocessedRequest(
            token_ids=[5, 6, 7, 8, 9], request_id="restart-bench",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=2, ignore_eos=True),
        )
        async for out in engine.generate(req, Context()):
            if out.token_ids:
                return time.perf_counter()
        raise RuntimeError("no token produced")

    t_tok = asyncio.run(first_token())
    print(
        "READY "
        + json.dumps(
            {"weights_hit": hit, "load_s": round(load_s, 3),
             "token_at": t_tok}
        ),
        flush=True,
    )
    signal.pause()  # hold until the parent SIGKILLs us


def _spawn_and_time(model_dir: str, workdir: str) -> dict:
    """Spawn one worker, wait for its first token, return timings. The
    returned process is already SIGKILLed (crash semantics)."""
    env = {**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)}
    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.bench.restart",
         "--worker", model_dir, workdir],
        stdout=subprocess.PIPE, env=env, text=True, bufsize=1,
    )
    info: Optional[dict] = None
    assert proc.stdout is not None
    # readline() blocks forever on a silent hung worker — read from a
    # thread so the 600s bound is real.
    import queue as _queue
    import threading

    lines: _queue.Queue = _queue.Queue()

    def _reader():
        for line in proc.stdout:
            lines.put(line)
        lines.put(None)

    threading.Thread(target=_reader, daemon=True).start()
    deadline = time.time() + 600
    while time.time() < deadline:
        try:
            line = lines.get(timeout=5)
        except _queue.Empty:
            continue
        if line is None:
            break
        if line.startswith("READY "):
            info = json.loads(line[len("READY "):])
            break
    elapsed = time.perf_counter() - t0
    proc.kill()  # SIGKILL: the crash the warm path must recover from
    proc.wait(timeout=30)
    if info is None:
        raise RuntimeError("worker never produced a token")
    return {
        "spawn_to_first_token_s": round(elapsed, 3),
        "weights_hit": info["weights_hit"],
        "weight_load_s": info["load_s"],
    }


def run(model_dir: str, workdir: str) -> dict:
    os.makedirs(workdir, exist_ok=True)
    cold = _spawn_and_time(model_dir, workdir)
    warm = _spawn_and_time(model_dir, workdir)
    assert not cold["weights_hit"] and warm["weights_hit"], (cold, warm)
    return {
        "metric": "kill-to-first-token recovery",
        "cold_s": cold["spawn_to_first_token_s"],
        "warm_s": warm["spawn_to_first_token_s"],
        "speedup": round(
            cold["spawn_to_first_token_s"]
            / max(warm["spawn_to_first_token_s"], 1e-9),
            2,
        ),
        "cold_weight_load_s": cold["weight_load_s"],
        "warm_weight_load_s": warm["weight_load_s"],
    }


def main() -> None:
    if len(sys.argv) >= 4 and sys.argv[1] == "--worker":
        _worker_body(sys.argv[2], sys.argv[3])
        return
    import argparse
    import tempfile

    ap = argparse.ArgumentParser("restart bench")
    ap.add_argument("--model-dir", required=True)
    ap.add_argument(
        "--workdir", default=None,
        help="cache root (weights shm/disk + jax compile cache); a warm "
        "workdir from a previous run makes even the 'cold' leg warm",
    )
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="restart-bench-")
    print(json.dumps(run(args.model_dir, workdir)))


if __name__ == "__main__":
    main()
