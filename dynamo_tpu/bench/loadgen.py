"""AIPerf-style load generator for OpenAI-compatible endpoints.

Reference parity: the reference benchmarks with AIPerf — fixed ISL/OSL
workloads swept over concurrency, reporting tokens/sec, TTFT and ITL
percentiles (ref: docs/benchmarks/benchmarking.md, benchmarks/ — the
methodology BASELINE.md prescribes). This is the in-tree equivalent: an
asyncio client driving `/v1/completions` with pre-tokenized prompts
(exact ISL), ``nvext.ignore_eos`` pinning OSL, and optional shared prefixes
to exercise KV-aware routing.

Measurement model: one streaming request per in-flight slot; TTFT = first
SSE data chunk, ITL = gaps between subsequent chunks (chunk == one engine
emission — with burst token emission a chunk can carry several tokens, the
same granularity a user perceives).
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class WorkloadSpec:
    """Fixed ISL/OSL/concurrency workload (the AIPerf triple)."""

    model: str
    isl: int = 128
    osl: int = 64
    concurrency: int = 8
    requests: int = 32
    prefix_len: int = 0  # shared prompt prefix (prefix-cache/router overlap)
    vocab: int = 256  # token ids drawn from [1, vocab)
    temperature: float = 0.0
    seed: int = 0
    warmup_requests: int = 0  # sent before the measured window, not recorded


@dataclass
class RequestResult:
    ok: bool
    ttft_ms: float = 0.0
    itls_ms: List[float] = field(default_factory=list)
    latency_ms: float = 0.0
    chunks: int = 0
    text_len: int = 0
    error: Optional[str] = None


@dataclass
class LoadReport:
    spec: WorkloadSpec
    wall_s: float
    results: List[RequestResult]

    @property
    def ok_results(self) -> List[RequestResult]:
        return [r for r in self.results if r.ok]

    @property
    def errors(self) -> int:
        return sum(1 for r in self.results if not r.ok)

    def _pct(self, values: List[float], q: float) -> float:
        return float(np.percentile(values, q)) if values else 0.0

    def summary(self) -> Dict[str, Any]:
        ok = self.ok_results
        ttfts = [r.ttft_ms for r in ok]
        itls = [itl for r in ok for itl in r.itls_ms]
        lats = [r.latency_ms for r in ok]
        out_tokens = len(ok) * self.spec.osl
        return {
            "model": self.spec.model,
            "isl": self.spec.isl,
            "osl": self.spec.osl,
            "concurrency": self.spec.concurrency,
            "requests": len(self.results),
            "errors": self.errors,
            "wall_s": round(self.wall_s, 3),
            "output_tok_per_s": round(out_tokens / self.wall_s, 2) if self.wall_s else 0.0,
            "req_per_s": round(len(ok) / self.wall_s, 3) if self.wall_s else 0.0,
            "p50_ttft_ms": round(self._pct(ttfts, 50), 1),
            "p90_ttft_ms": round(self._pct(ttfts, 90), 1),
            "p99_ttft_ms": round(self._pct(ttfts, 99), 1),
            "p50_itl_ms": round(self._pct(itls, 50), 2),
            "p90_itl_ms": round(self._pct(itls, 90), 2),
            "p99_itl_ms": round(self._pct(itls, 99), 2),
            "p50_latency_ms": round(self._pct(lats, 50), 1),
            "p99_latency_ms": round(self._pct(lats, 99), 1),
        }

    def to_json_line(self) -> str:
        return json.dumps(self.summary())


MD_COLUMNS = [
    ("concurrency", "conc"),
    ("output_tok_per_s", "tok/s"),
    ("req_per_s", "req/s"),
    ("p50_ttft_ms", "p50 TTFT ms"),
    ("p99_ttft_ms", "p99 TTFT ms"),
    ("p50_itl_ms", "p50 ITL ms"),
    ("p99_itl_ms", "p99 ITL ms"),
    ("errors", "errors"),
]


def reports_to_markdown(reports: List["LoadReport"]) -> str:
    """One sweep → one markdown table (the tuning-guide presentation)."""
    if not reports:
        return "(no results)"
    s0 = reports[0].summary()
    head = f"ISL={s0['isl']} OSL={s0['osl']} model={s0['model']}"
    lines = [head, "", "| " + " | ".join(h for _, h in MD_COLUMNS) + " |",
             "|" + "|".join("---" for _ in MD_COLUMNS) + "|"]
    for rep in reports:
        s = rep.summary()
        lines.append("| " + " | ".join(str(s[k]) for k, _ in MD_COLUMNS) + " |")
    return "\n".join(lines)


def _make_prompt(spec: WorkloadSpec, rng: np.random.Generator, prefix: List[int]) -> List[int]:
    body = rng.integers(1, spec.vocab, size=max(spec.isl - len(prefix), 1))
    return prefix + [int(t) for t in body]


async def _one_request(
    session, url: str, spec: WorkloadSpec, prompt: List[int]
) -> RequestResult:
    payload = {
        "model": spec.model,
        "prompt": prompt,
        "max_tokens": spec.osl,
        "temperature": spec.temperature,
        "stream": True,
        "nvext": {"ignore_eos": True},
    }
    res = RequestResult(ok=False)
    start = time.perf_counter()
    last = start
    try:
        async with session.post(f"{url}/v1/completions", json=payload) as resp:
            if resp.status != 200:
                res.error = f"HTTP {resp.status}: {(await resp.text())[:200]}"
                return res
            async for raw in resp.content:
                line = raw.decode().strip()
                if not line.startswith("data:"):
                    continue
                data = line[5:].strip()
                if data == "[DONE]":
                    break
                now = time.perf_counter()
                if res.chunks == 0:
                    res.ttft_ms = (now - start) * 1e3
                else:
                    res.itls_ms.append((now - last) * 1e3)
                last = now
                res.chunks += 1
                try:
                    chunk = json.loads(data)
                    res.text_len += len(
                        (chunk.get("choices") or [{}])[0].get("text") or ""
                    )
                except json.JSONDecodeError:
                    pass
        res.latency_ms = (time.perf_counter() - start) * 1e3
        res.ok = res.chunks > 0
        if not res.ok:
            res.error = "empty stream"
    except Exception as exc:  # connection errors land in the report
        res.error = repr(exc)
    return res


async def run_load(url: str, spec: WorkloadSpec) -> LoadReport:
    """Drive ``spec`` against ``url`` (e.g. http://127.0.0.1:8080)."""
    import aiohttp

    rng = np.random.default_rng(spec.seed)
    prefix = (
        [int(t) for t in rng.integers(1, spec.vocab, size=spec.prefix_len)]
        if spec.prefix_len
        else []
    )
    prompts = [
        _make_prompt(spec, rng, prefix)
        for _ in range(spec.requests + spec.warmup_requests)
    ]
    results: List[RequestResult] = []

    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=None, sock_read=300)
    ) as session:

        async def drive(batch: List[List[int]], sink: Optional[List[RequestResult]]):
            next_idx = 0
            lock = asyncio.Lock()

            async def worker():
                nonlocal next_idx
                while True:
                    async with lock:
                        if next_idx >= len(batch):
                            return
                        i = next_idx
                        next_idx += 1
                    r = await _one_request(session, url, spec, batch[i])
                    if sink is not None:
                        sink.append(r)

            await asyncio.gather(
                *(worker() for _ in range(max(spec.concurrency, 1)))
            )

        # Warmup fully drains BEFORE the measured clock starts — its wall
        # time and results must not pollute the reported numbers.
        if spec.warmup_requests:
            await drive(prompts[: spec.warmup_requests], None)
        started = time.perf_counter()
        await drive(prompts[spec.warmup_requests :], results)
    wall = time.perf_counter() - started
    return LoadReport(spec=spec, wall_s=wall, results=results)


async def run_sweep(
    url: str, base: WorkloadSpec, concurrencies: List[int]
) -> List[LoadReport]:
    """Concurrency sweep, sequential runs (the AIPerf sweep loop)."""
    import dataclasses

    reports = []
    for c in concurrencies:
        spec = dataclasses.replace(base, concurrency=c)
        reports.append(await run_load(url, spec))
    return reports
