"""`dynamo-tpu bench compare` — the offline half of the perf sentinel.

Ingests two or more bench records (either a bench.py JSON line or the
driver wrapper ``{n, cmd, rc, tail, parsed}`` the repo's BENCH_r*.json
files use), plus optionally BASELINE.json for provenance, and emits
per-leg typed verdicts with noise bands: the newest record (the
candidate) is judged against the most recent usable record before it
(the reference). Nonzero exit on regression, so CI and the bench
driver's epilogue both get a machine-readable go/no-go instead of a
human eyeballing two JSON blobs.

Judged metrics are direction-typed (higher-is-better throughput and
coverage vs lower-is-better latency percentiles) and matched by PATH in
the nested record — ``secondary.p50_itl_ms`` only ever compares against
``secondary.p50_itl_ms``. A leg present in one record but not the other
is reported as ``no_baseline``/``leg_vanished``, never silently skipped:
a leg that stopped producing numbers is itself a regression signal.

Verdict taxonomy (shared with runtime/perf_ledger.py's live sentinel):
``ok`` | ``regression`` | ``improved`` | ``no_baseline`` |
``insufficient`` (non-numeric / missing values).

Dependency-free by design (stdlib only, no jax): the comparison must run
on boxes where the serving deps don't load — that is the point of a
regression sentinel for a TPU repo developed off-TPU.
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List, Optional, Tuple

# Stamped into every bench.py record (and checked here): bump when the
# meaning of a judged metric changes, so cross-round comparison never
# silently mixes incompatible semantics.
BENCH_SCHEMA_VERSION = 1

DEFAULT_NOISE_BAND = 0.10

# Judged metric leaf names -> direction ("up" = higher is better).
# Matched at any depth; the full dotted path labels the verdict.
METRIC_DIRECTIONS: Dict[str, str] = {
    "value": "up",
    "toks_per_sec_per_chip": "up",
    "toks_per_sec": "up",
    "p50_ttft_ms": "down",
    "p99_ttft_ms": "down",
    "p50_itl_ms": "down",
    "p99_itl_ms": "down",
    "fused_coverage": "up",
    "hit_rate": "up",
}


def unwrap_record(doc: Any) -> Optional[Dict[str, Any]]:
    """Accept either a raw bench.py record or the driver wrapper
    ``{n, cmd, rc, tail, parsed}``; None when unusable (failed round,
    skipped backend, or not a bench record at all)."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc and "cmd" in doc:
        doc = doc.get("parsed")
        if not isinstance(doc, dict):
            return None
    if "metric" not in doc:
        return None
    if doc.get("skipped"):
        return None
    return doc


def load_record(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return unwrap_record(json.load(f))
    except (OSError, ValueError):
        return None


def _walk_metrics(
    doc: Dict[str, Any], prefix: str = ""
) -> Dict[str, float]:
    """Flatten every judged numeric metric to ``dotted.path -> value``.
    Error legs (``{"error": ...}``) contribute nothing — their absence
    from the flat map is what surfaces them as vanished."""
    out: Dict[str, float] = {}
    if "error" in doc:
        return out
    for key, val in doc.items():
        path = f"{prefix}.{key}" if prefix else key
        if isinstance(val, dict):
            out.update(_walk_metrics(val, path))
        elif (
            key in METRIC_DIRECTIONS
            and isinstance(val, (int, float))
            and not isinstance(val, bool)
        ):
            out[path] = float(val)
    return out


def _leg_of(path: str) -> str:
    return path.rsplit(".", 1)[0] if "." in path else "primary"


def compare_records(
    reference: Dict[str, Any],
    candidate: Dict[str, Any],
    band: float = DEFAULT_NOISE_BAND,
) -> Dict[str, Any]:
    """Per-metric typed verdicts for candidate vs reference."""
    ref = _walk_metrics(reference)
    cand = _walk_metrics(candidate)
    verdicts: List[Dict[str, Any]] = []
    regressions = 0
    for path in sorted(set(ref) | set(cand)):
        direction = METRIC_DIRECTIONS[path.rsplit(".", 1)[-1]]
        row: Dict[str, Any] = {
            "path": path,
            "leg": _leg_of(path),
            "direction": direction,
            "reference": ref.get(path),
            "candidate": cand.get(path),
            "band": band,
        }
        if path not in cand:
            # The candidate stopped producing this number — a vanished
            # leg/metric is a signal, not a skip.
            row["verdict"] = "leg_vanished"
            regressions += 1
        elif path not in ref:
            row["verdict"] = "no_baseline"
        elif ref[path] == 0.0:
            row["verdict"] = "insufficient"
        else:
            ratio = cand[path] / ref[path]
            row["ratio"] = round(ratio, 4)
            good = ratio > 1.0 + band
            bad = ratio < 1.0 - band
            if direction == "down":
                good, bad = bad, good
            if bad:
                row["verdict"] = "regression"
                regressions += 1
            elif good:
                row["verdict"] = "improved"
            else:
                row["verdict"] = "ok"
        verdicts.append(row)
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "band": band,
        "reference_schema": reference.get("schema_version"),
        "candidate_schema": candidate.get("schema_version"),
        "reference_fingerprint": reference.get("fingerprint"),
        "candidate_fingerprint": candidate.get("fingerprint"),
        "verdicts": verdicts,
        "regressions": regressions,
        "verdict": "regression" if regressions else "ok",
    }


def compare_paths(
    paths: List[str],
    baseline_path: Optional[str] = None,
    band: float = DEFAULT_NOISE_BAND,
) -> Tuple[Dict[str, Any], int]:
    """CLI/epilogue entrypoint: ``paths`` oldest→newest; the last is the
    candidate, the most recent usable among the rest is the reference.
    Returns (report, exit_code): 0 ok, 1 regression, 2 unusable inputs."""
    if len(paths) < 2:
        return (
            {"error": "need at least two records (reference... candidate)"},
            2,
        )
    candidate = load_record(paths[-1])
    if candidate is None:
        return (
            {"error": f"candidate record {paths[-1]!r} is unusable "
                      "(failed round, skip record, or not bench JSON)"},
            2,
        )
    reference = None
    reference_path = None
    for p in reversed(paths[:-1]):
        reference = load_record(p)
        if reference is not None:
            reference_path = p
            break
    if reference is None:
        return (
            {"error": "no usable reference record among "
                      f"{paths[:-1]!r}"},
            2,
        )
    report = compare_records(reference, candidate, band=band)
    report["reference_path"] = reference_path
    report["candidate_path"] = paths[-1]
    skipped = [
        p for p in paths[:-1] if p != reference_path and load_record(p) is None
    ]
    if skipped:
        report["unusable_records"] = skipped
    if baseline_path:
        try:
            with open(baseline_path, "r", encoding="utf-8") as f:
                base = json.load(f)
            report["baseline"] = {
                "metric": base.get("metric"),
                "north_star": base.get("north_star"),
                "published": base.get("published"),
            }
        except (OSError, ValueError) as e:
            report["baseline"] = {"error": f"{type(e).__name__}: {e}"}
    return report, (1 if report["regressions"] else 0)


def format_report(report: Dict[str, Any]) -> str:
    """Human-readable verdict table (the CLI's default rendering)."""
    if "error" in report:
        return f"bench compare: {report['error']}"
    lines = [
        f"bench compare: {report['candidate_path']} "
        f"vs {report['reference_path']} (band ±{report['band']:.0%})"
    ]
    marks = {
        "ok": " ", "improved": "+", "regression": "!",
        "leg_vanished": "!", "no_baseline": "?", "insufficient": "?",
    }
    for row in report["verdicts"]:
        mark = marks.get(row["verdict"], "?")
        ref, cand = row["reference"], row["candidate"]
        ratio = row.get("ratio")
        lines.append(
            f"  [{mark}] {row['path']:<42} "
            f"{'-' if ref is None else f'{ref:g}':>12} -> "
            f"{'-' if cand is None else f'{cand:g}':>12}"
            + (f"  x{ratio:g}" if ratio is not None else "")
            + f"  {row['verdict']}"
        )
    lines.append(
        f"verdict: {report['verdict'].upper()} "
        f"({report['regressions']} regression(s), "
        f"{len(report['verdicts'])} metrics judged)"
    )
    return "\n".join(lines)


def add_compare_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "records", nargs="+",
        help="bench records oldest->newest (raw bench.py JSON or the "
        "driver's BENCH_r*.json wrappers); the last is the candidate",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="BASELINE.json for provenance (metric/north-star context "
        "attached to the report; not a verdict source)",
    )
    parser.add_argument(
        "--band", type=float, default=DEFAULT_NOISE_BAND,
        help="fractional noise band before a drift is a verdict "
        "(default %(default)s)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the raw report JSON instead of the table",
    )


def main_compare(args: argparse.Namespace) -> int:
    report, rc = compare_paths(
        list(args.records), baseline_path=args.baseline, band=args.band
    )
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(format_report(report))
    return rc
