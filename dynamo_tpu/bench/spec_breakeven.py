"""Speculative-decoding break-even measurement.

Real-checkpoint acceptance cannot be measured in this environment (zero
egress: no real weights exist, and random weights drive prompt-lookup
acceptance to ~0 — docs/design_docs/performance.md r3 measurement). What
CAN be measured on hardware is the COST side, which fixes the break-even
acceptance rate any real deployment needs:

  plain:  one fused decode step emits 1 token/seq in t_decode
  spec:   one verify step over [B, k+1] emits (1 + accepted) tokens/seq
          in t_verify (+ host proposal overhead, measured separately)

  spec wins  ⇔  E[accepted] > t_verify / t_decode - 1

Usage (real chip):
  python -m dynamo_tpu.bench.spec_breakeven --model llama3-8b --quant int8
  → JSON {t_decode_ms, t_verify_ms, k, break_even_acceptance, ...}

Ref: the reference's engines expose spec decode as a config lever
(docs per-engine spec-decode guidance); engines/tpu/spec.py is the
local implementation this prices.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _time_readback(arr) -> float:
    t0 = time.perf_counter()
    _ = np.asarray(arr)
    return time.perf_counter() - t0


def measure(model: str = "llama3-8b", quant: str | None = "int8",
            batch: int = 64, ctx: int = 160, spec_k: int = 4,
            block_size: int = 128, iters: int = 16) -> dict:
    import os

    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import jax.numpy as jnp

    from dynamo_tpu.engines.tpu.runner import DeviceRunner
    from dynamo_tpu.engines.tpu.engine import JaxEngineArgs
    from dynamo_tpu.models.config import (
        llama3_8b_config,
        qwen2_500m_config,
        tiny_config,
    )

    cfg = {
        "llama3-8b": llama3_8b_config,
        "qwen2.5-0.5b": qwen2_500m_config,
        "tiny": tiny_config,
    }[model]()
    P = (ctx + spec_k + block_size) // block_size + 1
    args = JaxEngineArgs(
        config=cfg, block_size=block_size, num_kv_blocks=batch * P + 8,
        max_num_seqs=batch, max_model_len=P * block_size,
        decode_steps=iters, quantization=quant,
    )
    runner = DeviceRunner(args)
    rng = np.random.default_rng(0)
    NB = args.num_kv_blocks
    tables = rng.permutation(NB - 1)[: batch * P].reshape(batch, P).astype(
        np.int32
    )
    pos = np.full((batch,), ctx, np.int32)
    toks = np.ones((batch,), np.int32)
    ones = np.ones((batch,), np.int32)
    temp = np.zeros((batch,), np.float32)
    topk = np.zeros((batch,), np.int32)
    topp = np.ones((batch,), np.float32)

    # Time at the jit level with ONE readback per timed loop: on the
    # tunneled dev platform a synchronous per-dispatch readback costs the
    # full ~77 ms RTT, which would swamp t_verify (production on-host
    # dispatch pays none of it).
    # The closing readback costs one tunnel RTT (~77 ms on the dev
    # platform); measure it and subtract so per-sample cost does not
    # depend on the loop count (it otherwise inflates the short verify
    # loop far more than the long decode loop).
    probe = jnp.zeros((8,), jnp.int32)
    _ = np.asarray(probe)
    t_rtt = min(
        _time_readback(probe) for _ in range(3)
    )

    def time_loop(fn, n, read):
        out = fn()  # compile
        _ = np.asarray(read(out))  # drain the compile+warmup dispatch
        out = fn()  # warm steady-state
        _ = np.asarray(read(out))
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        _ = np.asarray(read(out))
        return max(time.perf_counter() - t0 - t_rtt, 1e-9) / n

    d = jnp.asarray
    salts = np.zeros((batch,), np.int32)
    # Cache key matches the runner's dispatcher: (want_logprobs,
    # use_procs, use_megakernel) — the decode program the serving path
    # actually dispatches for plain greedy bursts.
    dec_key = (False, False, bool(runner.use_megakernel))
    dec_fn = runner._decode_state_fns.get(dec_key)
    if dec_fn is None:
        dec_fn = runner._build_decode_fn(use_megakernel=dec_key[2])
        runner._decode_state_fns[dec_key] = dec_fn

    # The state-path decode program donates tokens/pos (the carry), so
    # hand it FRESH device copies each call — pos stays constant across
    # timed iterations (constant attention work), unlike threading the
    # advancing carry.
    def dec_call():
        out = dec_fn(
            runner.params, runner.lora, runner.k_cache, runner.v_cache,
            d(toks), d(pos), d(ones), d(tables),
            d(salts), runner.rng, d(temp), d(topk), d(topp),
            d(np.zeros((batch,), np.int32)),
        )
        # out = (toks, logp, k, v, carry_tok, carry_pos)
        runner.k_cache, runner.v_cache = out[2], out[3]
        return out

    t_decode = time_loop(dec_call, 3, lambda o: o[0]) / iters

    # spec verify: ONE [B, k+1] forward + rejection-sampling acceptance at
    # every position (greedy rows degrade to argmax verify inside the same
    # program)
    ver_toks = np.ones((batch, spec_k + 1), np.int32)
    lens = np.full((batch,), spec_k + 1, np.int32)
    if runner._spec_fn is None:
        runner._spec_fn = runner._build_spec_fn()

    def mk_ver_call(vtemp):
        vt = d(np.full((batch,), vtemp, np.float32))

        def ver_call():
            out = runner._spec_fn(
                runner.params, runner.lora, runner.k_cache, runner.v_cache,
                d(ver_toks), d(pos), d(lens), d(tables), None,
                runner.rng, np.int32(2), vt, d(topk), d(topp),
            )
            runner.k_cache, runner.v_cache = out[-2], out[-1]
            return out

        return ver_call

    t_verify = time_loop(mk_ver_call(0.0), 8, lambda o: o[0])

    # sampled-mode probe: the same verify program with temperature>0 rows.
    # Proposals here are the model's own greedy continuations, so the
    # accepted-token count shows how much of the greedy acceptance a
    # sampled deployment retains at this temperature (rejection sampling
    # accepts proposal x with prob p(x) — r5, VERDICT item 7).
    t_verify_sampled = time_loop(mk_ver_call(0.8), 8, lambda o: o[0])
    greedy_emit, greedy_counts = runner.run_spec(
        ver_toks, pos, lens, tables, None,
    )
    # Proposals = each row's VALID greedy-verify emissions; positions past
    # counts[i] are zero padding, not model tokens, so pad by repeating the
    # last valid token (repeats depress tail acceptance — the column is a
    # lower bound on sampled acceptance of greedy-quality proposals).
    sampled_props = np.zeros((batch, spec_k), np.int32)
    for i in range(batch):
        n = max(int(greedy_counts[i]), 1)
        row = greedy_emit[i, :n]
        sampled_props[i, :min(n, spec_k)] = row[:spec_k]
        if n < spec_k:
            sampled_props[i, n:] = row[n - 1]
    sp_toks = np.concatenate(
        [ver_toks[:, :1], sampled_props], axis=1
    ).astype(np.int32)
    _em, sp_counts = runner.run_spec(
        sp_toks, pos, lens, tables, None,
        temp=np.full((batch,), 0.8, np.float32),
        topk=topk, topp=topp,
    )
    sampled_accepted = float(np.mean(sp_counts - 1))

    # host proposal cost: the same index+lookup NgramSpecDecoder.propose
    # runs per sequence per tick (engines/tpu/spec.py:41), standalone
    hist = rng.integers(0, 1000, size=512).tolist()
    n = 3

    def propose_once():
        index = {}
        for p in range(n - 1, len(hist) - 1):
            index[tuple(hist[p - n + 1 : p + 1])] = p + 1
        cont = index.get(tuple(hist[-n:]))
        return hist[cont : cont + spec_k] if cont is not None else []

    t0 = time.perf_counter()
    for _ in range(200):
        propose_once()
    t_proposal = (time.perf_counter() - t0) / 200

    be = t_verify / t_decode - 1.0
    return {
        "metric": "speculative-decode break-even",
        "model": cfg.name,
        "quant": quant,
        "batch": batch,
        "ctx": ctx,
        "spec_k": spec_k,
        "t_decode_ms_per_token_step": round(t_decode * 1000, 3),
        "t_verify_ms": round(t_verify * 1000, 3),
        "t_proposal_us": round(t_proposal * 1e6, 1),
        "verify_over_decode": round(t_verify / t_decode, 3),
        # spec emits (1 + accepted) tokens per verify; plain emits
        # t_verify/t_decode tokens in the same wall time
        "break_even_accepted_tokens": round(be, 3),
        "break_even_acceptance_rate": round(max(be, 0.0) / spec_k, 3),
        "t_verify_sampled_ms": round(t_verify_sampled * 1000, 3),
        "sampled_accepted_of_greedy_props": round(sampled_accepted, 3),
        "backend": jax.default_backend(),
    }


def main() -> None:
    ap = argparse.ArgumentParser("spec break-even")
    ap.add_argument("--model", default="llama3-8b")
    ap.add_argument("--quant", default="int8")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--ctx", type=int, default=160)
    ap.add_argument("--spec-k", type=int, default=4)
    args = ap.parse_args()
    print(
        json.dumps(
            measure(
                args.model, args.quant or None, args.batch, args.ctx,
                args.spec_k,
            )
        )
    )


if __name__ == "__main__":
    main()
