"""The core streaming-engine abstraction.

Reference parity: ``AsyncEngine`` trait (lib/runtime/src/engine.rs:201) and the
type-erased ``AnyAsyncEngine`` (engine.rs:285). In this framework an engine is
anything with::

    async def generate(request, context) -> AsyncIterator[response]

Handlers may be written as plain async generator functions; ``as_engine``
adapts them. Streams are plain async iterators — one item per token-delta for
LLM engines — and the context controls cancellation (see context.py).
"""

from __future__ import annotations

import inspect
from typing import Any, AsyncIterator, Awaitable, Callable, Optional, Protocol, runtime_checkable

from dynamo_tpu.runtime.context import Context


@runtime_checkable
class AsyncEngine(Protocol):
    """Streaming request→response-stream engine."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


HandlerFn = Callable[..., Any]


class _FnEngine:
    """Adapts a function to the AsyncEngine protocol.

    Accepts any of:
      - ``async def f(request) -> AsyncIterator``        (async generator)
      - ``async def f(request, context) -> AsyncIterator``
      - ``async def f(request[, context]) -> value``     (unary; wrapped into a
        one-item stream)
    """

    def __init__(self, fn: HandlerFn, name: Optional[str] = None) -> None:
        self._fn = fn
        self._wants_context = _accepts_context(fn)
        self.name = name or getattr(fn, "__name__", "engine")

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if self._wants_context:
            result = self._fn(request, context)
        else:
            result = self._fn(request)
        return _as_stream(result)

    def __repr__(self) -> str:
        return f"FnEngine({self.name})"


def _accepts_context(fn: HandlerFn) -> bool:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    params = [
        p
        for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    # Bound methods already exclude `self`.
    return len(params) >= 2


async def _await_one(awaitable: Awaitable[Any]) -> AsyncIterator[Any]:
    value = await awaitable
    if hasattr(value, "__aiter__"):
        async for item in value:
            yield item
    else:
        yield value


def _as_stream(result: Any) -> AsyncIterator[Any]:
    if hasattr(result, "__aiter__"):
        return result.__aiter__()
    if inspect.isawaitable(result):
        return _await_one(result)
    raise TypeError(
        f"engine handler returned {type(result).__name__}; expected an async "
        "generator or awaitable"
    )


def as_engine(obj: Any, name: Optional[str] = None) -> AsyncEngine:
    """Coerce a handler function / object with .generate into an AsyncEngine."""
    if callable(getattr(obj, "generate", None)):
        return obj
    if callable(obj):
        return _FnEngine(obj, name=name)
    raise TypeError(f"cannot adapt {type(obj).__name__} to AsyncEngine")


async def collect(stream: AsyncIterator[Any]) -> list:
    """Drain a stream into a list (test/batch helper)."""
    out = []
    async for item in stream:
        out.append(item)
    return out
