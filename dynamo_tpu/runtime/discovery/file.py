"""File-backed discovery: a shared directory as the KV store.

Reference parity: lib/runtime/src/storage/kv/file.rs (file discovery backend)
with etcd-style lease liveness mapped onto mtime heartbeats: a lease is a
file the owner touches periodically; keys written under a lease are expired
by any participant's poll loop once the heartbeat goes stale (ref: etcd lease
keep-alive, transports/etcd.rs).

Good for multi-process single-host clusters (tests, one TPU host). Multi-host
uses DiscdDiscovery (discd.py).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.discovery import (
    EventKind,
    Lease,
    Watch,
    WatchEvent,
    _WATCH_CLOSED,
)
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_LEASE_DIR = ".leases"


class FileDiscovery:
    def __init__(self, root: str, *, poll_interval: float = 0.2) -> None:
        self.root = root
        self.poll_interval = poll_interval
        os.makedirs(os.path.join(root, _LEASE_DIR), exist_ok=True)
        self._watchers: List[Tuple[str, asyncio.Queue]] = []
        self._poll_task: Optional[asyncio.Task] = None
        self._known: Dict[str, Any] = {}  # key → value (last observed)
        self._closed = False

    # -- paths -------------------------------------------------------------

    def _path(self, key: str) -> str:
        safe = key.strip("/").replace("/", os.sep)
        return os.path.join(self.root, safe + ".json")

    def _key_of(self, path: str) -> str:
        rel = os.path.relpath(path, self.root)
        return rel[: -len(".json")].replace(os.sep, "/")

    # -- KV ----------------------------------------------------------------

    async def put(self, key: str, value: Dict[str, Any], lease: Optional[Lease] = None) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc = {"value": value, "lease": lease.id if lease else None}
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        self._observe(key, value)

    async def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass
        self._observe(key, None)

    async def get(self, key: str) -> Optional[Dict[str, Any]]:
        doc = self._read(self._path(key))
        return doc["value"] if doc else None

    async def get_prefix(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for key, value in self._scan().items():
            if key.startswith(prefix):
                out[key] = value
        return out

    def _read(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None
        lease_id = doc.get("lease")
        if lease_id and self._lease_expired(lease_id):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return None
        return doc

    def _scan(self) -> Dict[str, Dict[str, Any]]:
        found: Dict[str, Dict[str, Any]] = {}
        for dirpath, dirnames, filenames in os.walk(self.root):
            if _LEASE_DIR in dirnames:
                dirnames.remove(_LEASE_DIR)
            for fname in filenames:
                if not fname.endswith(".json"):
                    continue
                path = os.path.join(dirpath, fname)
                doc = self._read(path)
                if doc is not None:
                    found[self._key_of(path)] = doc["value"]
        return found

    # -- leases ------------------------------------------------------------

    def _lease_path(self, lease_id: str) -> str:
        return os.path.join(self.root, _LEASE_DIR, lease_id)

    def _lease_expired(self, lease_id: str) -> bool:
        path = self._lease_path(lease_id)
        try:
            with open(path) as f:
                doc = json.load(f)
            return time.time() - os.path.getmtime(path) > doc["ttl"]
        except (FileNotFoundError, json.JSONDecodeError, KeyError):
            return True

    async def create_lease(self, ttl: float) -> Lease:
        lease = Lease(id=uuid.uuid4().hex, ttl=ttl)
        with open(self._lease_path(lease.id), "w") as f:
            json.dump({"ttl": ttl}, f)
        return lease

    async def keep_alive(self, lease: Lease) -> None:
        try:
            os.utime(self._lease_path(lease.id))
        except FileNotFoundError:
            # Re-create: the lease may have been swept while we were paused.
            with open(self._lease_path(lease.id), "w") as f:
                json.dump({"ttl": lease.ttl}, f)

    async def revoke_lease(self, lease: Lease) -> None:
        # Delete keys owned by the lease, then the heartbeat file.
        for dirpath, dirnames, filenames in os.walk(self.root):
            if _LEASE_DIR in dirnames:
                dirnames.remove(_LEASE_DIR)
            for fname in filenames:
                if not fname.endswith(".json"):
                    continue
                path = os.path.join(dirpath, fname)
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (FileNotFoundError, json.JSONDecodeError):
                    continue
                if doc.get("lease") == lease.id:
                    try:
                        os.unlink(path)
                    except FileNotFoundError:
                        pass
        try:
            os.unlink(self._lease_path(lease.id))
        except FileNotFoundError:
            pass

    # -- watch -------------------------------------------------------------

    def watch(self, prefix: str) -> Watch:
        queue: asyncio.Queue = asyncio.Queue()
        snapshot_state = self._scan()
        self._known.update(snapshot_state)
        snapshot = [
            WatchEvent(EventKind.PUT, k, v)
            for k, v in sorted(snapshot_state.items())
            if k.startswith(prefix)
        ]
        entry = (prefix, queue)
        self._watchers.append(entry)
        if self._poll_task is None or self._poll_task.done():
            self._poll_task = asyncio.get_running_loop().create_task(
                self._poll_loop(), name="file-discovery-poll"
            )

        def _close(w: Watch) -> None:
            self._watchers = [e for e in self._watchers if e[1] is not queue]
            queue.put_nowait(_WATCH_CLOSED)

        return Watch(prefix, snapshot, queue, on_close=_close)

    def _observe(self, key: str, value: Optional[Dict[str, Any]]) -> None:
        """Local-change fast path: notify watchers without waiting on a poll."""
        prev = self._known.get(key)
        if value is None:
            if key in self._known:
                del self._known[key]
                self._emit(WatchEvent(EventKind.DELETE, key))
        elif prev != value:
            self._known[key] = value
            self._emit(WatchEvent(EventKind.PUT, key, value))

    def _emit(self, event: WatchEvent) -> None:
        for prefix, queue in list(self._watchers):
            if event.key.startswith(prefix):
                queue.put_nowait(event)

    async def _poll_loop(self) -> None:
        from dynamo_tpu.runtime.tasks import Backoff

        # A scan failure (shared-filesystem blip) hits every watcher at
        # once; jittered backoff keeps the recovering mount from being
        # re-polled by the whole fleet in lockstep.
        backoff = Backoff(base_s=self.poll_interval, cap_s=30 * self.poll_interval)
        while not self._closed and self._watchers:
            delay = self.poll_interval
            try:
                current = await asyncio.get_running_loop().run_in_executor(
                    None, self._scan
                )
                for key in list(self._known):
                    if key not in current:
                        self._observe(key, None)
                for key, value in current.items():
                    self._observe(key, value)
                backoff.reset()
            except Exception:
                logger.exception("file discovery poll failed")
                delay = backoff.next_delay()
            await asyncio.sleep(delay)

    async def close(self) -> None:
        self._closed = True
        if self._poll_task is not None:
            self._poll_task.cancel()
            await reap_task(self._poll_task, "file-discovery poll", logger)
            self._poll_task = None
