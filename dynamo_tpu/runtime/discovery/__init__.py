"""Discovery plane: service/instance/model registration + watch.

Reference parity: lib/runtime/src/discovery/{mod.rs,kv_store.rs,kube.rs,mock.rs}
and the lease-backed etcd transport (transports/etcd.rs). The reference
supports etcd / NATS-KV / file / Kubernetes backends; etcd and NATS are not
available in this environment, so the first-class backends are:

  - ``MemoryDiscovery``  — process-local shared bus (ref: discovery/mock.rs);
    zero-infra testing, used by DistributedRuntime.process_local().
  - ``FileDiscovery``    — shared-directory backend with mtime-refreshed
    leases (ref: storage/kv/file.rs); works across processes on one host.
  - ``DiscdDiscovery``   — client for the self-hosted discd TCP KV service
    (our mini-etcd; see runtime/discovery/discd.py) for multi-host.

Data model: a flat key → JSON document store with optional leases. Keys:

    instances/{namespace}/{component}/{endpoint}/{instance_id}
    models/{namespace}/{model_slug}/{instance_id}

A lease is kept alive by its owner; when the owner dies the backend expires
the lease and watchers observe Delete events — this is the liveness mechanism
(ref: etcd lease keep-alive, SURVEY §5 failure detection).
"""

from __future__ import annotations

import asyncio
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator, Dict, List, Optional, Protocol, Tuple


class EventKind(str, Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass(frozen=True)
class WatchEvent:
    kind: EventKind
    key: str
    value: Optional[Dict[str, Any]] = None  # None for deletes


@dataclass
class Lease:
    id: str
    ttl: float


class DiscoveryBackend(Protocol):
    """Key→JSON store with leases and prefix watch."""

    async def put(self, key: str, value: Dict[str, Any], lease: Optional[Lease] = None) -> None: ...
    async def delete(self, key: str) -> None: ...
    async def get(self, key: str) -> Optional[Dict[str, Any]]: ...
    async def get_prefix(self, prefix: str) -> Dict[str, Dict[str, Any]]: ...
    def watch(self, prefix: str) -> "Watch": ...
    async def create_lease(self, ttl: float) -> Lease: ...
    async def revoke_lease(self, lease: Lease) -> None: ...
    async def close(self) -> None: ...


class Watch:
    """Async iterator of WatchEvents for a key prefix.

    Yields a synthetic PUT for every pre-existing key first (snapshot), then
    live events. Close with ``aclose`` or ``async with``.
    """

    def __init__(self, prefix: str, snapshot: List[WatchEvent], queue: "asyncio.Queue[WatchEvent]", on_close=None) -> None:
        self.prefix = prefix
        self._snapshot = list(snapshot)
        self._queue = queue
        self._closed = False
        self._on_close = on_close

    def __aiter__(self) -> "Watch":
        return self

    async def __anext__(self) -> WatchEvent:
        if self._snapshot:
            return self._snapshot.pop(0)
        if self._closed:
            raise StopAsyncIteration
        event = await self._queue.get()
        if event is _WATCH_CLOSED:
            self._closed = True
            raise StopAsyncIteration
        return event

    def drain_snapshot(self) -> List[WatchEvent]:
        """Synchronously take the initial snapshot (pre-existing keys); the
        iterator then yields only live events. Lets callers apply the snapshot
        inline without racing the watch task."""
        snapshot = self._snapshot
        self._snapshot = []
        return snapshot

    async def aclose(self) -> None:
        if not self._closed:
            self._closed = True
            if self._on_close is not None:
                self._on_close(self)

    async def __aenter__(self) -> "Watch":
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.aclose()


_WATCH_CLOSED: WatchEvent = WatchEvent(EventKind.DELETE, "\x00closed\x00")


class MemoryDiscovery:
    """Process-local discovery bus.

    Multiple DistributedRuntimes in one process share state when constructed
    with the same ``bus`` name — this is how accelerator-free integration
    tests emulate a cluster (ref: SharedMockRegistry, discovery/mock.rs).
    """

    _buses: Dict[str, "MemoryDiscovery"] = {}

    def __init__(self) -> None:
        self._data: Dict[str, Dict[str, Any]] = {}
        self._lease_keys: Dict[str, List[str]] = {}
        self._watchers: List[Tuple[str, asyncio.Queue, asyncio.AbstractEventLoop]] = []

    @classmethod
    def shared(cls, bus: str = "default") -> "MemoryDiscovery":
        if bus not in cls._buses:
            cls._buses[bus] = cls()
        return cls._buses[bus]

    @classmethod
    def reset(cls, bus: Optional[str] = None) -> None:
        if bus is None:
            cls._buses.clear()
        else:
            cls._buses.pop(bus, None)

    def _notify(self, event: WatchEvent) -> None:
        for prefix, queue, loop in list(self._watchers):
            if event.key.startswith(prefix):
                try:
                    loop.call_soon_threadsafe(queue.put_nowait, event)
                except RuntimeError:
                    # Watcher's loop is gone (test teardown) — drop it.
                    self._watchers = [w for w in self._watchers if w[1] is not queue]

    async def put(self, key: str, value: Dict[str, Any], lease: Optional[Lease] = None) -> None:
        self._data[key] = dict(value)
        if lease is not None:
            self._lease_keys.setdefault(lease.id, []).append(key)
        self._notify(WatchEvent(EventKind.PUT, key, dict(value)))

    async def delete(self, key: str) -> None:
        if key in self._data:
            del self._data[key]
            self._notify(WatchEvent(EventKind.DELETE, key))

    async def get(self, key: str) -> Optional[Dict[str, Any]]:
        value = self._data.get(key)
        return dict(value) if value is not None else None

    async def get_prefix(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        return {k: dict(v) for k, v in self._data.items() if k.startswith(prefix)}

    def watch(self, prefix: str) -> Watch:
        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_running_loop()
        entry = (prefix, queue, loop)
        self._watchers.append(entry)
        snapshot = [
            WatchEvent(EventKind.PUT, k, dict(v))
            for k, v in sorted(self._data.items())
            if k.startswith(prefix)
        ]

        def _close(w: Watch) -> None:
            self._watchers = [e for e in self._watchers if e[1] is not queue]
            try:
                loop.call_soon_threadsafe(queue.put_nowait, _WATCH_CLOSED)
            except RuntimeError:
                pass

        return Watch(prefix, snapshot, queue, on_close=_close)

    async def create_lease(self, ttl: float) -> Lease:
        return Lease(id=uuid.uuid4().hex, ttl=ttl)

    async def revoke_lease(self, lease: Lease) -> None:
        for key in self._lease_keys.pop(lease.id, []):
            await self.delete(key)

    async def close(self) -> None:
        pass


def instance_key(namespace: str, component: str, endpoint: str, instance_id: int) -> str:
    return f"instances/{namespace}/{component}/{endpoint}/{instance_id:016x}"


def instance_prefix(namespace: str, component: Optional[str] = None, endpoint: Optional[str] = None) -> str:
    parts = ["instances", namespace]
    if component is not None:
        parts.append(component)
        if endpoint is not None:
            parts.append(endpoint)
    return "/".join(parts) + "/"


def model_key(namespace: str, model_slug: str, instance_id: int) -> str:
    return f"models/{namespace}/{model_slug}/{instance_id:016x}"


MODELS_PREFIX = "models/"
