"""discd: a self-hosted discovery KV service + client (the etcd of this
framework).

Reference parity: the reference's default non-k8s discovery plane is etcd
with leases and watches (lib/runtime/src/transports/etcd.rs,
storage/kv/etcd.rs). etcd isn't available in this environment, so discd is a
minimal TCP service speaking the two-part msgpack codec with the same
semantics the runtime needs: put/delete/get/prefix scan, prefix watch with
snapshot, and TTL leases whose expiry deletes owned keys — watchers observe
DELETE events, which is the cluster's worker-death signal.

Run the server:  python -m dynamo_tpu.discd --port 2379
Client:          DiscdDiscovery("host:2379")
"""

from __future__ import annotations

import asyncio
import itertools
import time
import uuid
from typing import Any, Dict, List, Optional, Set, Tuple

from dynamo_tpu.runtime.discovery import (
    EventKind,
    Lease,
    Watch,
    WatchEvent,
    _WATCH_CLOSED,
)
from dynamo_tpu.runtime.network.codec import FrameReader, FrameWriter
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class DiscdServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        snapshot_path: Optional[str] = None,
        snapshot_interval_s: float = 2.0,
    ) -> None:
        self.host = host
        self.port = port
        self._data: Dict[str, Tuple[Dict[str, Any], Optional[str]]] = {}
        self._leases: Dict[str, Tuple[float, float]] = {}  # id → (ttl, last beat)
        self._watchers: Dict[int, Tuple[str, FrameWriter]] = {}
        self._watch_ids = itertools.count(1)
        self._server: Optional[asyncio.base_events.Server] = None
        self._sweeper: Optional[asyncio.Task] = None
        self.bound_port: Optional[int] = None
        self._conn_writers: set = set()
        # -- HA minimum (the raft-replicated-etcd role, single-node form):
        # keyspace + lease snapshots so a crashed/restarted discd comes back
        # with the SAME keys and lease ids. Restored leases restart their
        # TTL clock from boot, so live owners (whose keepalive loops retry
        # through the outage — runtime/distributed._keep_alive_loop) re-beat
        # within one interval and never lose registration; truly dead
        # owners still expire one TTL after the restart.
        # Ref: the reference's etcd lease/keyspace durability
        # (lib/runtime/src/transports/etcd.rs).
        self.snapshot_path = snapshot_path
        self.snapshot_interval_s = snapshot_interval_s
        self._dirty = False
        self.restored_keys = 0

    async def start(self) -> int:
        if self.snapshot_path:
            self._load_snapshot()
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.bound_port = self._server.sockets[0].getsockname()[1]
        self._sweeper = asyncio.get_running_loop().create_task(
            self._sweep_loop(), name="discd-lease-sweeper"
        )
        logger.info("discd listening on %s:%s", self.host, self.bound_port)
        return self.bound_port

    async def stop(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            await reap_task(self._sweeper, "discd lease sweeper", logger)
        if self.snapshot_path and self._dirty:
            self._save_snapshot()
        if self._server is not None:
            self._server.close()
            # 3.12 wait_closed() waits for live connections too — close them.
            for writer in list(self._conn_writers):
                writer.close()
            await self._server.wait_closed()

    # -- snapshot persistence ----------------------------------------------

    def _save_snapshot(self) -> None:
        import json
        import os

        doc = {
            "data": {k: [v, lid] for k, (v, lid) in self._data.items()},
            "leases": {lid: ttl for lid, (ttl, _beat) in self._leases.items()},
        }
        tmp = self.snapshot_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.snapshot_path)  # atomic on POSIX
            self._dirty = False
        except OSError:
            logger.exception("discd snapshot write failed")

    def _load_snapshot(self) -> None:
        import json
        import os

        if not os.path.exists(self.snapshot_path):
            return
        try:
            with open(self.snapshot_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            logger.exception("discd snapshot unreadable; starting empty")
            return
        now = time.monotonic()
        self._data = {
            k: (v, lid) for k, (v, lid) in (doc.get("data") or {}).items()
        }
        self._leases = {
            lid: (float(ttl), now) for lid, ttl in (doc.get("leases") or {}).items()
        }
        self.restored_keys = len(self._data)
        logger.info(
            "discd restored %d keys, %d leases from %s",
            len(self._data), len(self._leases), self.snapshot_path,
        )

    async def _sweep_loop(self) -> None:
        last_snap = time.monotonic()
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            expired = [
                lid for lid, (ttl, beat) in self._leases.items() if now - beat > ttl
            ]
            for lid in expired:
                logger.info("discd lease %s expired", lid[:8])
                await self._drop_lease(lid)
            if (
                self.snapshot_path
                and self._dirty
                and now - last_snap >= self.snapshot_interval_s
            ):
                self._save_snapshot()
                last_snap = now

    async def _drop_lease(self, lease_id: str) -> None:
        if self._leases.pop(lease_id, None) is not None:
            self._dirty = True
        doomed = [k for k, (_, lid) in self._data.items() if lid == lease_id]
        for key in doomed:
            del self._data[key]
            self._dirty = True
            await self._notify(EventKind.DELETE, key, None)

    async def _notify(self, kind: EventKind, key: str, value: Optional[Dict[str, Any]]) -> None:
        dead: List[int] = []
        for wid, (prefix, fw) in list(self._watchers.items()):
            if not key.startswith(prefix):
                continue
            try:
                await fw.send(
                    {"watch": wid, "kind": kind.value, "key": key}, value
                )
            except (ConnectionError, RuntimeError):
                dead.append(wid)
        for wid in dead:
            self._watchers.pop(wid, None)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        fr = FrameReader(reader)
        fw = FrameWriter(writer)
        self._conn_writers.add(writer)
        conn_watches: Set[int] = set()
        try:
            while True:
                frame = await fr.recv()
                if frame is None:
                    break
                header, payload = frame
                try:
                    await self._dispatch(header, payload, fw, conn_watches)
                except Exception as exc:
                    logger.exception("discd op failed")
                    with _quiet():
                        await fw.send(
                            {"reqid": header.get("reqid"), "error": repr(exc)}
                        )
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            for wid in conn_watches:
                self._watchers.pop(wid, None)
            fw.close()
            self._conn_writers.discard(writer)

    async def _dispatch(
        self, header: Dict[str, Any], payload: Any, fw: FrameWriter, conn_watches: Set[int]
    ) -> None:
        op = header.get("op")
        reqid = header.get("reqid")
        if op == "put":
            key = header["key"]
            self._data[key] = (payload, header.get("lease"))
            self._dirty = True
            await fw.send({"reqid": reqid, "ok": True})
            await self._notify(EventKind.PUT, key, payload)
        elif op == "delete":
            key = header["key"]
            existed = self._data.pop(key, None) is not None
            self._dirty = self._dirty or existed
            await fw.send({"reqid": reqid, "ok": True})
            if existed:
                await self._notify(EventKind.DELETE, key, None)
        elif op == "get":
            entry = self._data.get(header["key"])
            await fw.send({"reqid": reqid, "ok": True, "found": entry is not None},
                          entry[0] if entry else None)
        elif op == "get_prefix":
            prefix = header["prefix"]
            out = {k: v for k, (v, _) in self._data.items() if k.startswith(prefix)}
            await fw.send({"reqid": reqid, "ok": True}, out)
        elif op == "watch":
            wid = next(self._watch_ids)
            prefix = header["prefix"]
            snapshot = {
                k: v for k, (v, _) in sorted(self._data.items()) if k.startswith(prefix)
            }
            await fw.send({"reqid": reqid, "ok": True, "watch_id": wid}, snapshot)
            self._watchers[wid] = (prefix, fw)
            conn_watches.add(wid)
        elif op == "unwatch":
            wid = header.get("watch_id")
            self._watchers.pop(wid, None)
            conn_watches.discard(wid)
            await fw.send({"reqid": reqid, "ok": True})
        elif op == "lease_create":
            lid = uuid.uuid4().hex
            self._leases[lid] = (float(header["ttl"]), time.monotonic())
            self._dirty = True
            await fw.send({"reqid": reqid, "ok": True, "lease_id": lid})
        elif op == "lease_keepalive":
            lid = header["lease_id"]
            if lid in self._leases:
                ttl, _ = self._leases[lid]
                self._leases[lid] = (ttl, time.monotonic())
                await fw.send({"reqid": reqid, "ok": True})
            else:
                await fw.send({"reqid": reqid, "error": "lease not found"})
        elif op == "lease_revoke":
            await self._drop_lease(header["lease_id"])
            await fw.send({"reqid": reqid, "ok": True})
        else:
            await fw.send({"reqid": reqid, "error": f"unknown op {op!r}"})


# ---------------------------------------------------------------------------
# Client (DiscoveryBackend implementation)
# ---------------------------------------------------------------------------


class DiscdDiscovery:
    def __init__(self, address: str) -> None:
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._fw: Optional[FrameWriter] = None
        self._pump: Optional[asyncio.Task] = None
        self._reqids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, asyncio.Queue] = {}
        # Strong refs to watch bootstrap/unwatch tasks: the loop keeps
        # only weak ones, so an unretained handle can be GC'd mid-flight.
        self._bg_tasks: Set[asyncio.Task] = set()
        self._lock = asyncio.Lock()
        self._closed = False
        # _closed doubles as "connection needs re-establishing" (the pump
        # sets it on loss); _shutdown is the explicit close() — the only
        # thing that stops a bootstrap retry loop.
        self._shutdown = False

    async def _ensure(self) -> None:
        if self._fw is not None and not self._closed:
            return
        reader, writer = await asyncio.open_connection(self.host, self.port)
        self._fw = FrameWriter(writer)
        fr = FrameReader(reader)
        self._closed = False

        async def pump() -> None:
            try:
                while True:
                    frame = await fr.recv()
                    if frame is None:
                        break
                    header, payload = frame
                    if "watch" in header and "reqid" not in header:
                        q = self._watches.get(header["watch"])
                        if q is not None:
                            kind = EventKind(header["kind"])
                            q.put_nowait(
                                WatchEvent(kind, header["key"],
                                           payload if kind == EventKind.PUT else None)
                            )
                        continue
                    fut = self._pending.pop(header.get("reqid"), None)
                    if fut is not None and not fut.done():
                        fut.set_result((header, payload))
            finally:
                self._closed = True
                for fut in self._pending.values():
                    if not fut.done():
                        fut.set_exception(ConnectionError("discd connection lost"))
                self._pending.clear()
                for q in self._watches.values():
                    q.put_nowait(_WATCH_CLOSED)

        self._pump = asyncio.get_running_loop().create_task(pump(), name="discd-client-pump")

    def _spawn_bg(self, coro, *, name: str) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def _call(self, header: Dict[str, Any], payload: Any = None) -> Tuple[Dict[str, Any], Any]:
        async with self._lock:
            await self._ensure()
            reqid = next(self._reqids)
            header["reqid"] = reqid
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[reqid] = fut
            assert self._fw is not None
            await self._fw.send(header, payload)
        rh, rp = await fut
        if "error" in rh:
            raise RuntimeError(f"discd: {rh['error']}")
        return rh, rp

    # -- DiscoveryBackend ---------------------------------------------------

    async def put(self, key: str, value: Dict[str, Any], lease: Optional[Lease] = None) -> None:
        await self._call({"op": "put", "key": key, "lease": lease.id if lease else None}, value)

    async def delete(self, key: str) -> None:
        await self._call({"op": "delete", "key": key})

    async def get(self, key: str) -> Optional[Dict[str, Any]]:
        rh, rp = await self._call({"op": "get", "key": key})
        return rp if rh.get("found") else None

    async def get_prefix(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        _, rp = await self._call({"op": "get_prefix", "prefix": prefix})
        return rp or {}

    def watch(self, prefix: str) -> Watch:
        queue: asyncio.Queue = asyncio.Queue()
        snapshot_box: List[WatchEvent] = []
        watch_id_box: List[int] = []

        # The Watch must be returned synchronously (interface parity with the
        # memory backend); fetch the snapshot eagerly in a bootstrap task and
        # feed everything through the queue. Bootstrap retries with jittered
        # exponential backoff: a discd restart disconnects every client at
        # once, and bare one-shot bootstraps would either die (old behavior)
        # or stampede the recovering server in lockstep.
        async def bootstrap() -> None:
            from dynamo_tpu.runtime.tasks import Backoff

            backoff = Backoff(base_s=0.1, cap_s=5.0)
            while not self._shutdown:
                try:
                    rh, snapshot = await self._call(
                        {"op": "watch", "prefix": prefix}
                    )
                    wid = rh["watch_id"]
                    watch_id_box.append(wid)
                    self._watches[wid] = queue
                    for k, v in sorted((snapshot or {}).items()):
                        queue.put_nowait(WatchEvent(EventKind.PUT, k, v))
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    delay = backoff.next_delay()
                    logger.warning(
                        "discd watch bootstrap for %r failed (%r); "
                        "retrying in %.2fs", prefix, exc, delay,
                    )
                    await asyncio.sleep(delay)
            queue.put_nowait(_WATCH_CLOSED)

        self._spawn_bg(bootstrap(), name="discd-watch-bootstrap")

        def _close(w: Watch) -> None:
            if watch_id_box:
                wid = watch_id_box[0]
                self._watches.pop(wid, None)
                self._spawn_bg(
                    self._call({"op": "unwatch", "watch_id": wid}),
                    name="discd-unwatch",
                )
            queue.put_nowait(_WATCH_CLOSED)

        return Watch(prefix, snapshot_box, queue, on_close=_close)

    async def create_lease(self, ttl: float) -> Lease:
        rh, _ = await self._call({"op": "lease_create", "ttl": ttl})
        return Lease(id=rh["lease_id"], ttl=ttl)

    async def keep_alive(self, lease: Lease) -> None:
        await self._call({"op": "lease_keepalive", "lease_id": lease.id})

    async def revoke_lease(self, lease: Lease) -> None:
        await self._call({"op": "lease_revoke", "lease_id": lease.id})

    async def close(self) -> None:
        self._closed = True
        self._shutdown = True
        if self._pump is not None:
            self._pump.cancel()
            await reap_task(self._pump, "discd event pump", logger)
        for task in list(self._bg_tasks):
            task.cancel()
            await reap_task(task, "discd background task", logger)
        if self._fw is not None:
            self._fw.close()
            self._fw = None


class _quiet:
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        return et is not None and issubclass(et, (ConnectionError, RuntimeError))
