"""KV-reuse observability plane: prefix popularity, cache ROI, tier flow.

ROADMAP item 2 (enterprise-scale KV reuse) needs eviction informed by "the
router's observed prefix popularity" and a hit-rate win provable as TTFT
goodput — but nothing in the stack *observed* prefix popularity or what
each cache hit saved. This module is that measurement substrate (the
trajectory plane's sibling, design: docs/design_docs/kv_reuse_observability.md):

* ``PrefixPopularitySketch`` — a space-saving heavy-hitter sketch over
  block-hash-chain anchors: fixed capacity, min-replacement, exponentially
  decayed counts (recency-weighted popularity). Fed from router radix
  matches and engine prefix-cache hits; memory is bounded by capacity, not
  by the number of distinct prefixes ever seen.
* ``KvReuseMetrics`` — the lint-pinned ``ALL_KVCACHE`` family: hit rate by
  tier, reused vs recomputed prefill tokens, prefill-seconds-saved, sketch
  occupancy/replacements, tier-eviction reasons.
* ``KvReusePlane`` — the process-global aggregation point: sketch +
  metrics + the EWMA per-token prefill cost that prices a hit
  (seconds_saved = cached_tokens × cost/token), plus per-request ROI
  stamping into the trajectory plane (``note_event`` ring "kvcache").

Hot-path budget: every feed is O(1) amortized (dict lookup + heap push)
and rides admission / stream-end paths — OUTSIDE the DYN002 decode tick
scope — so the plane stays under the 1%/burst observe-overhead bar
(``_prof_gap.py``). Feeds never raise: observability must not take down
serving.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dynamo_tpu import config

logger = logging.getLogger(__name__)

# Declared in the canonical registry (config.py).
SKETCH_CAPACITY = config.KV_SKETCH_CAPACITY
SKETCH_HALF_LIFE_S = config.KV_SKETCH_HALF_LIFE_S


class _SketchEntry:
    """One tracked prefix. Counts are stored in inflated coordinates
    (see PrefixPopularitySketch) so ordering is time-invariant."""

    __slots__ = (
        "anchor", "count", "error", "hits", "tokens", "last_hit",
        "tiers", "workers",
    )

    def __init__(self, anchor: int) -> None:
        self.anchor = anchor
        self.count = 0.0  # inflated (scaled) decayed count
        self.error = 0.0  # space-saving overestimation bound (scaled)
        self.hits = 0  # raw lifetime touches (undecayed)
        self.tokens = 0  # cumulative tokens served from cache
        self.last_hit = 0.0  # wall clock, for display
        self.tiers: Dict[str, int] = {}  # tier -> raw hit count
        # worker key -> [scaled count, tokens] for zero-residue drop_worker
        self.workers: Dict[Any, List[float]] = {}


class PrefixPopularitySketch:
    """Space-saving heavy hitters with exponential time decay.

    Classic space-saving: at most ``capacity`` entries; an untracked key
    arriving at capacity replaces the minimum-count entry, inheriting its
    count as the overestimation ``error``. Guarantees every true heavy
    hitter above ~N/capacity is tracked, with bounded error.

    Decay without rescans: instead of decaying old counts we *inflate* new
    increments — a touch at time t has weight ``2^((t - origin)/half_life)``.
    Ratios between entries then equal the ratios of their exponentially
    decayed counts, ordering is time-invariant, and a lazy min-heap works.
    The true decayed count is recovered at read time by multiplying with
    ``2^(-(now - origin)/half_life)``; ``origin`` is rebased before the
    inflation factor can overflow a float.

    Thread-safe (router thread + engine loop may both feed it); every
    operation is O(log capacity) amortized.
    """

    # Rebase origin once the inflation exponent passes this (2^256 is
    # comfortably inside float range; rebase is O(capacity), rare).
    _REBASE_EXP = 256.0

    def __init__(
        self,
        capacity: Optional[int] = None,
        half_life_s: Optional[float] = None,
    ) -> None:
        self.capacity = int(capacity if capacity is not None else SKETCH_CAPACITY.get())
        self.half_life_s = float(
            half_life_s if half_life_s is not None else SKETCH_HALF_LIFE_S.get()
        )
        self._lock = threading.Lock()
        self._entries: Dict[int, _SketchEntry] = {}
        # Lazy min-heap of (scaled_count, anchor); stale tuples (count no
        # longer matching the entry) are skipped at pop time. Bounded by
        # periodic rebuild so sketch memory stays O(capacity).
        self._heap: List[Tuple[float, int]] = []
        self._origin = time.time()
        self.replacements = 0
        self.total_touches = 0

    # -- internals (lock held) ----------------------------------------------

    def _weight(self, now: float) -> float:
        if self.half_life_s <= 0:
            return 1.0
        exp = (now - self._origin) / self.half_life_s
        if exp > self._REBASE_EXP:
            self._rebase(now)
            exp = 0.0
        return 2.0 ** exp

    def _rebase(self, now: float) -> None:
        shift = 2.0 ** (-(now - self._origin) / self.half_life_s)
        for e in self._entries.values():
            e.count *= shift
            e.error *= shift
            for pair in e.workers.values():
                pair[0] *= shift
        self._origin = now
        self._rebuild_heap()

    def _rebuild_heap(self) -> None:
        self._heap = [(e.count, a) for a, e in self._entries.items()]
        heapq.heapify(self._heap)

    def _pop_min(self) -> _SketchEntry:
        """Remove and return the minimum-count entry (fresh heap top)."""
        while self._heap:
            count, anchor = heapq.heappop(self._heap)
            entry = self._entries.get(anchor)
            if entry is not None and entry.count == count:
                del self._entries[anchor]
                return entry
        # Heap exhausted by staleness: rebuild and retry (entries is
        # non-empty when this is called).
        self._rebuild_heap()
        return self._pop_min()

    def _decay_factor(self, now: float) -> float:
        if self.half_life_s <= 0:
            return 1.0
        return 2.0 ** (-(now - self._origin) / self.half_life_s)

    # -- feeds ---------------------------------------------------------------

    def touch(
        self,
        anchor: int,
        tokens: int = 0,
        tier: str = "device",
        worker: Any = None,
    ) -> None:
        """Record one cache hit on the prefix anchored at ``anchor``."""
        now = time.time()
        with self._lock:
            self.total_touches += 1
            w = self._weight(now)
            entry = self._entries.get(anchor)
            if entry is None:
                if len(self._entries) >= self.capacity:
                    victim = self._pop_min()
                    self.replacements += 1
                    entry = _SketchEntry(anchor)
                    # Space-saving inheritance: the newcomer takes the
                    # victim's count as its floor AND its error bound.
                    entry.count = victim.count
                    entry.error = victim.count
                else:
                    entry = _SketchEntry(anchor)
                self._entries[anchor] = entry
            entry.count += w
            entry.hits += 1
            entry.tokens += int(tokens)
            entry.last_hit = now
            entry.tiers[tier] = entry.tiers.get(tier, 0) + 1
            if worker is not None:
                pair = entry.workers.setdefault(worker, [0.0, 0.0])
                pair[0] += w
                pair[1] += tokens
            heapq.heappush(self._heap, (entry.count, anchor))
            if len(self._heap) > 8 * self.capacity:
                self._rebuild_heap()

    def drop_worker(self, worker: Any) -> int:
        """Zero-residue purge: subtract a departed worker's contributions;
        entries it alone sustained are removed. Returns entries touched."""
        touched = 0
        with self._lock:
            dead: List[int] = []
            for anchor, e in self._entries.items():
                pair = e.workers.pop(worker, None)
                if pair is None:
                    continue
                touched += 1
                e.count -= pair[0]
                e.tokens = max(0, e.tokens - int(pair[1]))
                # Entirely (or numerically) this worker's entry: drop it.
                if e.count <= e.error * 1e-12 + 1e-9 and not e.workers:
                    dead.append(anchor)
            for anchor in dead:
                del self._entries[anchor]
            if touched:
                self._rebuild_heap()
        return touched

    # -- reads ---------------------------------------------------------------

    def top(self, k: int = 20) -> List[Dict[str, Any]]:
        """Ranked top-K prefixes by decayed popularity."""
        now = time.time()
        with self._lock:
            f = self._decay_factor(now)
            ranked = sorted(
                self._entries.values(), key=lambda e: e.count, reverse=True
            )[: max(0, int(k))]
            return [
                {
                    "anchor": f"{e.anchor:016x}",
                    "score": e.count * f,
                    "score_error": e.error * f,
                    "hits": e.hits,
                    "tokens_from_cache": e.tokens,
                    "age_s": max(0.0, now - e.last_hit),
                    "tiers": dict(e.tiers),
                }
                for e in ranked
            ]

    def top_scores(self, k: int = 20) -> Dict[int, float]:
        """Ranked top-K as ``{anchor: decayed_score}`` — the narrow feed
        the KVBM eviction scorer consumes (kvbm/manager.py): integer
        anchors, no per-row formatting, one lock hold."""
        with self._lock:
            f = self._decay_factor(time.time())
            ranked = heapq.nlargest(
                max(0, int(k)), self._entries.values(),
                key=lambda e: e.count,
            )
            return {e.anchor: e.count * f for e in ranked}

    def stamp(self) -> Tuple[int, int]:
        """Cheap change marker: ``(total_touches, replacements)``.
        Consumers that cache a derived view (the KVBM protected-prefix
        map) rebuild only when this moves."""
        with self._lock:
            return (self.total_touches, self.replacements)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "tracked": len(self._entries),
                "replacements": self.replacements,
                "total_touches": self.total_touches,
                "half_life_s": self.half_life_s,
            }

    def __len__(self) -> int:
        return len(self._entries)


class KvReuseMetrics:
    """The ``ALL_KVCACHE`` family on a private registry (metrics_core.py
    rationale: several planes per process must not collide)."""

    def __init__(self, sketch: PrefixPopularitySketch) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self._sketch = sketch
        self.registry = MetricsRegistry()
        self.hits = self.registry.counter(
            mn.KVCACHE_HITS_TOTAL,
            "Prefix-cache hits by the tier the hit resolved from",
            ["tier"],
        )
        self.misses = self.registry.counter(
            mn.KVCACHE_MISSES_TOTAL,
            "Requests that found no cached prefix in any tier",
        )
        self.hit_rate = self.registry.gauge(
            mn.KVCACHE_HIT_RATE,
            "Fraction of prefix lookups resolved by each tier "
            "(render-time ratio of the hit/miss counters)",
            ["tier"],
        )
        self.reused_tokens = self.registry.counter(
            mn.KVCACHE_REUSED_TOKENS_TOTAL,
            "Prefill tokens served from cache instead of recomputed",
        )
        self.recomputed_tokens = self.registry.counter(
            mn.KVCACHE_RECOMPUTED_TOKENS_TOTAL,
            "Prefill tokens actually computed on device",
        )
        self.seconds_saved = self.registry.counter(
            mn.KVCACHE_PREFILL_SECONDS_SAVED_TOTAL,
            "Estimated prefill seconds saved by cache hits "
            "(cached tokens x EWMA per-token prefill cost)",
        )
        self.prefill_cost = self.registry.gauge(
            mn.KVCACHE_PREFILL_COST_PER_TOKEN,
            "EWMA per-token prefill cost the ROI estimate prices hits at",
        )
        self.sketch_tracked = self.registry.gauge(
            mn.KVCACHE_SKETCH_TRACKED_PREFIXES,
            "Prefixes tracked by the popularity sketch (<= capacity)",
        )
        self.sketch_replacements = self.registry.counter(
            mn.KVCACHE_SKETCH_REPLACEMENTS_TOTAL,
            "Space-saving min-replacements (sketch churn)",
        )
        self.sketch_lookup_p99 = self.registry.gauge(
            mn.KVCACHE_SKETCH_LOOKUP_P99_SECONDS,
            "p99 sketch touch latency (recorded by the scale harness)",
        )
        self.evictions = self.registry.counter(
            mn.KVCACHE_EVICTIONS_TOTAL,
            "Tier evictions by reason (arena_full | capacity | corrupt)",
            ["tier", "reason"],
        )
        self._known_tiers: set = set()
        self.registry.on_render(self._refresh)

    def _refresh(self) -> None:
        st = self._sketch.stats()
        self.sketch_tracked.set(st["tracked"])
        self.sketch_replacements.set_total(st["replacements"])
        # Hit rate per tier = tier hits / all lookups (hits + misses).
        total = self.misses.value()
        per_tier = {t: self.hits.value(tier=t) for t in self._known_tiers}
        total += sum(per_tier.values())
        for t, n in per_tier.items():
            self.hit_rate.set(n / total if total > 0 else 0.0, tier=t)

    def note_hit(self, tier: str) -> None:
        self._known_tiers.add(tier)
        self.hits.inc(tier=tier)

    def forget_tier(self, tier: str) -> None:
        """Departed-tier GC: drop the gauge series (counters stay — they
        are monotonic history)."""
        self._known_tiers.discard(tier)
        self.hit_rate.remove(tier=tier)

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class KvReusePlane:
    """Process-global aggregation point for the KV-reuse plane."""

    # EWMA smoothing for the per-token prefill cost (same spirit as the
    # disagg link-bandwidth EWMA: stable under bursty chunk sizes).
    _EWMA_ALPHA = 0.2

    def __init__(
        self,
        capacity: Optional[int] = None,
        half_life_s: Optional[float] = None,
    ) -> None:
        self.sketch = PrefixPopularitySketch(capacity, half_life_s)
        self.metrics = KvReuseMetrics(self.sketch)
        self._cost_lock = threading.Lock()
        self._cost_per_token: Optional[float] = None
        # Live tier-occupancy sources: label -> callable returning
        # {tier: {"blocks": int, ...}}. Registered by TieredKvManager
        # (and anything else holding tiers); deregistered on close.
        self._tier_sources: Dict[str, Callable[[], Dict[str, Any]]] = {}

    # -- prefill cost (the ROI price) ---------------------------------------

    def note_prefill_cost(self, duration_s: float, tokens: int) -> None:
        """Feed one prefill dispatch (engines observe_prefill rides this)."""
        if tokens <= 0 or duration_s <= 0:
            return
        per_token = duration_s / tokens
        with self._cost_lock:
            if self._cost_per_token is None:
                self._cost_per_token = per_token
            else:
                self._cost_per_token += self._EWMA_ALPHA * (
                    per_token - self._cost_per_token
                )
            self.metrics.prefill_cost.set(self._cost_per_token)

    def prefill_cost_per_token(self) -> float:
        with self._cost_lock:
            return self._cost_per_token or 0.0

    # -- per-request attribution --------------------------------------------

    def note_request(
        self,
        *,
        anchor: Optional[int],
        cached_tokens: int,
        recomputed_tokens: int,
        tier: str = "device",
        worker: Any = None,
        trace_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """One admitted request's cache outcome: sketch + ROI counters +
        (when traced) a trajectory "kvcache"/"roi" event. Returns the ROI
        dict so callers can stamp it elsewhere (lifecycle, bench)."""
        seconds_saved = cached_tokens * self.prefill_cost_per_token()
        roi = {
            "cached_tokens": int(cached_tokens),
            "recomputed_tokens": int(recomputed_tokens),
            "seconds_saved": seconds_saved,
            "tier": tier,
        }
        try:
            if cached_tokens > 0:
                if anchor is not None:
                    self.sketch.touch(
                        anchor, tokens=cached_tokens, tier=tier, worker=worker
                    )
                self.metrics.note_hit(tier)
                self.metrics.reused_tokens.inc(int(cached_tokens))
                if seconds_saved > 0:
                    self.metrics.seconds_saved.inc(seconds_saved)
            else:
                self.metrics.misses.inc()
            if recomputed_tokens > 0:
                self.metrics.recomputed_tokens.inc(int(recomputed_tokens))
            if trace_id:
                from dynamo_tpu.runtime.trajectory import note_event

                note_event(trace_id, "kvcache", "roi", **roi)
        except Exception:
            # Observability must not take down serving — but a plane bug
            # must not be invisible either.
            logger.debug("kv-reuse ROI feed failed", exc_info=True)
        return roi

    def note_router_match(
        self, anchor: int, tokens: int, worker: Any = None
    ) -> None:
        """Router radix match: popularity only (the engine-side hit will
        account the metrics — double feeds would inflate hit rates)."""
        try:
            self.sketch.touch(anchor, tokens=tokens, tier="device", worker=worker)
        except Exception:
            logger.debug("kv-reuse router feed failed", exc_info=True)

    def note_eviction(self, tier: str, reason: str, n: int = 1) -> None:
        if n > 0:
            self.metrics.evictions.inc(n, tier=tier, reason=reason)

    def drop_worker(self, worker: Any) -> int:
        """Departed-worker purge (the PR 10 zero-residue audit extended to
        this plane): sketch contributions subtracted, entries it alone
        sustained removed."""
        return self.sketch.drop_worker(worker)

    # -- tier sources / introspection ---------------------------------------

    def register_tier_source(
        self, label: str, fn: Callable[[], Dict[str, Any]]
    ) -> None:
        self._tier_sources[label] = fn

    def forget_tier_source(self, label: str) -> None:
        self._tier_sources.pop(label, None)

    def tiers(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for label, fn in list(self._tier_sources.items()):
            try:
                out[label] = fn()
            except Exception:
                out[label] = {"error": "source failed"}
        return out

    def snapshot(self, top_k: int = 10) -> Dict[str, Any]:
        """The GET /debug/kvcache body (also the CLI's source)."""
        m = self.metrics
        m._refresh()
        hit_rate = {
            t: m.hit_rate.value(tier=t) for t in sorted(m._known_tiers)
        }
        return {
            "hit_rate": hit_rate,
            "hits": {
                t: m.hits.value(tier=t) for t in sorted(m._known_tiers)
            },
            "misses": m.misses.value(),
            "reused_prefill_tokens": m.reused_tokens.value(),
            "recomputed_prefill_tokens": m.recomputed_tokens.value(),
            "prefill_seconds_saved": m.seconds_saved.value(),
            "prefill_cost_per_token_s": self.prefill_cost_per_token(),
            "sketch": self.sketch.stats(),
            "tiers": self.tiers(),
            "top_prefixes": self.sketch.top(top_k),
        }


_PLANE: Optional[KvReusePlane] = None
_PLANE_LOCK = threading.Lock()


def global_plane() -> KvReusePlane:
    """The process-global plane (router, engines, and KVBM all feed the
    same sketch — colocated planes share popularity by design)."""
    global _PLANE
    if _PLANE is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                _PLANE = KvReusePlane()
    return _PLANE


def render_kv_reuse_metrics(openmetrics: bool = False) -> str:
    """ALL_KVCACHE exposition for every SystemStatusServer (the KV-reuse
    analog of render_trajectory_metrics)."""
    return global_plane().metrics.render(openmetrics=openmetrics)


def kvcache_index(
    plane: Optional[KvReusePlane] = None, top_k: int = 10
) -> Dict[str, Any]:
    """The GET /debug/kvcache response body — ONE shape shared by the
    system server and the CLI."""
    plane = plane if plane is not None else global_plane()
    return plane.snapshot(top_k=top_k)


def kvcache_prefixes(
    plane: Optional[KvReusePlane] = None, k: int = 50
) -> Dict[str, Any]:
    """The GET /debug/kvcache/prefixes body: ranked top-K + sketch stats."""
    plane = plane if plane is not None else global_plane()
    return {"sketch": plane.sketch.stats(), "prefixes": plane.sketch.top(k)}
