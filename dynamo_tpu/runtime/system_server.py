"""Per-process system HTTP server: health, metrics, engine admin, LoRAs.

Reference parity: lib/runtime/src/system_status_server.rs — every worker
process exposes a small HTTP surface for orchestration:
  GET  /health             aggregated health (registered sources)
  GET  /live               liveness (the process event loop turns)
  GET  /metrics            Prometheus text (registered collectors)
  ANY  /engine/{path}      registered engine callbacks (sleep/wake/stats/…)
  GET  /v1/loras           list loaded adapters
  POST /v1/loras           {"name": ..., "path": ...} load an adapter
  DELETE /v1/loras/{name}  unload an adapter

This is the TPU build's analog of the reference's axum system server; the
engine registers its callbacks via ``attach_engine`` (the reference's
engine-routes registry, system_status_server.rs /engine/{*path} handler).
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from aiohttp import web

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# handler(body: dict) -> (status, payload)
EngineRoute = Callable[[Dict[str, Any]], Awaitable[Tuple[int, Any]]]


class SystemStatusServer:
    def __init__(self, *, host: str = "0.0.0.0", port: int = 0) -> None:
        self.host = host
        self.port = port
        self._engine_routes: Dict[str, EngineRoute] = {}
        self._health_sources: Dict[str, Callable[[], Tuple[bool, Any]]] = {}
        self._metrics_sources: List[Callable[[], str]] = []
        self._lora_list: Optional[Callable[[], List[str]]] = None
        self._lora_load: Optional[Callable[[str, str], Awaitable[None]]] = None
        self._lora_unload: Optional[Callable[[str], Awaitable[None]]] = None
        self._runner: Optional[web.AppRunner] = None

    # -- registration ------------------------------------------------------

    def register_engine_route(self, path: str, handler: EngineRoute) -> None:
        self._engine_routes[path.strip("/")] = handler

    def register_health(
        self, name: str, fn: Callable[[], Tuple[bool, Any]]
    ) -> None:
        self._health_sources[name] = fn

    def register_metrics(self, fn: Callable[[], str]) -> None:
        """fn returns Prometheus exposition-format text."""
        self._metrics_sources.append(fn)

    def register_loras(self, list_fn, load_fn, unload_fn) -> None:
        self._lora_list = list_fn
        self._lora_load = load_fn
        self._lora_unload = unload_fn

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_route("*", "/engine/{path:.*}", self._engine)
        app.router.add_get("/v1/loras", self._loras_list)
        app.router.add_post("/v1/loras", self._loras_load)
        app.router.add_delete("/v1/loras/{name}", self._loras_unload)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # Resolve the ephemeral port for port=0.
        server = site._server  # noqa: SLF001 - aiohttp exposes no accessor
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]
        logger.info("system status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers ----------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        details: Dict[str, Any] = {}
        healthy = True
        for name, fn in self._health_sources.items():
            try:
                ok, detail = fn()
            except Exception as exc:  # a broken source is an unhealthy one
                ok, detail = False, f"health source error: {exc}"
            details[name] = detail
            healthy = healthy and ok
        status = 200 if healthy else 503
        return web.json_response(
            {"status": "healthy" if healthy else "unhealthy", "details": details},
            status=status,
        )

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, request: web.Request) -> web.Response:
        parts = []
        for fn in self._metrics_sources:
            try:
                parts.append(fn())
            except Exception:
                logger.exception("metrics source failed")
        return web.Response(
            text="\n".join(parts) + "\n",
            content_type="text/plain",
            charset="utf-8",
        )

    async def _engine(self, request: web.Request) -> web.Response:
        path = request.match_info["path"].strip("/")
        handler = self._engine_routes.get(path)
        if handler is None:
            return web.json_response(
                {"error": f"no engine route {path!r}",
                 "routes": sorted(self._engine_routes)},
                status=404,
            )
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        try:
            status, payload = await handler(body if isinstance(body, dict) else {})
        except Exception as exc:
            logger.exception("engine route %s failed", path)
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(payload, status=status)

    async def _loras_list(self, request: web.Request) -> web.Response:
        if self._lora_list is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        return web.json_response({"loras": self._lora_list()})

    async def _loras_load(self, request: web.Request) -> web.Response:
        if self._lora_load is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        try:
            body = await request.json()
            name, path = body["name"], body["path"]
        except Exception:
            return web.json_response(
                {"error": "body must be {'name': ..., 'path': ...}"}, status=400
            )
        try:
            await self._lora_load(name, path)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:
            logger.exception("LoRA load failed")
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response({"loaded": name}, status=201)

    async def _loras_unload(self, request: web.Request) -> web.Response:
        if self._lora_unload is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        name = request.match_info["name"]
        try:
            await self._lora_unload(name)
        except KeyError as exc:
            return web.json_response({"error": str(exc)}, status=404)
        return web.json_response({"unloaded": name})


def engine_stats_prometheus(stats: Dict[str, Any]) -> str:
    """Engine stats dict → Prometheus gauges with canonical names
    (ref: metrics/prometheus_names.rs — runtime/metric_names.py is the
    single place that defines them)."""
    from dynamo_tpu.runtime.metric_names import engine_gauge

    lines = []
    for key, value in stats.items():
        if isinstance(value, dict):
            continue  # nested (kvbm) stats get their own exporter if needed
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        name = engine_gauge(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {float(value)}")
    return "\n".join(lines)


def attach_engine(server: SystemStatusServer, engine: Any) -> None:
    """Register the native engine's admin surface on the system server
    (ref: the engine-routes registry in system_status_server.rs plus vllm
    handlers sleep/wake and LoRA load/unload)."""

    async def _stats(body: Dict[str, Any]):
        return 200, engine.stats()

    async def _sleep(body: Dict[str, Any]):
        await engine.sleep(int(body.get("level", 1)))
        return 200, {"sleeping": True, "level": engine.sleep_level}

    async def _wake(body: Dict[str, Any]):
        await engine.wake()
        return 200, {"sleeping": False}

    async def _clear(body: Dict[str, Any]):
        return 200, {"cleared_blocks": engine.clear_kv_blocks()}

    async def _checkpoint(body: Dict[str, Any]):
        path = body.get("path")
        if not path:
            return 400, {"error": "body must include 'path'"}
        return 200, await engine.save_checkpoint(path)

    async def _restore(body: Dict[str, Any]):
        path = body.get("path")
        if not path:
            return 400, {"error": "body must include 'path'"}
        try:
            n = await engine.load_checkpoint(path)
        except (OSError, ValueError, KeyError, IndexError) as exc:
            # Malformed manifests surface as any of these (JSONDecodeError
            # is a ValueError; missing fields KeyError; short data arrays
            # IndexError) — all are bad-input 400s, not server faults.
            return 400, {"error": repr(exc)}
        return 200, {"restored_blocks": n}

    server.register_engine_route("stats", _stats)
    server.register_engine_route("sleep", _sleep)
    server.register_engine_route("wake", _wake)
    server.register_engine_route("clear_kv_blocks", _clear)
    server.register_engine_route("checkpoint", _checkpoint)
    server.register_engine_route("restore", _restore)

    def _engine_health():
        failure = getattr(engine, "_failure", None)
        if failure is not None:
            return False, f"engine failed: {failure}"
        if engine.sleep_level > 0:
            return True, f"asleep (level {engine.sleep_level})"
        return True, "serving"

    server.register_health("engine", _engine_health)
    server.register_metrics(lambda: engine_stats_prometheus(engine.stats()))

    async def _load(name: str, path: str) -> None:
        # Disk I/O + stacking + host→device transfer off the event loop —
        # a multi-second inline load would stall token streaming and the
        # discovery lease keep-alive.
        device = getattr(engine, "_device", None)
        if device is not None:
            await device(engine.load_lora, name, path)
        else:
            await asyncio.get_running_loop().run_in_executor(
                None, engine.load_lora, name, path
            )

    async def _unload(name: str) -> None:
        # Same device-thread routing as _load: under multihost the restack
        # op must serialize with in-flight decode mirroring.
        device = getattr(engine, "_device", None)
        if device is not None:
            await device(engine.unload_lora, name)
        else:
            engine.unload_lora(name)

    server.register_loras(engine.lora_names, _load, _unload)
