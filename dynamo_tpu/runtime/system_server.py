"""Per-process system HTTP server: health, metrics, engine admin, LoRAs.

Reference parity: lib/runtime/src/system_status_server.rs — every worker
process exposes a small HTTP surface for orchestration:
  GET  /health             aggregated health (registered sources)
  GET  /live               liveness (the process event loop turns)
  GET  /metrics            Prometheus text (registered collectors)
  ANY  /engine/{path}      registered engine callbacks (sleep/wake/stats/…)
  GET  /v1/loras           list loaded adapters
  POST /v1/loras           {"name": ..., "path": ...} load an adapter
  DELETE /v1/loras/{name}  unload an adapter

Debug surface (serving-plane observability tentpole):
  GET  /debug/requests       recent + slow request-timeline summaries
  GET  /debug/requests/{id}  one ordered lifecycle timeline
  GET  /debug/traces         the process tracer's finished-span ring

This is the TPU build's analog of the reference's axum system server; the
engine registers its callbacks via ``attach_engine`` (the reference's
engine-routes registry, system_status_server.rs /engine/{*path} handler).

``/metrics`` speaks OpenMetrics when the scraper asks for it (Accept:
application/openmetrics-text): metrics sources whose render callable takes
an ``openmetrics`` keyword (runtime/metrics_core.py registries) then emit
trace-id exemplars, linking histogram spikes to /debug timelines.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from aiohttp import web

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# handler(body: dict) -> (status, payload)
EngineRoute = Callable[[Dict[str, Any]], Awaitable[Tuple[int, Any]]]


def _takes_openmetrics(fn: Callable[..., str]) -> bool:
    """Does this metrics source accept an ``openmetrics`` keyword
    (metrics_core registries do; plain text lambdas don't)?"""
    try:
        return "openmetrics" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def _merge_expositions(parts: List[str]) -> str:
    """Concatenate metric sources, collapsing duplicate family metadata.

    Two same-kind subsystem objects on one server (metrics_core's per-object
    registries make this easy — e.g. two tiered managers both calling
    ``register_metrics``) each emit their own ``# HELP``/``# TYPE`` block
    for the same family, and Prometheus rejects an exposition whose
    metadata repeats or interleaves. Group every source's samples under one
    metadata block per family (first HELP/TYPE wins); sample lines pass
    through verbatim. Identical series from two sources therefore stay
    visible as duplicates (Prometheus flags them) instead of being
    silently collapsed or summed — objects whose series would collide
    should share one metrics instance instead.
    """
    order: List[str] = []
    meta: Dict[str, List[str]] = {}
    samples: Dict[str, List[str]] = {}

    def block(name: str) -> None:
        if name not in meta:
            meta[name] = []
            samples[name] = []
            order.append(name)

    for part in parts:
        current = ""  # bare samples before any metadata keep source order
        for line in part.splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind, name = line.split(None, 3)[1:3]
                block(name)
                current = name
                if not any(m.startswith(f"# {kind} ") for m in meta[name]):
                    meta[name].append(line)
            elif line.startswith("#"):
                continue  # stray comments / EOF markers from a source
            else:
                block(current)
                samples[current].append(line)
    lines: List[str] = []
    for name in order:
        lines.extend(meta[name])
        lines.extend(samples[name])
    return "\n".join(lines)


class SystemStatusServer:
    def __init__(
        self,
        *,
        host: str = "0.0.0.0",
        port: int = 0,
        lifecycle: Any = None,  # RequestLifecycle; None = process-global
        tracer: Any = None,  # utils/tracing.Tracer; None = process-global
    ) -> None:
        self.host = host
        self.port = port
        self._lifecycle = lifecycle
        self._tracer = tracer
        self._engine_routes: Dict[str, EngineRoute] = {}
        self._health_sources: Dict[str, Callable[[], Tuple[bool, Any]]] = {}
        # (render fn, takes-openmetrics-kwarg) — classified once at
        # registration so the scrape path skips per-request reflection.
        self._metrics_sources: List[Tuple[Callable[[], str], bool]] = []
        self._lora_list: Optional[Callable[[], List[str]]] = None
        self._lora_load: Optional[Callable[[str, str], Awaitable[None]]] = None
        self._lora_unload: Optional[Callable[[str], Awaitable[None]]] = None
        self._runner: Optional[web.AppRunner] = None

    # -- registration ------------------------------------------------------

    def register_engine_route(self, path: str, handler: EngineRoute) -> None:
        self._engine_routes[path.strip("/")] = handler

    def register_health(
        self, name: str, fn: Callable[[], Tuple[bool, Any]]
    ) -> None:
        self._health_sources[name] = fn

    def register_metrics(self, fn: Callable[[], str]) -> None:
        """fn returns Prometheus exposition-format text."""
        self._metrics_sources.append((fn, _takes_openmetrics(fn)))

    def register_loras(self, list_fn, load_fn, unload_fn) -> None:
        self._lora_list = list_fn
        self._lora_load = load_fn
        self._lora_unload = unload_fn

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/debug/requests", self._debug_requests)
        app.router.add_get("/debug/requests/{id}", self._debug_request)
        app.router.add_get("/debug/traces", self._debug_traces)
        app.router.add_route("*", "/engine/{path:.*}", self._engine)
        app.router.add_get("/v1/loras", self._loras_list)
        app.router.add_post("/v1/loras", self._loras_load)
        app.router.add_delete("/v1/loras/{name}", self._loras_unload)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # Resolve the ephemeral port for port=0.
        server = site._server  # noqa: SLF001 - aiohttp exposes no accessor
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]
        logger.info("system status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers ----------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        details: Dict[str, Any] = {}
        healthy = True
        for name, fn in self._health_sources.items():
            try:
                ok, detail = fn()
            except Exception as exc:  # a broken source is an unhealthy one
                ok, detail = False, f"health source error: {exc}"
            details[name] = detail
            healthy = healthy and ok
        status = 200 if healthy else 503
        return web.json_response(
            {"status": "healthy" if healthy else "unhealthy", "details": details},
            status=status,
        )

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, request: web.Request) -> web.Response:
        openmetrics = "application/openmetrics-text" in request.headers.get(
            "Accept", ""
        )
        parts = []
        for fn, takes_om in self._metrics_sources:
            try:
                if openmetrics and takes_om:
                    parts.append(fn(openmetrics=True))
                else:
                    parts.append(fn())
            except Exception:
                logger.exception("metrics source failed")
        text = _merge_expositions([p for p in parts if p])
        if openmetrics:
            return web.Response(
                text=text + "\n# EOF\n",
                content_type="application/openmetrics-text",
                charset="utf-8",
            )
        return web.Response(
            text=text + "\n",
            content_type="text/plain",
            charset="utf-8",
        )

    # -- debug surface (lifecycle timelines + trace ring) ------------------

    def _lifecycle_obj(self):
        if self._lifecycle is None:
            from dynamo_tpu.runtime.lifecycle import global_lifecycle

            self._lifecycle = global_lifecycle()
        return self._lifecycle

    def _tracer_obj(self):
        if self._tracer is None:
            from dynamo_tpu.utils.tracing import global_tracer

            self._tracer = global_tracer()
        return self._tracer

    async def _debug_requests(self, request: web.Request) -> web.Response:
        lc = self._lifecycle_obj()
        return web.json_response(
            {
                "slow_threshold_s": lc.slow_threshold_s,
                "requests": [tl.summary() for tl in lc.timelines()],
                "slow": [tl.request_id for tl in lc.slow_timelines()],
            }
        )

    async def _debug_request(self, request: web.Request) -> web.Response:
        rid = request.match_info["id"]
        tl = self._lifecycle_obj().get(rid)
        if tl is None:
            return web.json_response(
                {"error": f"no timeline for request {rid!r}"}, status=404
            )
        return web.json_response(tl.to_dict())

    async def _debug_traces(self, request: web.Request) -> web.Response:
        """Dump the span ring, optionally filtered: /debug/traces?trace_id=…
        returns only that trace (the exemplar-chasing path)."""
        want = request.query.get("trace_id")
        spans = self._tracer_obj().finished_spans()
        if want:
            spans = [s for s in spans if s.trace_id == want]
        return web.json_response({"spans": [s.to_dict() for s in spans]})

    async def _engine(self, request: web.Request) -> web.Response:
        path = request.match_info["path"].strip("/")
        handler = self._engine_routes.get(path)
        if handler is None:
            return web.json_response(
                {"error": f"no engine route {path!r}",
                 "routes": sorted(self._engine_routes)},
                status=404,
            )
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        try:
            status, payload = await handler(body if isinstance(body, dict) else {})
        except Exception as exc:
            logger.exception("engine route %s failed", path)
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(payload, status=status)

    async def _loras_list(self, request: web.Request) -> web.Response:
        if self._lora_list is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        return web.json_response({"loras": self._lora_list()})

    async def _loras_load(self, request: web.Request) -> web.Response:
        if self._lora_load is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        try:
            body = await request.json()
            name, path = body["name"], body["path"]
        except Exception:
            return web.json_response(
                {"error": "body must be {'name': ..., 'path': ...}"}, status=400
            )
        try:
            await self._lora_load(name, path)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:
            logger.exception("LoRA load failed")
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response({"loaded": name}, status=201)

    async def _loras_unload(self, request: web.Request) -> web.Response:
        if self._lora_unload is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        name = request.match_info["name"]
        try:
            await self._lora_unload(name)
        except KeyError as exc:
            return web.json_response({"error": str(exc)}, status=404)
        return web.json_response({"unloaded": name})


def engine_stats_prometheus(stats: Dict[str, Any]) -> str:
    """Engine stats dict → Prometheus gauges with canonical names
    (ref: metrics/prometheus_names.rs — runtime/metric_names.py is the
    single place that defines them). Nested dict stats (the ``kvbm``
    sub-dict) flatten into ``<prefix>_<key>_<subkey>`` gauges instead of
    silently disappearing from the scrape."""
    from dynamo_tpu.runtime.metric_names import engine_gauge

    lines: List[str] = []

    def emit(name: str, value: float, source: str) -> None:
        lines.append(f"# HELP {name} Engine stat {source!r} (engine.stats())")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {float(value)}")

    def numeric(value: Any) -> bool:
        return not isinstance(value, bool) and isinstance(value, (int, float))

    for key, value in stats.items():
        if isinstance(value, dict):
            for sub, sv in value.items():
                if numeric(sv):
                    emit(engine_gauge(f"{key}_{sub}"), sv, f"{key}.{sub}")
            continue
        if numeric(value):
            emit(engine_gauge(key), value, key)
    return "\n".join(lines)


def attach_engine(server: SystemStatusServer, engine: Any) -> None:
    """Register the native engine's admin surface on the system server
    (ref: the engine-routes registry in system_status_server.rs plus vllm
    handlers sleep/wake and LoRA load/unload)."""

    async def _stats(body: Dict[str, Any]):
        return 200, engine.stats()

    async def _sleep(body: Dict[str, Any]):
        await engine.sleep(int(body.get("level", 1)))
        return 200, {"sleeping": True, "level": engine.sleep_level}

    async def _wake(body: Dict[str, Any]):
        await engine.wake()
        return 200, {"sleeping": False}

    async def _clear(body: Dict[str, Any]):
        return 200, {"cleared_blocks": engine.clear_kv_blocks()}

    async def _checkpoint(body: Dict[str, Any]):
        path = body.get("path")
        if not path:
            return 400, {"error": "body must include 'path'"}
        return 200, await engine.save_checkpoint(path)

    async def _restore(body: Dict[str, Any]):
        path = body.get("path")
        if not path:
            return 400, {"error": "body must include 'path'"}
        try:
            n = await engine.load_checkpoint(path)
        except (OSError, ValueError, KeyError, IndexError) as exc:
            # Malformed manifests surface as any of these (JSONDecodeError
            # is a ValueError; missing fields KeyError; short data arrays
            # IndexError) — all are bad-input 400s, not server faults.
            return 400, {"error": repr(exc)}
        return 200, {"restored_blocks": n}

    server.register_engine_route("stats", _stats)
    server.register_engine_route("sleep", _sleep)
    server.register_engine_route("wake", _wake)
    server.register_engine_route("clear_kv_blocks", _clear)
    server.register_engine_route("checkpoint", _checkpoint)
    server.register_engine_route("restore", _restore)

    def _engine_health():
        failure = getattr(engine, "_failure", None)
        if failure is not None:
            return False, f"engine failed: {failure}"
        if engine.sleep_level > 0:
            return True, f"asleep (level {engine.sleep_level})"
        return True, "serving"

    server.register_health("engine", _engine_health)
    server.register_metrics(lambda: engine_stats_prometheus(engine.stats()))
    step_metrics = getattr(engine, "step_metrics", None)
    if step_metrics is not None:
        step_metrics.register_metrics(server)

    async def _load(name: str, path: str) -> None:
        # Disk I/O + stacking + host→device transfer off the event loop —
        # a multi-second inline load would stall token streaming and the
        # discovery lease keep-alive.
        device = getattr(engine, "_device", None)
        if device is not None:
            await device(engine.load_lora, name, path)
        else:
            await asyncio.get_running_loop().run_in_executor(
                None, engine.load_lora, name, path
            )

    async def _unload(name: str) -> None:
        # Same device-thread routing as _load: under multihost the restack
        # op must serialize with in-flight decode mirroring.
        device = getattr(engine, "_device", None)
        if device is not None:
            await device(engine.unload_lora, name)
        else:
            engine.unload_lora(name)

    server.register_loras(engine.lora_names, _load, _unload)
