"""Per-process system HTTP server: health, metrics, engine admin, LoRAs.

Reference parity: lib/runtime/src/system_status_server.rs — every worker
process exposes a small HTTP surface for orchestration:
  GET  /health             aggregated health (registered sources)
  GET  /live               liveness (the process event loop turns)
  GET  /metrics            Prometheus text (registered collectors)
  ANY  /engine/{path}      registered engine callbacks (sleep/wake/stats/…)
  GET  /v1/loras           list loaded adapters
  POST /v1/loras           {"name": ..., "path": ...} load an adapter
  DELETE /v1/loras/{name}  unload an adapter

Debug surface (serving-plane observability tentpole):
  GET  /debug/requests       recent + slow request-timeline summaries
  GET  /debug/requests/{id}  one ordered lifecycle timeline
  GET  /debug/traces         the process tracer's finished-span ring

KV-reuse plane (runtime/kv_reuse_observe.py):
  GET  /debug/kvcache          hit-rate/ROI rollup + sketch stats + top
                               prefixes (?top_k=)
  GET  /debug/kvcache/prefixes ranked prefix popularity, full depth (?k=)

Device-plane debug surface (runtime/device_observe.py):
  GET  /debug/memory         HBM ledger categories + pool byte split +
                             device.memory_stats() + host weight-cache tiers
  GET  /debug/compiles       per-program compile telemetry (watched_jit)
  GET  /debug/flight         merged flight-recorder rings (?limit=, ?kind=)
  POST /debug/profile        {"action": "start"|"stop"|"status", "dir"?,
                             "seconds"?} — on-demand jax.profiler capture

This is the TPU build's analog of the reference's axum system server; the
engine registers its callbacks via ``attach_engine`` (the reference's
engine-routes registry, system_status_server.rs /engine/{*path} handler).

``/metrics`` speaks OpenMetrics when the scraper asks for it (Accept:
application/openmetrics-text): metrics sources whose render callable takes
an ``openmetrics`` keyword (runtime/metrics_core.py registries) then emit
trace-id exemplars, linking histogram spikes to /debug timelines.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from aiohttp import web

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# handler(body: dict) -> (status, payload)
EngineRoute = Callable[[Dict[str, Any]], Awaitable[Tuple[int, Any]]]


def _takes_openmetrics(fn: Callable[..., str]) -> bool:
    """Does this metrics source accept an ``openmetrics`` keyword
    (metrics_core registries do; plain text lambdas don't)?"""
    try:
        return "openmetrics" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def _merge_expositions(parts: List[str]) -> str:
    """Concatenate metric sources, collapsing duplicate family metadata.

    Two same-kind subsystem objects on one server (metrics_core's per-object
    registries make this easy — e.g. two tiered managers both calling
    ``register_metrics``) each emit their own ``# HELP``/``# TYPE`` block
    for the same family, and Prometheus rejects an exposition whose
    metadata repeats or interleaves. Group every source's samples under one
    metadata block per family (first HELP/TYPE wins); sample lines pass
    through verbatim. Identical series from two sources therefore stay
    visible as duplicates (Prometheus flags them) instead of being
    silently collapsed or summed — objects whose series would collide
    should share one metrics instance instead.
    """
    order: List[str] = []
    meta: Dict[str, List[str]] = {}
    samples: Dict[str, List[str]] = {}

    def block(name: str) -> None:
        if name not in meta:
            meta[name] = []
            samples[name] = []
            order.append(name)

    for part in parts:
        current = ""  # bare samples before any metadata keep source order
        for line in part.splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind, name = line.split(None, 3)[1:3]
                block(name)
                current = name
                if not any(m.startswith(f"# {kind} ") for m in meta[name]):
                    meta[name].append(line)
            elif line.startswith("#"):
                continue  # stray comments / EOF markers from a source
            else:
                block(current)
                samples[current].append(line)
    lines: List[str] = []
    for name in order:
        lines.extend(meta[name])
        lines.extend(samples[name])
    return "\n".join(lines)


class SystemStatusServer:
    def __init__(
        self,
        *,
        host: str = "0.0.0.0",
        port: int = 0,
        lifecycle: Any = None,  # RequestLifecycle; None = process-global
        tracer: Any = None,  # utils/tracing.Tracer; None = process-global
        trajectory: Any = None,  # TrajectoryStore; None = process-global
    ) -> None:
        self.host = host
        self.port = port
        self._lifecycle = lifecycle
        self._tracer = tracer
        self._trajectory = trajectory
        self._engine_routes: Dict[str, EngineRoute] = {}
        self._health_sources: Dict[str, Callable[[], Tuple[bool, Any]]] = {}
        # Readiness sources (crash plane): /readyz is 200 only when EVERY
        # registered source reports ready. Liveness (/healthz, /live) is
        # process-up only — a restoring worker is alive but NOT ready, so
        # the kubelet keeps it out of service without restarting it.
        self._ready_sources: Dict[str, Callable[[], Tuple[bool, Any]]] = {}
        # (render fn, takes-openmetrics-kwarg) — classified once at
        # registration so the scrape path skips per-request reflection.
        self._metrics_sources: List[Tuple[Callable[[], str], bool]] = []
        self._lora_list: Optional[Callable[[], List[str]]] = None
        self._lora_load: Optional[Callable[[str, str], Awaitable[None]]] = None
        self._lora_unload: Optional[Callable[[str], Awaitable[None]]] = None
        # Device-plane debug sources: flight-recorder rings (name →
        # snapshot fn) and HBM-ledger samplers (name → category dict fn).
        self._flight_sources: List[Tuple[str, Callable[[], List[Any]]]] = []
        self._memory_sources: List[Tuple[str, Callable[[], Dict[str, int]]]] = []
        # Drain plane (runtime/drain.py): (start_fn(deadline_s) -> awaitable
        # status dict, status_fn() -> dict). Registered by register_drain.
        self._drain_start: Optional[Callable[..., Awaitable[Dict[str, Any]]]] = None
        self._drain_status: Optional[Callable[[], Dict[str, Any]]] = None
        self._profile_timers: set = set()  # strong refs to auto-stop tasks
        self._runtime_metrics_registered = False
        self._runner: Optional[web.AppRunner] = None

    # -- registration ------------------------------------------------------

    def register_engine_route(self, path: str, handler: EngineRoute) -> None:
        self._engine_routes[path.strip("/")] = handler

    def register_health(
        self, name: str, fn: Callable[[], Tuple[bool, Any]]
    ) -> None:
        self._health_sources[name] = fn

    def register_readiness(
        self, name: str, fn: Callable[[], Tuple[bool, Any]]
    ) -> None:
        """``fn() -> (ready, detail)``; /readyz is 503 until every source
        is ready. The worker registers its warm-restore + registration
        gate here (readiness split from liveness, ISSUE 10)."""
        self._ready_sources[name] = fn

    def register_metrics(self, fn: Callable[[], str]) -> None:
        """fn returns Prometheus exposition-format text."""
        self._metrics_sources.append((fn, _takes_openmetrics(fn)))

    def register_loras(self, list_fn, load_fn, unload_fn) -> None:
        self._lora_list = list_fn
        self._lora_load = load_fn
        self._lora_unload = unload_fn

    def register_drain(
        self,
        start_fn: Callable[..., Awaitable[Dict[str, Any]]],
        status_fn: Callable[[], Dict[str, Any]],
    ) -> None:
        """Wire the drain controller: ``POST /drain`` (and the preStop's
        ``GET /drain?start=1``) awaits ``start_fn(deadline_s=...)``;
        ``GET /drain`` returns ``status_fn()``."""
        self._drain_start = start_fn
        self._drain_status = status_fn

    def register_flight(
        self, name: str, fn: Callable[[], List[Any]]
    ) -> None:
        """fn returns a FlightRecorder snapshot (list of event dicts);
        /debug/flight merges every registered ring by timestamp."""
        self._flight_sources.append((name, fn))

    def register_memory(
        self, name: str, fn: Callable[[], Dict[str, int]]
    ) -> None:
        """fn returns {category: bytes}; /debug/memory groups by source.
        Sources named ``*_detail`` are informational breakdowns of bytes
        another source already accounts for — shown, but excluded from
        ``ledger_total_bytes`` (no double counting)."""
        self._memory_sources.append((name, fn))

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        # Device-plane runtime families (compile watcher + profiler) are
        # process-global like the lifecycle/tracer rings: every system
        # server exposes them. Guarded so a stop()/start() cycle doesn't
        # register the source twice.
        if not self._runtime_metrics_registered:
            from dynamo_tpu.runtime.device_observe import render_runtime_metrics
            from dynamo_tpu.runtime.kv_reuse_observe import render_kv_reuse_metrics
            from dynamo_tpu.runtime.liveness import render_fence_metrics
            from dynamo_tpu.runtime.trajectory import render_trajectory_metrics

            self.register_metrics(render_runtime_metrics)
            # Crash-plane process-global families (stale-incarnation drops
            # + restore duration/outcome): every process participates in
            # fencing, so every system server exposes them.
            self.register_metrics(render_fence_metrics)
            # SLO plane (ALL_SLO goodput/burn-rate/phase gauges): the
            # tracker is process-global like the lifecycle/tracer rings.
            self.register_metrics(render_trajectory_metrics)
            # KV-reuse plane (ALL_KVCACHE hit-rate/ROI/sketch gauges):
            # process-global, one sketch per process.
            self.register_metrics(render_kv_reuse_metrics)
            # Perf ledger (ALL_PERF attribution gauges + anomaly counter,
            # plus its "perf" flight ring): process-global, one ledger per
            # process — the engine feeds it, every server exposes it.
            from dynamo_tpu.runtime.perf_ledger import (
                global_perf_ledger,
                render_perf_metrics,
            )

            self.register_metrics(render_perf_metrics)
            self.register_flight(
                "perf", global_perf_ledger().flight.snapshot
            )
            self._runtime_metrics_registered = True
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        # Probe split (deploy/pod_connector.py renders both): /healthz =
        # liveness (the event loop turns — restarting would not help a
        # slow restore), /readyz = readiness (restore done, endpoints
        # registered — route traffic here only past this gate).
        app.router.add_get("/healthz", self._live)
        app.router.add_get("/readyz", self._ready)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/debug/requests", self._debug_requests)
        app.router.add_get("/debug/requests/{id}", self._debug_request)
        app.router.add_get("/debug/traces", self._debug_traces)
        app.router.add_get("/debug/trajectory", self._debug_trajectories)
        app.router.add_get(
            "/debug/trajectory/{trace_id}", self._debug_trajectory
        )
        app.router.add_get("/debug/kvcache", self._debug_kvcache)
        app.router.add_get(
            "/debug/kvcache/prefixes", self._debug_kvcache_prefixes
        )
        app.router.add_get("/debug/perf", self._debug_perf)
        app.router.add_get("/debug/memory", self._debug_memory)
        app.router.add_get("/debug/compiles", self._debug_compiles)
        app.router.add_get("/debug/flight", self._debug_flight)
        app.router.add_post("/debug/profile", self._debug_profile)
        app.router.add_get("/drain", self._drain_get)
        app.router.add_post("/drain", self._drain_post)
        app.router.add_route("*", "/engine/{path:.*}", self._engine)
        app.router.add_get("/v1/loras", self._loras_list)
        app.router.add_post("/v1/loras", self._loras_load)
        app.router.add_delete("/v1/loras/{name}", self._loras_unload)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # Resolve the ephemeral port for port=0.
        server = site._server  # noqa: SLF001 - aiohttp exposes no accessor
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]
        logger.info("system status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers ----------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        details: Dict[str, Any] = {}
        healthy = True
        for name, fn in self._health_sources.items():
            try:
                ok, detail = fn()
            except Exception as exc:  # a broken source is an unhealthy one
                ok, detail = False, f"health source error: {exc}"
            details[name] = detail
            healthy = healthy and ok
        status = 200 if healthy else 503
        return web.json_response(
            {"status": "healthy" if healthy else "unhealthy", "details": details},
            status=status,
        )

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _ready(self, request: web.Request) -> web.Response:
        details: Dict[str, Any] = {}
        ready = True
        for name, fn in self._ready_sources.items():
            try:
                ok, detail = fn()
            except Exception as exc:  # a broken source is a not-ready one
                ok, detail = False, f"readiness source error: {exc}"
            details[name] = detail
            ready = ready and ok
        return web.json_response(
            {"status": "ready" if ready else "not_ready", "details": details},
            status=200 if ready else 503,
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        openmetrics = "application/openmetrics-text" in request.headers.get(
            "Accept", ""
        )
        parts = []
        for fn, takes_om in self._metrics_sources:
            try:
                if openmetrics and takes_om:
                    parts.append(fn(openmetrics=True))
                else:
                    parts.append(fn())
            except Exception:
                logger.exception("metrics source failed")
        text = _merge_expositions([p for p in parts if p])
        if openmetrics:
            return web.Response(
                text=text + "\n# EOF\n",
                content_type="application/openmetrics-text",
                charset="utf-8",
            )
        return web.Response(
            text=text + "\n",
            content_type="text/plain",
            charset="utf-8",
        )

    # -- debug surface (lifecycle timelines + trace ring) ------------------

    def _lifecycle_obj(self):
        if self._lifecycle is None:
            from dynamo_tpu.runtime.lifecycle import global_lifecycle

            self._lifecycle = global_lifecycle()
        return self._lifecycle

    def _tracer_obj(self):
        if self._tracer is None:
            from dynamo_tpu.utils.tracing import global_tracer

            self._tracer = global_tracer()
        return self._tracer

    async def _debug_requests(self, request: web.Request) -> web.Response:
        lc = self._lifecycle_obj()
        return web.json_response(
            {
                "slow_threshold_s": lc.slow_threshold_s,
                "requests": [tl.summary() for tl in lc.timelines()],
                "slow": [tl.request_id for tl in lc.slow_timelines()],
            }
        )

    async def _debug_request(self, request: web.Request) -> web.Response:
        rid = request.match_info["id"]
        tl = self._lifecycle_obj().get(rid)
        if tl is None:
            return web.json_response(
                {"error": f"no timeline for request {rid!r}"}, status=404
            )
        return web.json_response(tl.to_dict())

    async def _debug_traces(self, request: web.Request) -> web.Response:
        """Dump the span ring, optionally filtered: /debug/traces?trace_id=…
        returns only that trace (the exemplar-chasing path)."""
        want = request.query.get("trace_id")
        spans = self._tracer_obj().finished_spans()
        if want:
            spans = [s for s in spans if s.trace_id == want]
        return web.json_response({"spans": [s.to_dict() for s in spans]})

    # -- trajectory plane (runtime/trajectory.py) --------------------------

    def _trajectory_obj(self):
        if self._trajectory is None:
            from dynamo_tpu.runtime.trajectory import global_store

            self._trajectory = global_store()
        return self._trajectory

    async def _debug_trajectories(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.trajectory import trajectory_index

        return web.json_response(trajectory_index(self._trajectory_obj()))

    async def _debug_trajectory(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.trajectory import trajectory_view

        tid = request.match_info["trace_id"]
        stitched = trajectory_view(tid, self._trajectory_obj())
        if stitched is None:
            return web.json_response(
                {"error": f"no trajectory for trace {tid!r}"}, status=404
            )
        return web.json_response(stitched)

    # -- KV-reuse plane (runtime/kv_reuse_observe.py) ----------------------

    async def _debug_kvcache(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.kv_reuse_observe import kvcache_index

        try:
            top_k = int(request.query.get("top_k", "10"))
        except ValueError:
            top_k = 10
        return web.json_response(kvcache_index(top_k=top_k))

    async def _debug_kvcache_prefixes(
        self, request: web.Request
    ) -> web.Response:
        from dynamo_tpu.runtime.kv_reuse_observe import kvcache_prefixes

        try:
            k = int(request.query.get("k", "50"))
        except ValueError:
            k = 50
        return web.json_response(kvcache_prefixes(k=k))

    # -- perf ledger (runtime/perf_ledger.py) ------------------------------

    async def _debug_perf(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.perf_ledger import perf_index

        return web.json_response(perf_index())

    # -- device-plane debug surface (runtime/device_observe.py) ------------

    async def _debug_memory(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.device_observe import device_memory_stats

        sources: Dict[str, Dict[str, int]] = {}
        total = 0
        for name, fn in self._memory_sources:
            try:
                snap = fn()
            except Exception as exc:
                snap = {"error": f"{type(exc).__name__}: {exc}"}  # type: ignore[dict-item]
            sources[name] = snap
            if not name.endswith("_detail"):
                total += sum(
                    v for v in snap.values() if isinstance(v, int) and v > 0
                )
        body: Dict[str, Any] = {
            "sources": sources,
            "ledger_total_bytes": total,
            "devices": device_memory_stats(),
        }
        try:
            from dynamo_tpu.models.weight_cache import cache_usage

            # os.walk over the disk cache tiers off the event loop — this
            # loop also runs the engine tick; a cold/NFS cache walk here
            # would stall token streaming for the duration of the scrape.
            body["host_weight_cache"] = await asyncio.get_running_loop(
            ).run_in_executor(None, cache_usage)
        except Exception:  # keep the route alive without the models stack
            body["host_weight_cache"] = None
        # Cross-check where the backend reports real allocator numbers
        # (TPU does; CPU memory_stats is None): unaccounted = allocator
        # in-use minus everything the structural ledger can name. Only
        # computed for a SINGLE reporting device: the ledger counts each
        # logical array once, while N devices hold N physical copies of
        # replicated state — the naive multi-device difference would
        # report that replication as a phantom leak.
        reporting = [
            d for d in body["devices"]
            if isinstance(d, dict) and d.get("memory_stats")
        ]
        in_use = sum(
            d["memory_stats"].get("bytes_in_use", 0) for d in reporting
        )
        if in_use:
            body["device_bytes_in_use"] = in_use
            if len(reporting) == 1:
                body["unaccounted_bytes"] = in_use - total
            else:
                body["unaccounted_note"] = (
                    "multi-device: ledger bytes are logical (counted "
                    "once) while allocator bytes include per-device "
                    "replicas; no drift number computed"
                )
        return web.json_response(body)

    async def _debug_compiles(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.device_observe import global_compile_watcher

        return web.json_response(global_compile_watcher().snapshot())

    async def _debug_flight(self, request: web.Request) -> web.Response:
        """Merged flight-recorder rings, timestamp-ordered. Query params:
        ?limit=N (newest N after the merge), ?kind=dispatch (filter)."""
        events: List[Any] = []
        rings = []
        for name, fn in self._flight_sources:
            rings.append(name)
            try:
                events.extend(fn())
            except Exception:
                logger.exception("flight source %s failed", name)
        want_kind = request.query.get("kind")
        if want_kind:
            events = [e for e in events if e.get("kind") == want_kind]
        events.sort(key=lambda e: e.get("t_mono", 0.0))
        try:
            limit = int(request.query.get("limit", "0"))
        except ValueError:
            limit = 0
        if limit > 0:
            events = events[-limit:]
        return web.json_response({"rings": rings, "events": events})

    async def _debug_profile(self, request: web.Request) -> web.Response:
        from dynamo_tpu.runtime.device_observe import global_profiler

        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        if not isinstance(body, dict):
            body = {}
        action = str(body.get("action", "status"))
        profiler = global_profiler()
        if action == "start":
            # Validate BEFORE starting the trace: a bad 'seconds' after
            # start_trace would 500 while leaving an orphaned capture
            # active (and nothing to ever stop it).
            seconds: Optional[float] = None
            if body.get("seconds") is not None:
                try:
                    seconds = float(body["seconds"])
                except (TypeError, ValueError):
                    seconds = float("nan")
                # NaN fails the 0 < s check; inf would never fire.
                if not 0 < seconds < float("inf"):
                    return web.json_response(
                        {"error": f"bad seconds {body['seconds']!r} "
                                  "(need a positive finite number)"},
                        status=400,
                    )
            result = profiler.start(body.get("dir"))
            if result.get("ok") and seconds:
                # Bounded capture: auto-stop keeps an operator's one-shot
                # POST from tracing forever when the stop call never comes.
                capture_gen = result.get("generation")

                async def _auto_stop() -> None:
                    await asyncio.sleep(seconds)
                    # Only stop OUR capture generation: a manual stop +
                    # fresh start during the sleep (even into the same
                    # dir) must not have ITS capture killed by this stale
                    # timer.
                    status = profiler.status()
                    if (
                        not status.get("active")
                        or status.get("generation") != capture_gen
                    ):
                        return
                    logger.info(
                        "auto-stopped profiler capture: %s", profiler.stop()
                    )

                # Hold a strong reference: the loop keeps only weak task
                # refs, and a GC'd timer would leave the capture unbounded.
                task = asyncio.get_running_loop().create_task(_auto_stop())
                self._profile_timers.add(task)
                task.add_done_callback(self._profile_timers.discard)
                result["auto_stop_s"] = seconds
            # A degraded (profiler-unavailable) start is the documented
            # graceful no-op — 200 with degraded:true, not an error; 409
            # is reserved for "a capture is already active".
            status = 200 if result.get("ok") or result.get("degraded") else 409
            return web.json_response(result, status=status)
        if action == "stop":
            result = profiler.stop()
            status = 200 if result.get("ok") or result.get("degraded") else 409
            return web.json_response(result, status=status)
        if action == "status":
            return web.json_response(profiler.status())
        return web.json_response(
            {"error": f"unknown action {action!r} (start|stop|status)"},
            status=400,
        )

    # -- drain plane (runtime/drain.py) ------------------------------------

    async def _drain_get(self, request: web.Request) -> web.Response:
        """Drain status — or, with ``?start=1``, trigger-and-wait. The
        mutating GET exists for the k8s preStop hook, whose httpGet action
        only issues GETs; kubelet blocks on the response, which is exactly
        the preStop contract (pod deletion proceeds once drained)."""
        if self._drain_status is None:
            return web.json_response(
                {"error": "no drain controller registered"}, status=404
            )
        if request.query.get("start") in ("1", "true", "yes"):
            return await self._start_drain({})
        return web.json_response(self._drain_status())

    async def _drain_post(self, request: web.Request) -> web.Response:
        if self._drain_start is None:
            return web.json_response(
                {"error": "no drain controller registered"}, status=404
            )
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        return await self._start_drain(body if isinstance(body, dict) else {})

    async def _start_drain(self, body: Dict[str, Any]) -> web.Response:
        deadline_s: Optional[float] = None
        if body.get("deadline_s") is not None:
            try:
                deadline_s = float(body["deadline_s"])
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": f"bad deadline_s {body['deadline_s']!r}"},
                    status=400,
                )
        try:
            status = await self._drain_start(deadline_s=deadline_s)
        except Exception as exc:
            logger.exception("drain failed")
            return web.json_response({"error": repr(exc)}, status=500)
        return web.json_response(status)

    async def _engine(self, request: web.Request) -> web.Response:
        path = request.match_info["path"].strip("/")
        handler = self._engine_routes.get(path)
        if handler is None:
            return web.json_response(
                {"error": f"no engine route {path!r}",
                 "routes": sorted(self._engine_routes)},
                status=404,
            )
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        try:
            status, payload = await handler(body if isinstance(body, dict) else {})
        except Exception as exc:
            logger.exception("engine route %s failed", path)
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response(payload, status=status)

    async def _loras_list(self, request: web.Request) -> web.Response:
        if self._lora_list is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        return web.json_response({"loras": self._lora_list()})

    async def _loras_load(self, request: web.Request) -> web.Response:
        if self._lora_load is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        try:
            body = await request.json()
            name, path = body["name"], body["path"]
        except Exception:
            return web.json_response(
                {"error": "body must be {'name': ..., 'path': ...}"}, status=400
            )
        try:
            await self._lora_load(name, path)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:
            logger.exception("LoRA load failed")
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response({"loaded": name}, status=201)

    async def _loras_unload(self, request: web.Request) -> web.Response:
        if self._lora_unload is None:
            return web.json_response({"error": "LoRA not enabled"}, status=404)
        name = request.match_info["name"]
        try:
            await self._lora_unload(name)
        except KeyError as exc:
            return web.json_response({"error": str(exc)}, status=404)
        return web.json_response({"unloaded": name})


def engine_stats_prometheus(stats: Dict[str, Any]) -> str:
    """Engine stats dict → Prometheus gauges with canonical names
    (ref: metrics/prometheus_names.rs — runtime/metric_names.py is the
    single place that defines them). Nested dict stats (the ``kvbm``
    sub-dict) flatten into ``<prefix>_<key>_<subkey>`` gauges instead of
    silently disappearing from the scrape."""
    from dynamo_tpu.runtime.metric_names import engine_gauge

    lines: List[str] = []

    def emit(name: str, value: float, source: str) -> None:
        lines.append(f"# HELP {name} Engine stat {source!r} (engine.stats())")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {float(value)}")

    def numeric(value: Any) -> bool:
        return not isinstance(value, bool) and isinstance(value, (int, float))

    for key, value in stats.items():
        if isinstance(value, dict):
            for sub, sv in value.items():
                if numeric(sv):
                    emit(engine_gauge(f"{key}_{sub}"), sv, f"{key}.{sub}")
            continue
        if numeric(value):
            emit(engine_gauge(key), value, key)
    return "\n".join(lines)


def attach_engine(server: SystemStatusServer, engine: Any) -> None:
    """Register the native engine's admin surface on the system server
    (ref: the engine-routes registry in system_status_server.rs plus vllm
    handlers sleep/wake and LoRA load/unload). Tolerant of partial engines
    (the mocker, stubs): each route/metric source registers only when the
    engine exposes the matching surface, so a plain mock worker still gets
    /health, the /debug/* plane, and whatever stats it can report."""

    def has(name: str) -> bool:
        return callable(getattr(engine, name, None))

    async def _stats(body: Dict[str, Any]):
        return 200, engine.stats()

    async def _sleep(body: Dict[str, Any]):
        await engine.sleep(int(body.get("level", 1)))
        return 200, {"sleeping": True, "level": engine.sleep_level}

    async def _wake(body: Dict[str, Any]):
        await engine.wake()
        return 200, {"sleeping": False}

    async def _clear(body: Dict[str, Any]):
        return 200, {"cleared_blocks": engine.clear_kv_blocks()}

    async def _checkpoint(body: Dict[str, Any]):
        path = body.get("path")
        if not path:
            return 400, {"error": "body must include 'path'"}
        return 200, await engine.save_checkpoint(path)

    async def _restore(body: Dict[str, Any]):
        path = body.get("path")
        if not path:
            return 400, {"error": "body must include 'path'"}
        try:
            n = await engine.load_checkpoint(path)
        except (OSError, ValueError, KeyError, IndexError) as exc:
            # Malformed manifests surface as any of these (JSONDecodeError
            # is a ValueError; missing fields KeyError; short data arrays
            # IndexError) — all are bad-input 400s, not server faults.
            return 400, {"error": repr(exc)}
        return 200, {"restored_blocks": n}

    if has("stats"):
        server.register_engine_route("stats", _stats)
    if has("sleep"):
        server.register_engine_route("sleep", _sleep)
    if has("wake"):
        server.register_engine_route("wake", _wake)
    if has("clear_kv_blocks"):
        server.register_engine_route("clear_kv_blocks", _clear)
    if has("save_checkpoint"):
        server.register_engine_route("checkpoint", _checkpoint)
    if has("load_checkpoint"):
        server.register_engine_route("restore", _restore)

    def _engine_health():
        failure = getattr(engine, "_failure", None)
        if failure is not None:
            return False, f"engine failed: {failure}"
        level = getattr(engine, "sleep_level", 0)
        if level > 0:
            return True, f"asleep (level {level})"
        return True, "serving"

    server.register_health("engine", _engine_health)
    if has("stats"):
        server.register_metrics(
            lambda: engine_stats_prometheus(engine.stats())
        )
    if has("save_checkpoint") or has("load_checkpoint"):
        # Persisted-KV integrity counter (kvbm/integrity.py): process-
        # global, one registration per server — checkpoint CRC failures
        # and disk-tier spill failures land in the same family under
        # distinct source labels.
        from dynamo_tpu.kvbm.integrity import render_integrity_metrics

        server.register_metrics(render_integrity_metrics)
    step_metrics = getattr(engine, "step_metrics", None)
    if step_metrics is not None:
        step_metrics.register_metrics(server)

    # Device-plane sources (JaxEngine; mocks without them still attach):
    # flight rings → /debug/flight (+ per-kind event counters on /metrics),
    # HBM ledger → /debug/memory (+ per-category byte gauges).
    flight = getattr(engine, "flight", None)
    if flight is not None:
        server.register_flight(flight.name, flight.snapshot)
        server.register_metrics(flight.registry.render)
    runner_flight = getattr(getattr(engine, "runner", None), "flight", None)
    if runner_flight is not None:
        server.register_flight(runner_flight.name, runner_flight.snapshot)
        server.register_metrics(runner_flight.registry.render)
    hbm = getattr(engine, "hbm", None)
    if hbm is not None:
        server.register_memory("engine", hbm.snapshot)
        server.register_metrics(hbm.registry.render)
    pool_breakdown = getattr(engine, "kv_pool_bytes_breakdown", None)
    if pool_breakdown is not None:
        # Informational split of the ledger's kv_cache bytes (active vs
        # reusable-cached vs free) — "_detail" keeps it out of the total.
        server.register_memory("kv_pool_detail", pool_breakdown)

    async def _load(name: str, path: str) -> None:
        # Disk I/O + stacking + host→device transfer off the event loop —
        # a multi-second inline load would stall token streaming and the
        # discovery lease keep-alive.
        device = getattr(engine, "_device", None)
        if device is not None:
            await device(engine.load_lora, name, path)
        else:
            await asyncio.get_running_loop().run_in_executor(
                None, engine.load_lora, name, path
            )

    async def _unload(name: str) -> None:
        # Same device-thread routing as _load: under multihost the restack
        # op must serialize with in-flight decode mirroring.
        device = getattr(engine, "_device", None)
        if device is not None:
            await device(engine.unload_lora, name)
        else:
            engine.unload_lora(name)

    if has("lora_names") and has("load_lora") and has("unload_lora"):
        server.register_loras(engine.lora_names, _load, _unload)
