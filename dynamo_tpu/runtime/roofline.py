"""Pure-arithmetic decode roofline model (shared by bench.py and the
perf ledger).

One statement of the bandwidth math bench's ``run_70b_projection_leg``
and anchor derivation have always used: a fused decode step must stream
the full (active) weight set plus every sequence's KV history from HBM,
so the step-time floor is ``bytes_moved / HBM_BW`` and the throughput
roofline is ``batch / step_time``. Factored out of bench.py so the
always-on perf ledger (runtime/perf_ledger.py) can report a live
achieved-fraction-of-roofline gauge against the SAME model bench grades
rounds with — two surfaces, one formula.

Dependency-free by design (no jax import): ``cfg`` is duck-typed on the
plain-int attributes ModelConfig carries (d_model, n_layers, head_dim_,
n_heads, n_kv_heads, d_ff, vocab_size, tie_word_embeddings, is_moe,
moe_d_ff_, n_experts, n_experts_per_tok), so the module loads on boxes
where the serving deps don't.
"""

from __future__ import annotations

from typing import Callable, Optional

# Public hardware specs the roofline derives from (v5e chip class).
V5E_BW = 819e9  # B/s HBM
V5E_PEAK_BF16 = 197e12  # FLOP/s


def param_count(cfg) -> int:
    """Matmul-weight parameter count from the config (analytic)."""
    d, L, hd = cfg.d_model, cfg.n_layers, cfg.head_dim_
    H, KH, ff = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff
    per_layer = d * H * hd + 2 * d * KH * hd + H * hd * d  # wq wk wv wo
    if cfg.is_moe:
        eff = cfg.moe_d_ff_
        per_layer += cfg.n_experts * 3 * d * eff + d * cfg.n_experts
    else:
        per_layer += 3 * d * ff
    total = L * per_layer + cfg.vocab_size * d
    if not cfg.tie_word_embeddings:
        total += d * cfg.vocab_size
    return total


def active_param_count(cfg) -> int:
    """Params touched per token (MoE reads only top-k experts)."""
    if not cfg.is_moe:
        return param_count(cfg)
    d, L, hd = cfg.d_model, cfg.n_layers, cfg.head_dim_
    H, KH, eff = cfg.n_heads, cfg.n_kv_heads, cfg.moe_d_ff_
    per_layer = (
        d * H * hd + 2 * d * KH * hd + H * hd * d
        + cfg.n_experts_per_tok * 3 * d * eff + d * cfg.n_experts
    )
    total = L * per_layer + cfg.vocab_size * d
    if not cfg.tie_word_embeddings:
        total += d * cfg.vocab_size
    return total


def decode_step_bytes(
    cfg, batch: int, avg_ctx: float, quant: Optional[str]
) -> float:
    """HBM bytes one fused decode step must move: the full (active)
    weight stream plus every sequence's KV history."""
    wbytes = active_param_count(cfg) * (1 if quant == "int8" else 2)
    kv_per_tok = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim_ * 2
    return wbytes + batch * avg_ctx * kv_per_tok


def decode_roofline_toks_per_sec(
    cfg,
    batch: int,
    avg_ctx: float,
    quant: Optional[str],
    hbm_bw: float = V5E_BW,
) -> float:
    """Bandwidth-roofline decode throughput (tokens/s, whole chip) for
    this model/batch/context: ``batch / (step_bytes / hbm_bw)``."""
    step_bytes = decode_step_bytes(cfg, batch, avg_ctx, quant)
    if step_bytes <= 0:
        return 0.0
    return batch * hbm_bw / step_bytes


def make_roofline_fn(
    cfg, quant: Optional[str], hbm_bw: float = V5E_BW
) -> Callable[[int, float], float]:
    """Close over a config: ``(batch, avg_ctx) -> roofline tok/s``. The
    shape the perf ledger stores at configure time — the ledger itself
    stays model-agnostic."""
    def fn(batch: int, avg_ctx: float) -> float:
        return decode_roofline_toks_per_sec(
            cfg, batch, avg_ctx, quant, hbm_bw=hbm_bw
        )
    return fn
