"""Fleet-wide request trajectory plane: cross-worker span stitching,
per-request phase attribution, and SLO goodput/burn-rate gauges.

Every per-process diagnostic surface (``/debug/traces``, ``/debug/flight``,
``/debug/requests``) shows one worker's slice of a request. This module is
the fleet-level joint view: workers ship their finished spans (plus
trace-tagged flight events) over the event plane to a bounded
frontend-side :class:`TrajectoryStore`, and ``GET
/debug/trajectory/{trace_id}`` answers "why was THIS request slow" with one
stitched, phase-attributed timeline covering frontend → router → prefill
worker → decode worker → handoff peer.

Three parts:

  * **Shipping** (:class:`TrajectoryShipper` worker-side,
    :class:`TrajectoryCollector` frontend-side): a tracer listener batches
    finished spans onto the ``<namespace>.trajectory`` topic from a pump
    task — span-producing paths never block, a full queue drops-and-counts,
    and the ``trajectory.ship`` fault seam (runtime/fault_names.py) proves
    a dying telemetry path never touches serving.
  * **Stitching** (:func:`stitch`): each process's spans carry its
    ``proc`` label (utils/tracing.py ``service_label``), a local-monotonic
    start anchor, and a monotonic-derived duration. Within one proc,
    offsets come from the monotonic deltas (exact). Across procs, remote
    wall clocks are NEVER compared directly (the liveness.py rule):
    a child is positioned by the wall delta to its remote parent, then
    RE-ANCHORED — clamped inside the parent span's bounds — and any
    residual is reported as ``skew_ms`` + ``skew_flagged`` instead of
    being silently believed. Durations always come from each proc's own
    clock, so phase sums stay honest under arbitrary wall skew.
  * **Attribution + SLO** (:func:`attribute_phases`, :class:`SloTracker`):
    the span catalog maps onto six phases (queue / prefill / kv_transfer
    incl. retries / decode / handoff_stall / overhead = root − attributed);
    every completed trajectory feeds per-phase p99-contribution gauges, and
    the frontend's stream verdicts (TTFT+ITL vs SLA) feed goodput and
    multi-window error-budget burn rate — the lint-pinned ``ALL_SLO``
    family (runtime/metric_names.py).
"""

from __future__ import annotations

import asyncio
import collections
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from dynamo_tpu import config
from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.metrics_core import MetricsRegistry
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# -- phase catalog ------------------------------------------------------------

PHASE_QUEUE = "queue"
PHASE_PREFILL = "prefill"
PHASE_KV_TRANSFER = "kv_transfer"
PHASE_DECODE = "decode"
PHASE_HANDOFF_STALL = "handoff_stall"
PHASE_OVERHEAD = "overhead"

PHASES = (
    PHASE_QUEUE,
    PHASE_PREFILL,
    PHASE_KV_TRANSFER,
    PHASE_DECODE,
    PHASE_HANDOFF_STALL,
    PHASE_OVERHEAD,
)

# Span name → phase. Spans not in the catalog (transport envelopes like
# endpoint.serve, the http root, router decisions) are structure, not
# phases — their time lands in whichever catalog span they contain, or in
# overhead. The catalog spans are non-overlapping by construction: queue
# ends at prefill start, the disagg pull completes before admission, a
# handoff stall is exactly the token gap between the source's decode end
# and the peer's decode start.
SPAN_PHASES = {
    "overload.queue": PHASE_QUEUE,
    "engine.queue": PHASE_QUEUE,
    "engine.prefill": PHASE_PREFILL,
    "disagg.pull": PHASE_KV_TRANSFER,
    "engine.decode": PHASE_DECODE,
    "drain.handoff": PHASE_HANDOFF_STALL,
    "migration.redispatch": PHASE_HANDOFF_STALL,
}

# Residual cross-proc skew below this is noise, not a flag.
SKEW_FLAG_MS = 0.001

# Service-entry span names: these are trajectory ROOTS even when they
# carry a parent_span_id — a traced CLIENT's traceparent makes the
# frontend span a child of a span that lives outside this fleet and will
# never ship here. Without this, any externally-traced request would
# read as a forever-incomplete orphan.
ROOT_SPAN_PREFIXES = ("http.", "grpc.")


def is_root_span(rec: Dict[str, Any]) -> bool:
    return not rec.get("parent_span_id") or str(
        rec.get("name", "")
    ).startswith(ROOT_SPAN_PREFIXES)


def trajectory_topic(namespace: str) -> str:
    return f"{namespace}.trajectory"


def span_record(span: Any) -> Dict[str, Any]:
    """Span → the wire/store record (Span.to_dict is already that shape)."""
    return span.to_dict()


def _proc_of(rec: Dict[str, Any]) -> str:
    attrs = rec.get("attributes") or {}
    return str(attrs.get("proc") or rec.get("proc") or "?")


# -- stitching ----------------------------------------------------------------


def stitch(
    spans: List[Dict[str, Any]],
    events: Optional[List[Dict[str, Any]]] = None,
    *,
    trace_id: Optional[str] = None,
    complete: bool = False,
) -> Dict[str, Any]:
    """Join one trace's span records into a single placed timeline.

    Offsets are milliseconds from the trajectory start. Same-proc children
    use monotonic deltas against their parent (exact); cross-proc children
    use the wall delta but are clamped inside the parent span's bounds
    (local durations are trusted, remote wall clocks are not) with the
    residual reported per span as ``skew_ms``/``skew_flagged``."""
    recs = [dict(s) for s in spans]
    by_id: Dict[str, Dict[str, Any]] = {}
    for s in recs:
        sid = s.get("span_id")
        if sid:
            by_id[sid] = s
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    orphans: List[Dict[str, Any]] = []
    for s in by_id.values():
        pid = s.get("parent_span_id")
        if pid and pid in by_id:
            children.setdefault(pid, []).append(s)
        elif pid and not is_root_span(s):
            orphans.append(s)
        else:
            # True roots plus service-entry spans whose parent lives in
            # the CLIENT's tracing system (never shipped here).
            roots.append(s)
    heads = roots + orphans
    if not heads:
        return {
            "trace_id": trace_id,
            "spans": [],
            "events": list(events or ()),
            "processes": [],
            "total_ms": 0.0,
            "phases": {p: 0.0 for p in PHASES},
            "dominant_phase": PHASE_OVERHEAD,
            "kv_reuse": _kv_reuse_rollup(events or ()),
            "skew_flagged": False,
            "complete": complete,
        }
    # Primary anchor: the earliest true root (the frontend's http span),
    # falling back to the earliest orphan when the root never arrived.
    primary = min(
        roots or orphans, key=lambda s: s.get("start_unix_s", 0.0)
    )
    anchor_wall = primary.get("start_unix_s", 0.0)
    any_skew = False

    def place(s: Dict[str, Any], offset: float, skew: float) -> None:
        nonlocal any_skew
        s["offset_ms"] = round(max(offset, 0.0), 3)
        if abs(skew) > SKEW_FLAG_MS:
            s["skew_ms"] = round(skew, 3)
            s["skew_flagged"] = True
            any_skew = True

    for head in heads:
        base = (head.get("start_unix_s", anchor_wall) - anchor_wall) * 1000.0
        if head in orphans:
            # Parent span missing (not yet shipped / ring-evicted): place
            # by wall against the primary anchor and say so.
            head["orphan"] = True
        place(head, base, 0.0)
        stack = [head]
        while stack:
            parent = stack.pop()
            p_off = parent["offset_ms"]
            p_dur = float(parent.get("duration_ms") or 0.0)
            for child in children.get(parent.get("span_id"), ()):  # type: ignore[arg-type]
                same_proc = _proc_of(child) == _proc_of(parent)
                c_mono = child.get("start_mono_s")
                p_mono = parent.get("start_mono_s")
                if same_proc and c_mono is not None and p_mono is not None:
                    d_ms = (c_mono - p_mono) * 1000.0
                else:
                    d_ms = (
                        child.get("start_unix_s", 0.0)
                        - parent.get("start_unix_s", 0.0)
                    ) * 1000.0
                raw = p_off + d_ms
                if same_proc:
                    place(child, raw, 0.0)
                else:
                    # Re-anchor inside the parent's bounds: the child's
                    # LOCAL duration is trusted, its remote wall position
                    # is not. Residual skew is surfaced, never applied.
                    c_dur = float(child.get("duration_ms") or 0.0)
                    lo = p_off
                    hi = max(lo, p_off + p_dur - c_dur)
                    clamped = min(max(raw, lo), hi)
                    place(child, clamped, raw - clamped)
                stack.append(child)
    placed = sorted(by_id.values(), key=lambda s: s.get("offset_ms", 0.0))
    total_ms = max(
        (s["offset_ms"] + float(s.get("duration_ms") or 0.0) for s in placed),
        default=0.0,
    )
    root_ms = (
        float(primary.get("duration_ms") or 0.0)
        if primary in roots else total_ms
    )
    phases, dominant = attribute_phases(placed, root_ms)
    procs: List[str] = []
    for s in placed:
        p = _proc_of(s)
        if p not in procs:
            procs.append(p)
    out_events: List[Dict[str, Any]] = []
    for ev in events or ():
        ev = dict(ev)
        t_wall = ev.get("t_wall")
        if t_wall is not None:
            off = (float(t_wall) - anchor_wall) * 1000.0
            ev["offset_ms"] = round(min(max(off, 0.0), total_ms), 3)
        out_events.append(ev)
    out_events.sort(key=lambda e: e.get("offset_ms", 0.0))
    return {
        "trace_id": trace_id or primary.get("trace_id"),
        "spans": placed,
        "events": out_events,
        "processes": procs,
        "total_ms": round(total_ms, 3),
        "root_ms": round(root_ms, 3),
        "phases": phases,
        "dominant_phase": dominant,
        "kv_reuse": _kv_reuse_rollup(out_events),
        "skew_flagged": any_skew,
        "complete": complete,
    }


def _kv_reuse_rollup(
    events: Iterable[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Aggregate the KV-reuse plane's per-request ROI events (ring
    ``kvcache``, kind ``roi``) into one cache-ROI line for the trajectory:
    how much prefill this request skipped, and from which tiers. None when
    the request carried no ROI event (engine predates the plane, or the
    event ring evicted it) — consumers must treat absent and None alike."""
    total: Optional[Dict[str, Any]] = None
    for ev in events:
        if ev.get("ring") != "kvcache" or ev.get("kind") != "roi":
            continue
        if total is None:
            total = {
                "cached_tokens": 0,
                "recomputed_tokens": 0,
                "seconds_saved": 0.0,
                "tiers": [],
            }
        total["cached_tokens"] += int(ev.get("cached_tokens") or 0)
        total["recomputed_tokens"] += int(ev.get("recomputed_tokens") or 0)
        total["seconds_saved"] += float(ev.get("seconds_saved") or 0.0)
        tier = ev.get("tier")
        if tier and tier not in total["tiers"]:
            total["tiers"].append(tier)
    if total is not None:
        total["seconds_saved"] = round(total["seconds_saved"], 6)
    return total


def attribute_phases(
    spans: List[Dict[str, Any]], total_ms: float
) -> Tuple[Dict[str, float], str]:
    """Per-phase milliseconds from the span catalog + the overhead rest.

    ``total_ms`` is the root span's duration (the client-observed wall);
    overhead = total − attributed, floored at 0 (phase spans from
    processes whose request work outlived the root — relays cut at a
    deadline — must not produce negative overhead)."""
    phases = {p: 0.0 for p in PHASES}
    for s in spans:
        phase = SPAN_PHASES.get(s.get("name"))  # type: ignore[arg-type]
        if phase is not None:
            phases[phase] += float(s.get("duration_ms") or 0.0)
    attributed = sum(phases.values())
    phases = {p: round(v, 3) for p, v in phases.items()}
    phases[PHASE_OVERHEAD] = round(max(total_ms - attributed, 0.0), 3)
    if total_ms <= 0:
        return phases, PHASE_OVERHEAD
    dominant = max(PHASES, key=lambda p: phases[p])
    return phases, dominant


# -- SLO tracker --------------------------------------------------------------


def _window_label(seconds: float) -> str:
    return f"{int(round(seconds / 60.0))}m"


class SloTracker:
    """Goodput / burn-rate / phase-p99 gauges (lint-pinned ``ALL_SLO``).

    Fed from two sides: the frontend's RequestTimer verdicts (one per
    finished stream — did TTFT and mean ITL meet the SLA) and the
    trajectory store's phase attributions (one per completed trajectory,
    REPLACED when late worker spans refine it). Disabled (no SLA
    configured) it is a no-op whose families still exist, so the metric
    closure holds on every deployment."""

    def __init__(
        self,
        *,
        ttft_sla_s: Optional[float] = None,
        itl_sla_s: Optional[float] = None,
        target: Optional[float] = None,
        windows: Tuple[float, ...] = (300.0, 3600.0),
        max_phase_traces: int = 1024,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if ttft_sla_s is None:
            ms = config.SLO_TTFT_MS.get()
            ttft_sla_s = ms / 1000.0 if ms > 0 else None
        if itl_sla_s is None:
            ms = config.SLO_ITL_MS.get()
            itl_sla_s = ms / 1000.0 if ms > 0 else None
        self.ttft_sla_s = ttft_sla_s
        self.itl_sla_s = itl_sla_s
        self.target = target if target is not None else config.SLO_TARGET.get()
        self.windows = tuple(windows)
        self.max_phase_traces = max_phase_traces
        self._clock = clock
        self._lock = threading.Lock()
        # (verdict time, good) pairs; pruned to the longest window.
        self._verdicts: "collections.deque" = collections.deque()
        # trace_id → (t, phases) — keyed so a late-arriving worker batch
        # REPLACES the trace's attribution instead of double-counting it.
        self._phases: "OrderedDict[str, Tuple[float, Dict[str, float]]]" = (
            OrderedDict()
        )
        self.good_streams = 0
        self.breached_streams = 0
        self.registry = MetricsRegistry()
        self.goodput = self.registry.gauge(
            mn.SLO_GOODPUT,
            "Fraction of finished streams meeting BOTH the TTFT and mean-"
            "ITL SLAs, per rolling window (1.0 with no traffic)",
            ["window"],
        )
        self.streams = self.registry.counter(
            mn.SLO_STREAMS_TOTAL,
            "Finished streams by SLO verdict (good | breach)",
            ["verdict"],
        )
        self.burn_rate = self.registry.gauge(
            mn.SLO_BURN_RATE,
            "Error-budget burn rate per window: breach fraction / "
            "(1 - slo_target); 1.0 = burning exactly the budget",
            ["window"],
        )
        self.phase_p99 = self.registry.gauge(
            mn.SLO_PHASE_P99_MS,
            "p99 of each request phase's duration over the trajectory "
            "window — the phase that dominates the latency tail",
            ["phase"],
        )
        self.registry.on_render(self._refresh)

    @property
    def enabled(self) -> bool:
        return self.ttft_sla_s is not None or self.itl_sla_s is not None

    def note_stream(
        self,
        trace_id: Optional[str],
        *,
        ttft_s: Optional[float],
        mean_itl_s: Optional[float],
        status: int = 200,
    ) -> None:
        """One finished stream's latency verdict (RequestTimer.done).
        Typed refusals (429/503/504) and server errors are breaches by
        definition — a refused stream did not meet the SLA."""
        if not self.enabled:
            return
        good = status < 429
        if ttft_s is None and mean_itl_s is None:
            # Token-less stream: only failures are fed here (the timer
            # skips token-less 2xx), and a failure met no SLA.
            good = False
        if self.ttft_sla_s is not None and (
            ttft_s is None or ttft_s > self.ttft_sla_s
        ):
            good = False
        if (
            self.itl_sla_s is not None
            and mean_itl_s is not None
            and mean_itl_s > self.itl_sla_s
        ):
            good = False
        now = self._clock()
        with self._lock:
            self._verdicts.append((now, good))
            horizon = now - max(self.windows)
            while self._verdicts and self._verdicts[0][0] < horizon:
                self._verdicts.popleft()
        if good:
            self.good_streams += 1
        else:
            self.breached_streams += 1
        self.streams.inc(verdict="good" if good else "breach")

    def note_phases(self, trace_id: str, phases: Dict[str, float]) -> None:
        """One trajectory's phase attribution; re-noting the same trace id
        (late worker spans refined the stitch) replaces the entry."""
        if not trace_id:
            return
        now = self._clock()
        with self._lock:
            self._phases[trace_id] = (now, dict(phases))
            self._phases.move_to_end(trace_id)
            while len(self._phases) > self.max_phase_traces:
                self._phases.popitem(last=False)

    def _refresh(self) -> None:
        now = self._clock()
        with self._lock:
            verdicts = list(self._verdicts)
            phase_rows = [
                ph for t, ph in self._phases.values()
                if now - t <= max(self.windows)
            ]
        budget = max(1.0 - self.target, 1e-9)
        for w in self.windows:
            in_window = [g for t, g in verdicts if now - t <= w]
            label = _window_label(w)
            if not in_window:
                self.goodput.set(1.0, window=label)
                self.burn_rate.set(0.0, window=label)
                continue
            frac_good = sum(1 for g in in_window if g) / len(in_window)
            self.goodput.set(round(frac_good, 6), window=label)
            self.burn_rate.set(
                round((1.0 - frac_good) / budget, 4), window=label
            )
        for phase in PHASES:
            vals = sorted(float(ph.get(phase, 0.0)) for ph in phase_rows)
            # Nearest-rank p99 (ceil(0.99 n) - 1); few samples → the max.
            p99 = vals[(99 * len(vals) + 99) // 100 - 1] if vals else 0.0
            self.phase_p99.set(round(p99, 3), phase=phase)

    def snapshot(self) -> Dict[str, Any]:
        """SLO state for bench legs / debug surfaces."""
        self._refresh()
        labels = [_window_label(w) for w in self.windows]
        return {
            "enabled": self.enabled,
            "ttft_sla_ms": (
                round(1000 * self.ttft_sla_s, 3)
                if self.ttft_sla_s is not None else None
            ),
            "itl_sla_ms": (
                round(1000 * self.itl_sla_s, 3)
                if self.itl_sla_s is not None else None
            ),
            "target": self.target,
            "good_streams": self.good_streams,
            "breached_streams": self.breached_streams,
            "goodput": {
                lab: self.goodput.value(window=lab) for lab in labels
            },
            "burn_rate": {
                lab: self.burn_rate.value(window=lab) for lab in labels
            },
            "phase_p99_ms": {
                p: self.phase_p99.value(phase=p) for p in PHASES
            },
        }

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


# -- the frontend-side store --------------------------------------------------


class TrajectoryStore:
    """Bounded per-trace span/event accumulator + stitcher.

    Ring discipline mirrors runtime/lifecycle.py: a recent ring (LRU by
    trace id, incomplete traces evicted last-resort only) plus a slow/error
    capture ring retaining stitched SUMMARIES of trajectories whose root
    exceeded the SLA threshold or errored — a tail-latency incident stays
    inspectable (with its dominant phase named) long after the recent ring
    churned past it. Writes happen on the frontend's event loop (collector
    pump + local tracer listener) — DYN005 owner of the ``trajectory``
    flight ring."""

    def __init__(
        self,
        *,
        max_recent: Optional[int] = None,
        max_slow: Optional[int] = None,
        slow_threshold_s: Optional[float] = None,
        slo: Optional[SloTracker] = None,
        max_spans_per_trace: int = 512,
    ) -> None:
        from dynamo_tpu.runtime.lifecycle import SLOW_REQUEST_S

        self.max_recent = (
            max_recent if max_recent is not None
            else config.TRAJECTORY_RECENT.get()
        )
        self.max_slow = (
            max_slow if max_slow is not None else config.TRAJECTORY_SLOW.get()
        )
        self.slow_threshold_s = (
            slow_threshold_s if slow_threshold_s is not None
            else SLOW_REQUEST_S.get()
        )
        self.max_spans_per_trace = max_spans_per_trace
        self.slo = slo if slo is not None else SloTracker()
        self._recent: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._slow: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()
        self.flight = FlightRecorder("trajectory", capacity=512)
        self.spans_ingested = 0
        self.spans_dropped = 0

    # -- ingestion ---------------------------------------------------------

    def attach_tracer(self, tracer: Any) -> None:
        """Feed this process's own finished spans (the frontend's http
        root, router decisions, overload queue waits) without a network
        hop."""
        self._tracer_listener = lambda span: self.add_span(span_record(span))
        tracer.add_listener(self._tracer_listener)

    def detach_tracer(self, tracer: Any) -> None:
        listener = getattr(self, "_tracer_listener", None)
        if listener is not None:
            tracer.remove_listener(listener)
            self._tracer_listener = None

    def ingest(self, payload: Dict[str, Any]) -> None:
        """One shipped batch from a worker (TrajectoryCollector pump).
        Completed traces are refreshed ONCE per batch, not per span — a
        worker batch landing after the root (the normal ship-cadence
        ordering) must not restitch the whole trace per late span on the
        event loop that is also serving requests."""
        proc = payload.get("proc")
        completed: Dict[str, Dict[str, Any]] = {}
        for rec in payload.get("spans") or ():
            if isinstance(rec, dict):
                if proc and not rec.get("proc"):
                    rec["proc"] = proc
                entry = self.add_span(rec, refresh=False)
                if entry is not None:
                    completed[entry["trace_id"]] = entry
        for ev in payload.get("events") or ():
            if isinstance(ev, dict):
                self.add_event(ev)
        for entry in completed.values():
            try:
                self._on_complete(entry)
            except Exception:
                logger.debug("trajectory refresh failed", exc_info=True)

    def _entry(self, trace_id: str) -> Dict[str, Any]:
        entry = self._recent.get(trace_id)
        if entry is None:
            entry = {
                "trace_id": trace_id,
                "spans": [],
                "events": [],
                "complete": False,
                "root": None,
                "t_first": time.monotonic(),
            }
            self._recent[trace_id] = entry
            while len(self._recent) > self.max_recent:
                # Evict completed trajectories first: an in-flight
                # long-tail request must still be collecting when its
                # root arrives, or it can never reach the slow ring.
                victim = next(
                    (t for t, e in self._recent.items() if e["complete"]),
                    None,
                )
                if victim is None:
                    self._recent.popitem(last=False)
                else:
                    del self._recent[victim]
        else:
            self._recent.move_to_end(trace_id)
        return entry

    def add_span(
        self, rec: Dict[str, Any], *, refresh: bool = True
    ) -> Optional[Dict[str, Any]]:
        """Never raises: observability must not take down serving. With
        ``refresh=False`` (batch ingest) the completed entry is returned
        instead of refreshed inline, so the caller refreshes once."""
        try:
            trace_id = rec.get("trace_id")
            if not trace_id:
                return None
            completed = None
            with self._lock:
                entry = self._entry(trace_id)
                if len(entry["spans"]) >= self.max_spans_per_trace:
                    self.spans_dropped += 1
                    return None
                entry["spans"].append(rec)
                self.spans_ingested += 1
                if is_root_span(rec):
                    entry["root"] = rec
                    entry["complete"] = True
                if entry["complete"]:
                    completed = entry
            self.flight.record(
                "ingest", trace_id=trace_id, name=rec.get("name"),
                proc=_proc_of(rec),
            )
            if completed is not None and refresh:
                self._on_complete(completed)
                return None
            return completed
        except Exception:
            logger.debug("trajectory span ingest failed", exc_info=True)
            return None

    def add_event(self, ev: Dict[str, Any]) -> None:
        try:
            trace_id = ev.get("trace_id")
            if not trace_id:
                return
            with self._lock:
                entry = self._entry(trace_id)
                if len(entry["events"]) < self.max_spans_per_trace:
                    entry["events"].append(ev)
        except Exception:
            logger.debug("trajectory event ingest failed", exc_info=True)

    def _on_complete(self, entry: Dict[str, Any]) -> None:
        """Root span present (or a late span refined a completed trace):
        refresh the phase feed + slow/error ring from a fresh stitch."""
        stitched = stitch(
            entry["spans"], entry["events"],
            trace_id=entry["trace_id"], complete=True,
        )
        self.slo.note_phases(entry["trace_id"], stitched["phases"])
        root = entry.get("root") or {}
        errored = any(
            str(s.get("status", "ok")) != "ok" for s in entry["spans"]
        )
        slow = (
            float(root.get("duration_ms") or 0.0)
            >= self.slow_threshold_s * 1000.0
        )
        if not (slow or errored):
            return
        summary = self._summary_of(stitched)
        summary["retained"] = "slow" if slow else "error"
        with self._lock:
            fresh = entry["trace_id"] not in self._slow
            self._slow[entry["trace_id"]] = summary
            self._slow.move_to_end(entry["trace_id"])
            while len(self._slow) > self.max_slow:
                self._slow.popitem(last=False)
        if fresh:
            self.flight.record(
                "slow_capture", trace_id=entry["trace_id"],
                dominant_phase=summary["dominant_phase"],
                total_ms=summary["total_ms"],
            )

    # -- reads -------------------------------------------------------------

    @staticmethod
    def _summary_of(stitched: Dict[str, Any]) -> Dict[str, Any]:
        # ``summary: True`` + span COUNT under a distinct key: a consumer
        # of GET /debug/trajectory/{id} iterating ``spans`` must get a
        # list or nothing, never an int (slow-ring hits serve this shape
        # after the full span set aged out of the recent ring).
        return {
            "trace_id": stitched["trace_id"],
            "summary": True,
            "total_ms": stitched["total_ms"],
            "processes": stitched["processes"],
            "span_count": len(stitched["spans"]),
            "phases": stitched["phases"],
            # The one-GET bottleneck answer: a slow request names the
            # phase that dominated it.
            "dominant_phase": stitched["dominant_phase"],
            "kv_reuse": stitched.get("kv_reuse"),
            "skew_flagged": stitched["skew_flagged"],
            "complete": stitched["complete"],
        }

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Stitch one trajectory on demand (off the ingest path)."""
        with self._lock:
            entry = self._recent.get(trace_id)
            if entry is not None:
                spans = list(entry["spans"])
                events = list(entry["events"])
                complete = entry["complete"]
            else:
                slow = self._slow.get(trace_id)
                if slow is not None:
                    return dict(slow)
                return None
        return stitch(spans, events, trace_id=trace_id, complete=complete)

    def summaries(self) -> List[Dict[str, Any]]:
        with self._lock:
            entries = [
                (t, list(e["spans"]), list(e["events"]), e["complete"])
                for t, e in self._recent.items()
            ]
        return [
            self._summary_of(
                stitch(spans, events, trace_id=t, complete=complete)
            )
            for t, spans, events, complete in entries
        ]

    def slow_summaries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._slow.values()]

    def register_metrics(self, server: Any) -> None:
        server.register_metrics(self.slo.render)
        server.register_flight(self.flight.name, self.flight.snapshot)


# -- worker-side shipping -----------------------------------------------------


class TrajectoryShipper:
    """Batch finished spans + trace-tagged events onto the event plane.

    The tracer listener may fire from any thread, so the queue is a plain
    bounded deque (thread-safe appends; overflow evicts-and-counts like the
    OTLP exporter). A pump task drains it on a flush cadence and publishes
    one ``{proc, spans, events}`` message per batch; a failed publish (or
    an injected ``trajectory.ship`` fault) drops the batch and counts it —
    telemetry must never take down serving."""

    def __init__(
        self,
        event_plane: Any,
        namespace: str,
        *,
        proc: Optional[str] = None,
        flush_interval_s: Optional[float] = None,
        max_batch: int = 128,
        max_queue: int = 4096,
    ) -> None:
        from dynamo_tpu.utils.tracing import service_label

        self._plane = event_plane
        self._topic = trajectory_topic(namespace)
        self.proc = proc or service_label()
        self.flush_interval_s = (
            flush_interval_s if flush_interval_s is not None
            else config.TRAJECTORY_SHIP_INTERVAL_S.get()
        )
        self.max_batch = max_batch
        self._spans: "collections.deque" = collections.deque(maxlen=max_queue)
        self._events: "collections.deque" = collections.deque(maxlen=max_queue)
        self.shipped = 0
        self.dropped = 0
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    def attach(self, tracer: Any) -> None:
        tracer.add_listener(self._on_span)

    def _on_span(self, span: Any) -> None:
        if not getattr(span, "trace_id", None):
            return
        if len(self._spans) == self._spans.maxlen:
            self.dropped += 1
        self._spans.append(span_record(span))

    def offer_event(
        self, trace_id: Optional[str], ring: str, kind: str, **fields: Any
    ) -> None:
        """One trace-tagged flight event (retries, breaker trips, handoff
        progress) to ride the next batch."""
        if not trace_id:
            return
        if len(self._events) == self._events.maxlen:
            self.dropped += 1
        self._events.append({
            "trace_id": trace_id, "ring": ring, "kind": kind,
            "t_wall": time.time(), **fields,
        })

    def start(self) -> None:
        # get_running_loop, not get_event_loop: starting outside a loop
        # must raise loudly instead of binding the pump to a dead loop
        # (the Planner.start lesson, PR 12 satellite).
        if self._task is None:
            self._stop.clear()
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="trajectory-ship"
            )

    def _drain(self) -> Tuple[List[dict], List[dict]]:
        spans: List[dict] = []
        events: List[dict] = []
        while self._spans and len(spans) < self.max_batch:
            spans.append(self._spans.popleft())
        while self._events and len(events) < self.max_batch:
            events.append(self._events.popleft())
        return spans, events

    async def flush_once(self) -> None:
        while self._spans or self._events:
            spans, events = self._drain()
            if not spans and not events:
                return
            try:
                # Chaos seam: the telemetry path dying must cost exactly
                # this batch, never the serving path that produced it.
                fault_point(fault_names.TRAJECTORY_SHIP, batch=len(spans))
                await self._plane.publish(
                    self._topic,
                    {"proc": self.proc, "spans": spans, "events": events},
                )
                self.shipped += len(spans) + len(events)
            except Exception:
                self.dropped += len(spans) + len(events)
                logger.debug(
                    "trajectory batch dropped (%d spans)", len(spans),
                    exc_info=True,
                )
                return

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.flush_interval_s
                )
            except asyncio.TimeoutError:
                pass
            await self.flush_once()

    async def close(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
        await self.flush_once()


class TrajectoryCollector:
    """Frontend-side subscription pump: event plane → TrajectoryStore."""

    def __init__(
        self, event_plane: Any, namespace: str,
        store: Optional[TrajectoryStore] = None,
    ) -> None:
        self._plane = event_plane
        self._topic = trajectory_topic(namespace)
        self.store = store if store is not None else global_store()
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._sub = self._plane.subscribe(self._topic)
        self._task = asyncio.get_running_loop().create_task(
            self._pump(), name=f"trajectory-collect:{self._topic}"
        )

    async def _pump(self) -> None:
        async for _topic, payload in self._sub:
            try:
                if isinstance(payload, dict):
                    self.store.ingest(payload)
            except Exception:
                logger.exception("bad trajectory batch")

    async def stop(self) -> None:
        from dynamo_tpu.runtime.tasks import reap_task

        if self._sub is not None:
            await self._sub.aclose()
            self._sub = None
        if self._task is not None:
            self._task.cancel()
            await reap_task(self._task, "trajectory collector pump", logger)
            self._task = None


# -- process globals ----------------------------------------------------------

_STORE: Optional[TrajectoryStore] = None
_SHIPPER: Optional[TrajectoryShipper] = None


def global_store() -> TrajectoryStore:
    """The process-global store, lazily attached to the global tracer so
    every process (frontend, worker, test harness) can serve
    ``/debug/trajectory`` over at least its own spans."""
    global _STORE
    if _STORE is None:
        from dynamo_tpu.utils.tracing import global_tracer

        _STORE = TrajectoryStore()
        _STORE.attach_tracer(global_tracer())
    return _STORE


def set_global_shipper(shipper: Optional[TrajectoryShipper]) -> None:
    """Install the worker's shipper for ``note_event`` call sites."""
    global _SHIPPER
    _SHIPPER = shipper


def note_event(
    trace_id: Optional[str], ring: str, kind: str, **fields: Any
) -> None:
    """Trace-tag one flight event into the trajectory plane: queued on the
    worker's shipper when one is installed, and fed to the local store when
    this process holds one (the frontend). One None-check each when the
    plane is idle — safe at any call site."""
    if not trace_id:
        return
    if _SHIPPER is not None:
        _SHIPPER.offer_event(trace_id, ring, kind, **fields)
    if _STORE is not None:
        _STORE.add_event({
            "trace_id": trace_id, "ring": ring, "kind": kind,
            "t_wall": time.time(), **fields,
        })


def global_slo() -> SloTracker:
    return global_store().slo


def trajectory_index(store: Optional[TrajectoryStore] = None) -> Dict[str, Any]:
    """The GET /debug/trajectory response body — ONE shape shared by the
    system server and the frontend HttpService."""
    store = store if store is not None else global_store()
    return {
        "slow_threshold_s": store.slow_threshold_s,
        "traces": store.summaries(),
        "slow": store.slow_summaries(),
        "slo": store.slo.snapshot(),
    }


def trajectory_view(
    trace_id: str, store: Optional[TrajectoryStore] = None
) -> Optional[Dict[str, Any]]:
    """The GET /debug/trajectory/{trace_id} body (None = 404)."""
    store = store if store is not None else global_store()
    return store.get(trace_id)


def render_trajectory_metrics(openmetrics: bool = False) -> str:
    """ALL_SLO exposition for every SystemStatusServer (the trajectory
    analog of render_runtime_metrics): goodput/burn-rate/phase gauges are
    process-global, armed wherever streams finish."""
    return global_store().slo.render(openmetrics=openmetrics)
