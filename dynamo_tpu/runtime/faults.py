"""faultline: a process-global, seeded, deterministic fault-injection plane.

The fault-tolerance machinery (request migration, canary health, disagg
retry/breaker) is only trustworthy if the failures it absorbs can be
*produced on demand* — FlowKV's observation (PAPERS.md) is that transfer
failures and stragglers must be absorbed by the scheduler, and the only way
to prove a scheduler absorbs a failure is to inject that failure in a test
that replays bit-identically. This module is the seam: subsystems call
``fault_point(<declared name>)`` at the places a real deployment fails
(wire send/recv, per-chunk KV pulls, engine tick dispatch/reap, lease
renewal, canary probes, tier IO) and an armed :class:`FaultPlane` decides —
deterministically — whether that hit raises.

Design rules:

  * **Disabled is free.** ``fault_point`` is a module-global ``None`` check
    when no plane is armed — no locks, no logging, no allocation. The
    dispatch/reap seams sit on the decode hot path, and dynlint DYN002
    walks through this module to prove the purity holds.
  * **Schedules are (seed, operation-count), never wall-clock.** A rule
    fires at the Nth hit of a point, every Nth hit, or with probability p
    drawn from a per-point ``random.Random(f"{seed}:{point}")`` stream —
    so the same plan over the same workload produces the identical
    injection trace regardless of host speed, and a failing chaos run
    replays exactly (asserted by tests/test_faultline.py).
  * **Closed name set.** Every point name comes from
    runtime/fault_names.py; arming a plan that names an undeclared point
    fails fast, and dynlint DYN006 statically closes call sites over the
    same registry.

The module also aggregates process-wide *recovery activity* counters
(``note_activity``): retries, breaker transitions, migrations. bench.py
records them in every leg so a chaos-free run proves zero spurious
activations of the self-healing paths.
"""

from __future__ import annotations

import random
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.fault_names import ALL_FAULT_POINTS


class InjectedFault(Exception):
    """Marker mixin: every exception the plane raises derives from this,
    so tests (and post-mortems) can tell injected chaos from organic
    failures while production code still sees the native type."""


class InjectedConnectionError(InjectedFault, ConnectionError):
    pass


class InjectedTimeoutError(InjectedFault, TimeoutError):
    pass


class InjectedError(InjectedFault, RuntimeError):
    pass


_KINDS = {
    "connection": InjectedConnectionError,
    "timeout": InjectedTimeoutError,
    "error": InjectedError,
}

# Data-mutating kind: instead of raising, a firing "corrupt" rule flips one
# bit of the payload passing through a ``fault_payload`` seam (deterministic:
# bit 0 of the middle byte), modeling silent storage/wire corruption. Only
# seams that carry a payload (``fault_payload``) can apply it; at a plain
# ``fault_point`` a firing corrupt rule is recorded in the trace but mutates
# nothing (there is nothing to mutate).
CORRUPT_KIND = "corrupt"


def corrupt_bytes(data: bytes, flip: int = 0) -> bytes:
    """The deterministic corruption transform: bit ``flip % 8`` of the
    middle byte. Exposed so tests can predict the exact corrupted form.
    ``flip`` distinguishes stacked applications on one hit — the flip is
    an involution, so two rules flipping the SAME bit would silently
    restore the pristine payload while the trace claims two injections."""
    if not data:
        return data
    buf = bytearray(data)
    buf[len(buf) // 2] ^= 1 << (flip % 8)
    return bytes(buf)


@dataclass(frozen=True)
class FaultRule:
    """One trigger on one point. ``at`` are 1-based hit indices; ``every``
    fires on every Nth hit; ``p`` draws per hit from the point's seeded
    stream (the draw happens on EVERY hit, fire or not, so replay stays
    aligned). ``times`` bounds total fires (None = unbounded)."""

    point: str
    at: Tuple[int, ...] = ()
    every: int = 0
    p: float = 0.0
    kind: str = "connection"
    times: Optional[int] = None

    def __post_init__(self) -> None:
        if self.point not in ALL_FAULT_POINTS:
            raise ValueError(
                f"undeclared fault point {self.point!r} — add it to "
                "runtime/fault_names.py (DYN006 closes call sites over "
                "the same registry)"
            )
        if self.kind not in _KINDS and self.kind != CORRUPT_KIND:
            raise ValueError(
                f"unknown fault kind {self.kind!r} "
                f"(one of {sorted([*_KINDS, CORRUPT_KIND])})"
            )
        # Tolerate list specs from JSON plans.
        if not isinstance(self.at, tuple):
            object.__setattr__(self, "at", tuple(self.at))

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultRule":
        unknown = set(d) - set(cls.__dataclass_fields__)
        if unknown:
            # A typo'd trigger field ("evry") would otherwise arm a rule
            # with all-default triggers that never fires — a chaos run
            # passing vacuously. Same fail-fast contract as point names.
            raise ValueError(
                f"unknown FaultRule field(s) {sorted(unknown)} "
                f"(valid: {sorted(cls.__dataclass_fields__)})"
            )
        return cls(**d)


@dataclass(frozen=True)
class FaultPlan:
    """A seed plus an ordered rule list — the full chaos schedule. The
    plan (not the plane) is what a failing run's repro ships."""

    seed: int = 0
    rules: Tuple[FaultRule, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.rules, tuple):
            object.__setattr__(self, "rules", tuple(self.rules))

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultPlan":
        return cls(
            seed=int(d.get("seed", 0)),
            rules=tuple(
                FaultRule.from_dict(r) for r in d.get("rules", [])
            ),
        )


@dataclass
class _RuleState:
    fired: int = 0


class FaultPlane:
    """Armed chaos: per-point hit counters + rule evaluation + the
    injection trace. ``hit`` is the only method on a hot path; it bumps a
    dict counter, evaluates the (usually absent) rules for the point, and
    either returns or raises. No locks anywhere — per-point hit streams
    are single-threaded at every installed seam, and the GIL makes the
    counter bumps safe for cross-point concurrency."""

    def __init__(self, plan: FaultPlan) -> None:
        # Deferred import: this module is imported by runtime/distributed.py
        # (for the module-level fault_point), and metrics_core pulls
        # utils.logging — importing it at module level closes an import
        # cycle when utils.logging is the process's first entry into the
        # runtime package. A plane is only built for chaos runs.
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.plan = plan
        self.hits: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}
        # (point, hit index, rule index, kind) per injection — the replay
        # identity two runs of the same plan must agree on.
        self.trace: List[Tuple[str, int, int, str]] = []
        self._rules: Dict[str, List[Tuple[int, FaultRule, _RuleState]]] = {}
        self._rng: Dict[str, random.Random] = {}
        for i, rule in enumerate(plan.rules):
            self._rules.setdefault(rule.point, []).append(
                (i, rule, _RuleState())
            )
            if rule.p:
                # Seeded per POINT (not per rule): the stream advances one
                # draw per hit per p-rule, in declaration order, so the
                # trace is a pure function of (plan, per-point hit counts).
                self._rng.setdefault(
                    rule.point, random.Random(f"{plan.seed}:{rule.point}")
                )
        self.registry = MetricsRegistry()
        self._armed_gauge = self.registry.gauge(
            mn.FAULTS_ARMED,
            "1 while a fault plan is armed in this process (chaos runs "
            "only; production scrapes must read 0)",
        )
        self._injections = self.registry.counter(
            mn.FAULTS_INJECTIONS_TOTAL,
            "Faults injected by the armed plan, per declared point",
            ["point"],
        )
        self.registry.on_render(self._refresh)

    def _refresh(self) -> None:
        self._armed_gauge.set(1 if _PLANE is self else 0)
        for point, n in list(self.injected.items()):
            self._injections.set_total(n, point=point)

    def hit(self, name: str, info: Dict[str, Any]) -> None:
        self._eval(name, info, None)

    def hit_payload(self, name: str, data: bytes, info: Dict[str, Any]) -> bytes:
        """Payload-carrying hit (``fault_payload`` seams): raising kinds
        raise exactly like ``hit``; a firing "corrupt" rule returns the
        deterministically bit-flipped payload instead."""
        out = self._eval(name, info, data)
        return data if out is None else out

    def _eval(
        self, name: str, info: Dict[str, Any], data: Optional[bytes]
    ) -> Optional[bytes]:
        n = self.hits.get(name, 0) + 1
        self.hits[name] = n
        rules = self._rules.get(name)
        if not rules:
            return None
        rng = self._rng.get(name)
        corrupted: Optional[bytes] = None
        n_corrupt = 0
        for idx, rule, state in rules:
            fire = n in rule.at
            if rule.every and n % rule.every == 0:
                fire = True
            if rule.p and rng is not None:
                # One draw per hit per p-rule keeps replays aligned even
                # when another rule already decided to fire.
                draw = rng.random() < rule.p
                fire = fire or draw
            if not fire:
                continue
            if rule.times is not None and state.fired >= rule.times:
                continue
            state.fired += 1
            self.injected[name] = self.injected.get(name, 0) + 1
            self.trace.append((name, n, idx, rule.kind))
            if rule.kind == CORRUPT_KIND:
                # Mutate-and-continue: later raising rules on the same hit
                # still evaluate (a plan may corrupt AND kill one point).
                # At a payload-less seam there is nothing to mutate — the
                # trace entry still records the scheduled fire.
                if data is not None:
                    # Stacked corrupt rules on one hit flip DIFFERENT bits
                    # (flip=0, 1, …): corrupt_bytes is an involution, so
                    # re-flipping bit 0 would restore the pristine payload
                    # while the trace claims two injections.
                    corrupted = corrupt_bytes(
                        data if corrupted is None else corrupted, n_corrupt
                    )
                    n_corrupt += 1
                continue
            raise _KINDS[rule.kind](
                f"injected {rule.kind} fault at {name} "
                f"(hit {n}, rule {idx}{', ' + repr(info) if info else ''})"
            )
        return corrupted

    def snapshot(self) -> Dict[str, Any]:
        return {
            "seed": self.plan.seed,
            "hits": dict(self.hits),
            "injected": dict(self.injected),
            "trace": [list(t) for t in self.trace],
        }


_PLANE: Optional[FaultPlane] = None

# Process-wide recovery-activity counters (retry/breaker/migration events),
# counted whether or not a plane is armed: bench legs record them so a
# chaos-free run PROVES the self-healing paths sat idle.
_ACTIVITY: Dict[str, int] = {}


def fault_point(name: str, **info: Any) -> None:
    """Declare-and-maybe-fail one named operation. Disabled cost: one
    module-global load and a None check."""
    plane = _PLANE
    if plane is not None:
        plane.hit(name, info)


def fault_payload(name: str, data: bytes, **info: Any) -> bytes:
    """Payload-carrying seam variant: behaves exactly like ``fault_point``
    for raising kinds, and additionally lets a "corrupt" rule flip one bit
    of ``data`` (deterministically) before returning it. One hit per call —
    a seam uses EITHER fault_point OR fault_payload, never both, so hit
    schedules stay stable. Disabled cost: a None check, data untouched."""
    plane = _PLANE
    if plane is None:
        return data
    return plane.hit_payload(name, data, info)


def arm(plan: FaultPlan) -> FaultPlane:
    """Install ``plan`` as the process's fault plane (replacing any)."""
    global _PLANE
    _PLANE = FaultPlane(plan)
    return _PLANE


def disarm() -> None:
    global _PLANE
    _PLANE = None


def active_plane() -> Optional[FaultPlane]:
    return _PLANE


@contextmanager
def armed(plan: FaultPlan) -> Iterator[FaultPlane]:
    plane = arm(plan)
    try:
        yield plane
    finally:
        if _PLANE is plane:
            disarm()


def note_activity(kind: str, n: int = 1) -> None:
    """Record one recovery-path activation (e.g. ``pull_retries``,
    ``breaker_opens``, ``migrations``). GIL-atomic dict bump — callable
    from any thread, cheap enough for error paths."""
    _ACTIVITY[kind] = _ACTIVITY.get(kind, 0) + n


def activity_snapshot() -> Dict[str, int]:
    return dict(_ACTIVITY)


def reset_activity() -> None:
    _ACTIVITY.clear()


def plane_snapshot() -> Dict[str, Any]:
    """Fault-plane state for bench legs / debug surfaces: armed flag,
    per-point injections, and the recovery-activity counters."""
    plane = _PLANE
    return {
        "armed": plane is not None,
        "injections": dict(plane.injected) if plane is not None else {},
        "activity": activity_snapshot(),
    }
