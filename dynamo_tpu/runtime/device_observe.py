"""Device/runtime observability plane: compile telemetry, HBM ledger,
engine flight recorder, on-demand profiler capture.

PR 1 built the *serving-plane* observability layer (per-object metric
registries, request timelines, trace exemplars); this module is the
*device plane* — the reference Dynamo treats runtime-level metrics as a
first-class layer next to the serving metrics (PAPER layer map), and the
PR 2/3 decode path (width-bucketed programs, pipelined ticks, megakernel
fallback arming) created exactly the failure classes that are invisible
without it: a silent recompile storm, HBM-accounting drift, or a tick
pipeline wedging with no record of the events that led there.

Four parts, all designed to stay OFF the tick thread's critical path:

  1. **Compile telemetry** (``watched_jit`` / ``CompileWatcher``): every
     ``jax.jit`` program site wraps its compiled callable; per program we
     track compile count, distinct-signature count, compile wall-time, and
     a recompile-storm detector (counter + warning when one program object
     crosses its signature budget — the pow2 ``table_width_bucket``
     programs get an explicit expected-count budget from the runner).
     Steady-state cost per dispatch is two ``_cache_size()`` C++ calls and
     two ``perf_counter()`` reads — no locks, no tree flattening.
  2. **HBM ledger** (``HbmLedger``): structural byte accounting per
     category (KV pools, params, decode slot state, slot tables, LoRA
     stacks, processor state), sampled at scrape/snapshot time and
     cross-checked against ``device.memory_stats()`` where the backend
     provides it (TPU does; the CPU client returns None).
  3. **Flight recorder** (``FlightRecorder``): a preallocated,
     SINGLE-WRITER ring of typed engine events with monotonic timestamps.
     One ring per writer thread (the engine tick loop owns one, the
     device-thread runner owns another); ``/debug/flight`` merges them by
     timestamp. Append is O(1) into a preallocated slot — no locks, no
     allocation beyond the event tuple itself.
  4. **Profiler control** (``ProfilerControl``): ``POST /debug/profile``
     wraps ``jax.profiler.start_trace``/``stop_trace`` with graceful
     no-op degradation when the backend/profiler is unavailable.

Every Prometheus name comes from runtime/metric_names.py (``ALL_RUNTIME``)
— the lint test rejects inline literals. Metric values mirror the plain
host-side counters via ``on_render`` hooks, so the hot path never touches
a metrics lock; render-time sampling pays it instead.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.metrics_core import Histogram, MetricsRegistry
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Compile wall-times span ~10 ms (tiny scatter) to minutes (8B megakernel
# variants) — latency DEFAULT_BUCKETS top out at 60 s and start at 1 ms.
COMPILE_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    120.0, 300.0,
)

# Default per-program-object distinct-signature budget for sites without
# an explicit one: generous enough for legitimate multi-axis bucketing
# (the prefill program specializes on pow2 chunk × pow2 width × pow2 row
# buckets), small enough that a per-request shape leak — a fresh context
# length per call — still trips it within a few hundred requests.
DEFAULT_SIGNATURE_BUDGET = 256


class _ProgramStats:
    """Aggregated per-NAME compile stats. Several jit objects may share a
    name (the runner rebuilds its decode program per variant and per
    engine instance); totals aggregate, while the storm budget is judged
    per WatchedJit instance — a fresh engine recompiling its own programs
    is warmup, not a storm."""

    __slots__ = (
        "name", "compiles", "signatures", "storms", "compile_seconds",
        "last_compile_seconds", "budget", "_hist",
    )

    def __init__(self, name: str, hist: Histogram) -> None:
        self.name = name
        self.compiles = 0
        self.signatures = 0
        self.storms = 0
        self.compile_seconds = 0.0
        self.last_compile_seconds = 0.0
        self.budget: Optional[int] = None
        self._hist = hist

    def on_compile(self, n: int, dt: float) -> None:
        self.compiles += n
        self.signatures += n
        self.compile_seconds += dt
        self.last_compile_seconds = dt
        # Histogram takes its lock — fine: compiles are rare by definition
        # (a program that compiles on the hot path is the storm we detect).
        self._hist.observe(dt, program=self.name)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "compiles": self.compiles,
            "signatures": self.signatures,
            "storms": self.storms,
            "compile_seconds": round(self.compile_seconds, 4),
            "last_compile_seconds": round(self.last_compile_seconds, 4),
            "budget": self.budget,
        }


class WatchedJit:
    """Wrapper around one compiled (``jax.jit``) callable that attributes
    cache growth to its program name.

    Detection uses the jit object's own ``_cache_size()`` (a C++
    attribute read) — a call during which the cache grew IS a compile, and
    its wall time is compile-dominated. No signature hashing on the hot
    path; a fallback signature set exists only for jit-like callables
    without ``_cache_size`` (older/newer jax, test doubles).

    Unknown attributes forward to the wrapped callable so call sites can
    keep using ``_cache_size`` / ``clear_cache`` / ``lower`` directly.
    """

    __slots__ = ("_fn", "_stats", "_sigs", "_budget", "_seen", "_fast")

    def __init__(
        self, stats: _ProgramStats, fn: Callable, budget: Optional[int] = None
    ) -> None:
        self._fn = fn
        self._stats = stats
        self._sigs = 0  # distinct signatures THIS program object compiled
        self._budget = budget
        self._fast = hasattr(fn, "_cache_size")
        self._seen: Optional[set] = None if self._fast else set()

    @property
    def signatures(self) -> int:
        return self._sigs

    def __call__(self, *args, **kwargs):
        fn = self._fn
        if self._fast:
            before = fn._cache_size()
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            grew = fn._cache_size() - before
            if grew > 0:
                self._on_compile(grew, time.perf_counter() - t0)
            return out
        key = _abstract_signature(args, kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if key not in self._seen:
            self._seen.add(key)
            self._on_compile(1, time.perf_counter() - t0)
        return out

    def _on_compile(self, n: int, dt: float) -> None:
        self._sigs += n
        st = self._stats
        st.on_compile(n, dt)
        budget = self._budget if self._budget is not None else st.budget
        if budget is None:
            budget = DEFAULT_SIGNATURE_BUDGET
        if self._sigs > budget:
            st.storms += 1
            logger.warning(
                "recompile storm: program %r has compiled %d distinct "
                "signatures (budget %d) — dispatched shapes are not "
                "bucketing; every new signature pays a full XLA compile "
                "on the serving path",
                st.name, self._sigs, budget,
            )

    def __getattr__(self, item: str):
        return getattr(object.__getattribute__(self, "_fn"), item)


def _abstract_signature(args, kwargs) -> Tuple:
    """Cheap (shape, dtype) signature for the no-``_cache_size`` fallback.
    Non-array leaves degrade to their type — good enough for telemetry."""
    import jax

    def leaf_key(x):
        shape = getattr(x, "shape", None)
        if shape is not None:
            return (tuple(shape), str(getattr(x, "dtype", "?")))
        return (type(x).__name__, x if isinstance(x, (int, bool, str)) else None)

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(leaf_key(l) for l in leaves))


class CompileWatcher:
    """Per-process compile-telemetry registry (program name → stats).

    Metrics mirror the plain counters at render time (``on_render``), so
    dispatch-path increments are lock-free attribute bumps under the GIL.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        self._lock = threading.Lock()  # program-creation only, never hot
        self._programs: Dict[str, _ProgramStats] = {}
        self._hist = self.registry.histogram(
            mn.RUNTIME_COMPILE_SECONDS,
            "Wall time of calls that compiled a new program signature "
            "(trace + XLA compile + first execute)",
            ["program"],
            buckets=COMPILE_BUCKETS,
        )
        self._compiles = self.registry.counter(
            mn.RUNTIME_COMPILES_TOTAL,
            "jit program compilations observed per watched program site",
            ["program"],
        )
        self._signatures = self.registry.gauge(
            mn.RUNTIME_COMPILE_SIGNATURES,
            "Distinct compiled signatures per watched program site",
            ["program"],
        )
        self._storms = self.registry.counter(
            mn.RUNTIME_RECOMPILE_STORMS_TOTAL,
            "Signature-budget violations (a program object compiling more "
            "distinct signatures than its shape-bucketing budget allows)",
            ["program"],
        )
        self.registry.on_render(self._refresh)

    def _refresh(self) -> None:
        for name, st in list(self._programs.items()):
            self._compiles.set_total(st.compiles, program=name)
            self._signatures.set(st.signatures, program=name)
            self._storms.set_total(st.storms, program=name)

    def program(self, name: str) -> _ProgramStats:
        st = self._programs.get(name)
        if st is None:
            with self._lock:
                st = self._programs.get(name)
                if st is None:
                    st = _ProgramStats(name, self._hist)
                    self._programs[name] = st
        return st

    def set_budget(self, name: str, budget: Optional[int]) -> None:
        """Default per-instance signature budget for every WatchedJit that
        shares ``name`` and didn't set its own."""
        self.program(name).budget = budget

    def snapshot(self) -> Dict[str, Any]:
        # Materialize the shared dict in one C-level call before touching
        # Python code: writer threads may insert new programs mid-scrape.
        programs = {
            name: st.to_dict()
            for name, st in sorted(list(self._programs.items()))
        }
        return {"programs": programs, "totals": self.totals()}

    def totals(self) -> Dict[str, Any]:
        stats = list(self._programs.values())
        return {
            "programs": len(stats),
            "compiles": sum(s.compiles for s in stats),
            "signatures": sum(s.signatures for s in stats),
            "storms": sum(s.storms for s in stats),
            "compile_seconds": round(sum(s.compile_seconds for s in stats), 4),
        }


def watched_jit(
    name: str,
    fn: Callable,
    *,
    budget: Optional[int] = None,
    watcher: Optional[CompileWatcher] = None,
) -> WatchedJit:
    """Wrap an already-jitted callable with compile telemetry under
    ``name``. ``budget``: per-instance distinct-signature budget (None =
    the watcher's per-name default, which itself defaults to unbudgeted)."""
    w = watcher if watcher is not None else global_compile_watcher()
    return WatchedJit(w.program(name), fn, budget)


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------


def tree_device_bytes(tree: Any) -> int:
    """Sum ``.nbytes`` over every array-like leaf of a pytree. Works on
    jax arrays (including donated-and-replaced references — nbytes is
    shape metadata, valid even on deleted buffers), numpy mirrors, and
    int8 pool dicts; None and scalar leaves contribute 0."""
    if tree is None:
        return 0
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            try:
                total += int(nb)
            except (TypeError, ValueError):
                pass  # exotic nbytes (property raising, non-numeric)
    return total


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device ``memory_stats()`` where the backend provides it (TPU
    reports bytes_in_use / bytes_limit; the CPU client returns None)."""
    out: List[Dict[str, Any]] = []
    try:
        import jax

        devices = jax.devices()
    except Exception as exc:  # backend init failure: degrade, don't 500
        return [{"error": f"{type(exc).__name__}: {exc}"}]
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        out.append(
            {
                "id": getattr(d, "id", None),
                "platform": getattr(d, "platform", None),
                "memory_stats": stats,
            }
        )
    return out


class HbmLedger:
    """Structural device-memory accounting: category → byte-count sampler.

    Samplers run at snapshot/scrape time only (never on the tick thread)
    and read live object references — a category whose sampler throws
    reports -1 (visible as "unknown" rather than silently zero). The
    ledger also tracks the peak total it has ever observed, which
    bench.py records per leg."""

    def __init__(self) -> None:
        self._sources: Dict[str, Callable[[], int]] = {}
        self.peak_bytes = 0
        self.registry = MetricsRegistry()
        self._gauge = self.registry.gauge(
            mn.RUNTIME_HBM_BYTES,
            "Structural device-memory bytes per ledger category "
            "(sampled from live engine state at scrape time)",
            ["category"],
        )
        self._device_gauge = self.registry.gauge(
            mn.RUNTIME_HBM_DEVICE_BYTES,
            "Backend-reported device memory (device.memory_stats(), "
            "absent on backends that do not provide it)",
            ["device", "kind"],
        )
        self.registry.on_render(self._refresh)

    def register(self, category: str, fn: Callable[[], int]) -> None:
        self._sources[category] = fn

    def snapshot(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        # list() first: samplers run Python code (thread-switch points),
        # and a concurrent register() must not break the iteration.
        for category, fn in list(self._sources.items()):
            try:
                out[category] = int(fn())
            except Exception:
                out[category] = -1
        total = sum(v for v in out.values() if v > 0)
        if total > self.peak_bytes:
            self.peak_bytes = total
        return out

    def total_bytes(self) -> int:
        return sum(v for v in self.snapshot().values() if v > 0)

    def _refresh(self) -> None:
        for category, nbytes in self.snapshot().items():
            self._gauge.set(nbytes, category=category)
        for dev in device_memory_stats():
            stats = dev.get("memory_stats")
            if not stats:
                continue
            for kind in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
                if kind in stats:
                    self._device_gauge.set(
                        stats[kind], device=str(dev.get("id")), kind=kind
                    )


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Preallocated single-writer ring of typed engine events.

    Contract: ``record`` is called from EXACTLY ONE thread per recorder
    (the engine tick loop owns one ring, the device-thread runner owns
    another); readers (``snapshot``, the metrics refresh) may run on any
    thread and tolerate a concurrently advancing write index — a torn
    read can at worst miss or double-see the newest event, never corrupt
    the ring. Append is an index store + tuple build: O(1), no locks, no
    list growth."""

    def __init__(self, name: str, capacity: int = 2048) -> None:
        self.name = name
        self.capacity = int(capacity)
        self._ring: List[Optional[Tuple[float, str, Optional[dict]]]] = (
            [None] * self.capacity
        )
        self._n = 0  # total events ever recorded (monotonic)
        self.counts: Dict[str, int] = {}
        self.registry = MetricsRegistry()
        self._events = self.registry.counter(
            mn.RUNTIME_FLIGHT_EVENTS_TOTAL,
            "Flight-recorder events per ring and kind",
            ["ring", "kind"],
        )
        self._overwritten = self.registry.counter(
            mn.RUNTIME_FLIGHT_OVERWRITTEN_TOTAL,
            "Flight-recorder events overwritten by ring wrap (history "
            "older than the ring capacity is gone)",
            ["ring"],
        )
        self.registry.on_render(self._refresh)

    def record(self, kind: str, **fields: Any) -> None:
        i = self._n
        self._ring[i % self.capacity] = (
            time.monotonic(), kind, fields or None
        )
        self._n = i + 1
        self.counts[kind] = self.counts.get(kind, 0) + 1

    @property
    def total(self) -> int:
        return self._n

    @property
    def overwritten(self) -> int:
        return max(0, self._n - self.capacity)

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Events oldest→newest as dicts (``seq`` is the global event
        index, ``t_mono`` the monotonic timestamp)."""
        n = self._n
        start = max(0, n - self.capacity)
        if limit is not None:
            start = max(start, n - int(limit))
        out: List[Dict[str, Any]] = []
        for i in range(start, n):
            ev = self._ring[i % self.capacity]
            if ev is None:
                continue
            ts, kind, fields = ev
            d: Dict[str, Any] = {
                "seq": i, "t_mono": round(ts, 6), "ring": self.name,
                "kind": kind,
            }
            if fields:
                d.update(fields)
            out.append(d)
        return out

    def _refresh(self) -> None:
        for kind, count in list(self.counts.items()):
            self._events.set_total(count, ring=self.name, kind=kind)
        self._overwritten.set_total(self.overwritten, ring=self.name)


def dump_flight(
    recorders: Dict[str, "FlightRecorder"],
    *,
    dump_dir: Optional[str] = None,
    reason: str = "abort",
) -> Optional[str]:
    """Write every ring's events (merged, timestamp-ordered) to a JSON
    file; returns the path or None on failure. Used by the engine's
    ``_abort_inflight`` so a wedged/failed tick leaves a post-mortem even
    if nobody is scraping ``/debug/flight``."""
    try:
        if not dump_dir:
            from dynamo_tpu import config as _cfg

            dump_dir = _cfg.FLIGHT_DUMP_DIR.get() or None
        if not dump_dir:
            import tempfile

            dump_dir = tempfile.gettempdir()
        os.makedirs(dump_dir, exist_ok=True)
        events: List[Dict[str, Any]] = []
        for rec in recorders.values():
            events.extend(rec.snapshot())
        events.sort(key=lambda e: e["t_mono"])
        path = os.path.join(
            dump_dir,
            f"dynamo_tpu_flight_{os.getpid()}_{int(time.time() * 1000)}.json",
        )
        with open(path, "w") as f:
            json.dump(
                {
                    "reason": reason,
                    "rings": sorted(recorders),
                    "events": events,
                },
                f,
            )
        return path
    except Exception:
        logger.exception("flight-recorder dump failed")
        return None


# ---------------------------------------------------------------------------
# On-demand profiler capture
# ---------------------------------------------------------------------------


class ProfilerControl:
    """Start/stop ``jax.profiler`` traces on demand (POST /debug/profile).

    Degrades to a structured no-op when the profiler is unavailable
    (missing backend support, already-active capture from another tool):
    every path returns a JSON-able dict, never raises."""

    def __init__(self) -> None:
        self._lock = threading.Lock()  # admin path only
        self._active_dir: Optional[str] = None
        self._t_start = 0.0
        self.captures = 0
        # Monotonic capture generation: bumped on every successful start,
        # so a bounded capture's auto-stop timer can tell "my capture is
        # still the active one" apart from "a NEWER capture reuses my
        # dir" (dir equality cannot).
        self.generation = 0
        self.registry = MetricsRegistry()
        self._captures_metric = self.registry.counter(
            mn.RUNTIME_PROFILER_CAPTURES_TOTAL,
            "Completed on-demand jax.profiler captures",
        )
        self.registry.on_render(
            lambda: self._captures_metric.set_total(self.captures)
        )

    def status(self) -> Dict[str, Any]:
        return {
            "active": self._active_dir is not None,
            "dir": self._active_dir,
            "captures": self.captures,
            "generation": self.generation,
        }

    def start(self, log_dir: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            if self._active_dir is not None:
                return {
                    "ok": False,
                    "error": "capture already active",
                    "dir": self._active_dir,
                }
            if not log_dir:
                import tempfile

                # Hyphenated prefix: the metric-name lint greps for
                # dynamo_tpu_* snake literals.
                log_dir = tempfile.mkdtemp(prefix="dynamo-tpu-profile-")
            try:
                import jax.profiler

                jax.profiler.start_trace(log_dir)
            except Exception as exc:
                logger.warning("profiler start degraded to no-op: %s", exc)
                return {
                    "ok": False,
                    "degraded": True,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            self._active_dir = log_dir
            self._t_start = time.monotonic()
            self.generation += 1
            return {"ok": True, "dir": log_dir, "generation": self.generation}

    def stop(self) -> Dict[str, Any]:
        with self._lock:
            if self._active_dir is None:
                return {"ok": False, "error": "no active capture"}
            log_dir = self._active_dir
            duration = time.monotonic() - self._t_start
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception as exc:
                # A transient stop failure (export write error) may leave
                # jax's trace session live — keep the capture marked
                # active so the operator can RETRY the stop, unless the
                # error says the session already ended (then clearing is
                # the only way to un-wedge start()).
                msg = str(exc).lower()
                ended = (
                    "no trace" in msg or "not started" in msg
                    or "no active" in msg
                )
                if ended:
                    self._active_dir = None
                logger.warning("profiler stop degraded to no-op: %s", exc)
                return {
                    "ok": False,
                    "degraded": True,
                    "dir": log_dir,
                    "still_active": not ended,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            self._active_dir = None
            self.captures += 1
            return {
                "ok": True, "dir": log_dir, "duration_s": round(duration, 3)
            }


# ---------------------------------------------------------------------------
# Process globals (mirrors lifecycle.global_lifecycle / tracing.global_tracer)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_WATCHER: Optional[CompileWatcher] = None
_PROFILER: Optional[ProfilerControl] = None


def _init_globals() -> None:
    global _WATCHER, _PROFILER
    with _LOCK:
        if _WATCHER is not None:
            return
        _PROFILER = ProfilerControl()
        _WATCHER = CompileWatcher()


def global_compile_watcher() -> CompileWatcher:
    """Process-global compile telemetry (jit sites are module-level and
    per-runner; one watcher sees them all)."""
    if _WATCHER is None:
        _init_globals()
    return _WATCHER  # type: ignore[return-value]


def global_profiler() -> ProfilerControl:
    if _PROFILER is None:
        _init_globals()
    return _PROFILER  # type: ignore[return-value]


def render_runtime_metrics(openmetrics: bool = False) -> str:
    """Prometheus text for the process-global runtime families (compile
    watcher + profiler). Registered on every SystemStatusServer — the
    device plane is per-process, like the lifecycle/tracer debug rings."""
    parts = [
        global_compile_watcher().registry.render(openmetrics=openmetrics),
        global_profiler().registry.render(openmetrics=openmetrics),
    ]
    return "\n".join(p for p in parts if p)
