"""Pipeline operators: composable request/response-stream transformations.

Reference parity: the pipeline node graph in lib/runtime/src/pipeline.rs
(Source/Sink/Operator/SegmentSource/SegmentSink) and the assembled chain in
lib/llm/src/entrypoint/input/common.rs:173 (SegmentSource → OpenAIPreprocessor
→ Backend → Migration → Router).

The reference models pipelines as linked graph nodes with typed edges; here an
``Operator`` is a pure transformation around a downstream ``AsyncEngine``:

    stream = operator.generate(request, context, next=downstream)

An operator may rewrite the request (preprocessor), rewrite/augment the
response stream (detokenizer), retry against the downstream (migration), or
choose among many downstreams (router). ``build_pipeline`` folds a list of
operators onto a terminal engine, producing a plain AsyncEngine — so composed
pipelines nest and are themselves routable.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Optional, Protocol, runtime_checkable

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine, as_engine


@runtime_checkable
class Operator(Protocol):
    def generate(
        self, request: Any, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Any]:
        ...


class _BoundOperator:
    """An Operator partially applied to its downstream engine."""

    __slots__ = ("_op", "_next")

    def __init__(self, op: Operator, next: AsyncEngine) -> None:
        self._op = op
        self._next = next

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._op.generate(request, context, next=self._next)

    def __repr__(self) -> str:
        return f"{type(self._op).__name__} → {self._next!r}"


def build_pipeline(operators: List[Operator], engine: Any) -> AsyncEngine:
    """Fold operators (outermost first) onto a terminal engine."""
    current: AsyncEngine = as_engine(engine)
    for op in reversed(operators):
        current = _BoundOperator(op, current)
    return current


class PassthroughOperator:
    """Identity operator; useful as a base class and in tests."""

    async def generate(
        self, request: Any, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Any]:
        async for item in next.generate(request, context):
            yield item


class MapRequestOperator(PassthroughOperator):
    """Applies a (possibly async) function to the request before forwarding."""

    def __init__(self, fn) -> None:
        self._fn = fn

    async def generate(self, request, context, next):
        mapped = self._fn(request)
        if hasattr(mapped, "__await__"):
            mapped = await mapped
        async for item in next.generate(mapped, context):
            yield item


class MapStreamOperator(PassthroughOperator):
    """Applies a function to every item of the response stream."""

    def __init__(self, fn) -> None:
        self._fn = fn

    async def generate(self, request, context, next):
        async for item in next.generate(request, context):
            yield self._fn(item)
