"""Dependency-free metrics kit for subsystem collectors.

Reference parity: lib/runtime/src/metrics (the reference builds typed
Prometheus metrics into every runtime component and exposes them through the
system status server). The frontend keeps prometheus_client (http/metrics.py
predates this module and benefits from its battle-tested client); subsystem
collectors (router, KVBM, disagg, engine step loop) use this kit instead
because they are instantiated per-object — a process may hold several
routers or tiered managers, and prometheus_client's process-global default
registry turns re-instantiation into duplicate-name errors. Here every
subsystem owns a private ``MetricsRegistry`` and registers its ``render``
on the per-process ``SystemStatusServer`` via ``register_metrics``.

Exemplar support: histograms accept an optional ``trace_id`` per
observation, rendered OpenMetrics-style (`` # {trace_id="…"} value ts``)
when ``render(openmetrics=True)`` — a dashboard latency spike links
straight to the captured trace/timeline (tentpole part 3).

Every metric name MUST come from runtime/metric_names.py — the lint test
(tests/test_metric_names_lint.py) fails any emitter that inlines a
``dynamo_tpu_*`` string literal.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LabelKey = Tuple[str, ...]

DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0,
)
# Wide count buckets for token/block histograms (not latencies).
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)


def _fmt(v: float) -> str:
    # Prometheus text format: integers render without exponent noise.
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: Sequence[str], values: LabelKey, extra: str = "") -> str:
    parts = [f'{n}="{_escape(str(v))}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, kwargs: Dict[str, object]) -> LabelKey:
        if set(kwargs) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kwargs)} != declared "
                f"{sorted(self.labelnames)}"
            )
        return tuple(str(kwargs[n]) for n in self.labelnames)

    def render(self, openmetrics: bool = False) -> List[str]:  # pragma: no cover
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:
        super().__init__(name, help, labelnames)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_total(self, value: float, **labels: object) -> None:
        """Mirror an externally maintained monotonic total (e.g. TierStats
        counters owned by the storage tier) — used from on_render hooks so
        the legacy attribute stays the single source of truth."""
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, **labels: object) -> float:
        return self._values.get(self._key(labels), 0.0)

    def render(self, openmetrics: bool = False) -> List[str]:
        # OpenMetrics keys counter metadata on the family name (sans the
        # mandatory ``_total`` sample suffix); the classic text format keys
        # it on the sample name. Strict parsers reject a TYPE line whose
        # name already carries the suffix.
        family = sample = self.name
        if openmetrics:
            if family.endswith("_total"):
                family = family[: -len("_total")]
            sample = family + "_total"
        lines = [f"# HELP {family} {self.help}", f"# TYPE {family} counter"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{sample}{_label_str(self.labelnames, key)} {_fmt(v)}")
        return lines


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:
        super().__init__(name, help, labelnames)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels: object) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def remove(self, **labels: object) -> None:
        """Drop one series (a departed worker must not freeze at its last
        value)."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def value(self, **labels: object) -> float:
        return self._values.get(self._key(labels), 0.0)

    def render(self, openmetrics: bool = False) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{_label_str(self.labelnames, key)} {_fmt(v)}")
        return lines


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per label key: ([bucket counts..., +Inf], sum, count)
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        # (key, bucket index) -> last exemplar (value, trace_id, unix ts)
        self._exemplars: Dict[Tuple[LabelKey, int], Tuple[float, str, float]] = {}

    def observe(
        self, value: float, trace_id: Optional[str] = None, **labels: object
    ) -> None:
        key = self._key(labels)
        v = float(value)
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if v <= b:
                idx = i
                break
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            if trace_id:
                self._exemplars[(key, idx)] = (v, str(trace_id), time.time())

    def count(self, **labels: object) -> int:
        return sum(self._counts.get(self._key(labels), ()))

    def snapshot_total(self, **labels: object) -> Tuple[int, float]:
        """(observation count, value sum) for one label key — the cheap
        aggregate programmatic consumers (bench.py host-gap reporting)
        read without parsing the rendered exposition."""
        key = self._key(labels)
        with self._lock:
            return (
                sum(self._counts.get(key, ())),
                self._sums.get(key, 0.0),
            )

    def render(self, openmetrics: bool = False) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
            exemplars = dict(self._exemplars)
        for key, counts in items:
            acc = 0
            for i, bound in enumerate(list(self.buckets) + [float("inf")]):
                acc += counts[i]
                le = "+Inf" if bound == float("inf") else _fmt(bound)
                le_label = 'le="' + le + '"'
                line = (
                    f"{self.name}_bucket"
                    f"{_label_str(self.labelnames, key, le_label)} {acc}"
                )
                if openmetrics:
                    ex = exemplars.get((key, i))
                    if ex is not None:
                        v, tid, ts = ex
                        line += (
                            f' # {{trace_id="{_escape(tid)}"}} {_fmt(v)} {ts:.3f}'
                        )
                lines.append(line)
            ls = _label_str(self.labelnames, key)
            lines.append(f"{self.name}_sum{ls} {repr(sums.get(key, 0.0))}")
            lines.append(f"{self.name}_count{ls} {acc}")
        return lines


class MetricsRegistry:
    """A private registry: one per subsystem object. ``render()`` is the
    function handed to ``SystemStatusServer.register_metrics``."""

    def __init__(self) -> None:
        self._metrics: List[_Metric] = []
        self._before_render: List[Callable[[], None]] = []

    def counter(self, name: str, help: str, labelnames: Sequence[str] = ()) -> Counter:
        m = Counter(name, help, labelnames)
        self._metrics.append(m)
        return m

    def gauge(self, name: str, help: str, labelnames: Sequence[str] = ()) -> Gauge:
        m = Gauge(name, help, labelnames)
        self._metrics.append(m)
        return m

    def histogram(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        m = Histogram(name, help, labelnames, buckets)
        self._metrics.append(m)
        return m

    def on_render(self, fn: Callable[[], None]) -> None:
        """Register a pre-render hook — gauges sampled from live state
        (scheduler worker loads, tier occupancy) refresh at scrape time."""
        self._before_render.append(fn)

    def render(self, openmetrics: bool = False) -> str:
        for fn in self._before_render:
            try:
                fn()
            except Exception:  # a broken sampler must not break the scrape
                logger.debug("metrics render hook %r failed", fn,
                             exc_info=True)
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.render(openmetrics=openmetrics))
        return "\n".join(lines)
