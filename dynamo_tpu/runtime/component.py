"""Namespaces, components, endpoints, instances, and clients.

Reference parity: lib/runtime/src/component.rs (Namespace :411, Component :141,
Endpoint :320, Instance/TransportType :70,88) and the PushRouter
(pipeline/network/egress/push_router.rs:41,76 — RoundRobin/Random/Direct/KV).

Naming: ``{namespace}/{component}/{endpoint}`` addresses a logical service;
N live *instances* (workers) back it. Serving an endpoint registers an
instance in the discovery plane under a lease; clients watch the prefix and
route per-request among live instances.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator, Dict, List, Optional, TYPE_CHECKING

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.discovery import (
    EventKind,
    instance_key,
    instance_prefix,
)
from dynamo_tpu.runtime.engine import AsyncEngine, as_engine
from dynamo_tpu.runtime.tasks import reap_task

if TYPE_CHECKING:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class Instance:
    """A live worker behind an endpoint (ref: component.rs:70)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int
    transport: Dict[str, Any]  # {"kind": "local"|"tcp", ...address info}
    metadata: Dict[str, Any] = field(default_factory=dict, hash=False)

    @property
    def key(self) -> str:
        return instance_key(self.namespace, self.component, self.endpoint, self.instance_id)

    @property
    def endpoint_path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.endpoint}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "instance_id": self.instance_id,
            "transport": self.transport,
            "metadata": self.metadata,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Instance":
        return cls(
            namespace=d["namespace"],
            component=d["component"],
            endpoint=d["endpoint"],
            instance_id=int(d["instance_id"]),
            transport=dict(d.get("transport", {})),
            metadata=dict(d.get("metadata", {})),
        )


class RouterMode(str, Enum):
    ROUND_ROBIN = "round_robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class Namespace:
    def __init__(self, runtime: "DistributedRuntime", name: str) -> None:
        self._runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._runtime, self.name, name)

    @property
    def runtime(self) -> "DistributedRuntime":
        return self._runtime


class Component:
    def __init__(self, runtime: "DistributedRuntime", namespace: str, name: str) -> None:
        self._runtime = runtime
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._runtime, self.namespace, self.name, name)

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"


class Endpoint:
    def __init__(
        self, runtime: "DistributedRuntime", namespace: str, component: str, name: str
    ) -> None:
        self._runtime = runtime
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    async def serve_endpoint(
        self,
        handler: Any,
        *,
        instance_id: Optional[int] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> "ServedEndpoint":
        """Expose ``handler`` (an AsyncEngine or async generator function) as a
        live instance of this endpoint (ref: _core.pyi:153 serve_endpoint)."""
        engine = as_engine(handler)
        return await self._runtime._serve(self, engine, instance_id=instance_id, metadata=metadata or {})

    async def client(self, router_mode: RouterMode = RouterMode.ROUND_ROBIN) -> "Client":
        client = Client(self._runtime, self, router_mode)
        await client.start()
        return client


@dataclass
class ServedEndpoint:
    instance: Instance
    _runtime: "DistributedRuntime"
    _engine: AsyncEngine

    async def shutdown(self, grace_period: float = 30.0) -> None:
        await self._runtime._unserve(self, grace_period=grace_period)


class Client:
    """Routes requests to live instances of an endpoint.

    Reference parity: PushRouter (push_router.rs:41) + the client-side
    instance map fed by discovery watch (distributed.rs:394). KV-mode routing
    delegates instance selection to an injected picker (router layer).
    """

    def __init__(
        self,
        runtime: "DistributedRuntime",
        endpoint: Endpoint,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    ) -> None:
        self._runtime = runtime
        self._endpoint = endpoint
        self.router_mode = router_mode
        self._instances: Dict[int, Instance] = {}
        self._rr_index = 0
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._instances_nonempty = asyncio.Event()
        self._kv_picker = None  # async (request, instances) -> instance_id
        self._on_stream_done = None  # (instance_id, request) -> None
        self._instance_filter = None  # (instance_id) -> bool (health gating)

    @property
    def endpoint_path(self) -> str:
        return self._endpoint.path

    @property
    def instance_ids(self) -> List[int]:
        return sorted(self._instances)

    def set_kv_picker(self, picker) -> None:
        self._kv_picker = picker

    def set_instance_filter(self, predicate) -> None:
        """``predicate(instance_id) -> bool``; False excludes the instance
        from routing (ref: worker_monitor.rs eviction of unhealthy workers).
        Direct routing (explicit instance_id) bypasses the filter."""
        self._instance_filter = predicate

    def set_stream_done_callback(self, callback) -> None:
        """``callback(instance_id, request)`` fires when a routed stream ends
        (normally or not) — lets a KV router release its in-flight load
        prediction (ref: kv_router sequence.rs free on completion)."""
        self._on_stream_done = callback

    async def start(self) -> None:
        prefix = instance_prefix(
            self._endpoint.namespace, self._endpoint.component, self._endpoint.name
        )
        watch = self._runtime.discovery.watch(prefix)
        self._watch = watch

        def _apply(event) -> None:
            if event.kind == EventKind.PUT and event.value is not None:
                inst = Instance.from_dict(event.value)
                self._instances[inst.instance_id] = inst
                self._instances_nonempty.set()
            elif event.kind == EventKind.DELETE:
                iid = _instance_id_from_key(event.key)
                if iid is not None:
                    self._instances.pop(iid, None)
                if not self._instances:
                    self._instances_nonempty.clear()

        # Apply the snapshot inline so the first request can route immediately.
        for event in watch.drain_snapshot():
            _apply(event)

        async def _run() -> None:
            async for event in watch:
                _apply(event)

        self._watch_task = asyncio.get_running_loop().create_task(
            _run(), name=f"client-watch:{self.endpoint_path}"
        )

    async def wait_for_instances(self, timeout: float = 10.0) -> List[int]:
        await asyncio.wait_for(self._instances_nonempty.wait(), timeout=timeout)
        return self.instance_ids

    async def close(self) -> None:
        if self._watch is not None:
            await self._watch.aclose()
            self._watch = None
        if self._watch_task is not None:
            self._watch_task.cancel()
            await reap_task(self._watch_task, "endpoint watch", logger)
            self._watch_task = None

    # -- routing ----------------------------------------------------------

    async def _pick(self, request: Any, instance_id: Optional[int]) -> Instance:
        if not self._instances:
            raise NoInstancesError(self.endpoint_path)
        if instance_id is not None:
            inst = self._instances.get(instance_id)
            if inst is None:
                raise NoInstancesError(
                    f"{self.endpoint_path} instance {instance_id:#x} not found"
                )
            return inst
        eligible = self._instances
        if self._instance_filter is not None:
            eligible = {
                iid: inst
                for iid, inst in self._instances.items()
                if self._instance_filter(iid)
            }
            if not eligible:
                raise NoInstancesError(
                    f"{self.endpoint_path}: all instances excluded (unhealthy)"
                )
        ids = sorted(eligible)
        if self.router_mode == RouterMode.RANDOM:
            return eligible[random.choice(ids)]
        if self.router_mode == RouterMode.KV and self._kv_picker is not None:
            chosen = await self._kv_picker(request, dict(eligible))
            if chosen is not None and chosen in eligible:
                return eligible[chosen]
        # Round-robin default (also KV fallback when picker abstains).
        self._rr_index = (self._rr_index + 1) % len(ids)
        return eligible[ids[self._rr_index]]

    def generate(
        self,
        request: Any,
        context: Optional[Context] = None,
        *,
        instance_id: Optional[int] = None,
    ) -> AsyncIterator[Any]:
        ctx = context or Context()
        return self._generate(request, ctx, instance_id)

    async def _generate(
        self, request: Any, context: Context, instance_id: Optional[int]
    ) -> AsyncIterator[Any]:
        instance = None
        try:
            instance = await self._pick(request, instance_id)
            remote = self._runtime.request_plane_client(instance)
            async for item in remote.generate(request, context):
                yield item
        finally:
            # Fires even when _pick itself fails after the KV picker charged
            # the scheduler (the instance may have raced away) — otherwise
            # the router's in-flight accounting leaks.
            if self._on_stream_done is not None:
                try:
                    self._on_stream_done(
                        instance.instance_id if instance is not None else None,
                        request,
                    )
                except Exception:
                    logger.exception("stream-done callback failed")

    def direct(self, request: Any, instance_id: int, context: Optional[Context] = None):
        """Route to a specific instance (RouterMode::Direct)."""
        return self.generate(request, context, instance_id=instance_id)


class NoInstancesError(RuntimeError):
    """No live instances for an endpoint (ref: 'no responders' NATS error —
    the trigger for migration, migration.rs:24)."""


def _instance_id_from_key(key: str) -> Optional[int]:
    try:
        return int(key.rsplit("/", 1)[1], 16)
    except (IndexError, ValueError):
        return None
