"""Namespaces, components, endpoints, instances, and clients.

Reference parity: lib/runtime/src/component.rs (Namespace :411, Component :141,
Endpoint :320, Instance/TransportType :70,88) and the PushRouter
(pipeline/network/egress/push_router.rs:41,76 — RoundRobin/Random/Direct/KV).

Naming: ``{namespace}/{component}/{endpoint}`` addresses a logical service;
N live *instances* (workers) back it. Serving an endpoint registers an
instance in the discovery plane under a lease; clients watch the prefix and
route per-request among live instances.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator, Dict, List, Optional, TYPE_CHECKING

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.discovery import (
    EventKind,
    instance_key,
    instance_prefix,
)
from dynamo_tpu.runtime.engine import AsyncEngine, as_engine
from dynamo_tpu.runtime.tasks import reap_task

if TYPE_CHECKING:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class Instance:
    """A live worker behind an endpoint (ref: component.rs:70)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int
    transport: Dict[str, Any]  # {"kind": "local"|"tcp", ...address info}
    metadata: Dict[str, Any] = field(default_factory=dict, hash=False)

    @property
    def key(self) -> str:
        return instance_key(self.namespace, self.component, self.endpoint, self.instance_id)

    @property
    def endpoint_path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.endpoint}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "instance_id": self.instance_id,
            "transport": self.transport,
            "metadata": self.metadata,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Instance":
        return cls(
            namespace=d["namespace"],
            component=d["component"],
            endpoint=d["endpoint"],
            instance_id=int(d["instance_id"]),
            transport=dict(d.get("transport", {})),
            metadata=dict(d.get("metadata", {})),
        )


class RouterMode(str, Enum):
    ROUND_ROBIN = "round_robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class Namespace:
    def __init__(self, runtime: "DistributedRuntime", name: str) -> None:
        self._runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._runtime, self.name, name)

    @property
    def runtime(self) -> "DistributedRuntime":
        return self._runtime


class Component:
    def __init__(self, runtime: "DistributedRuntime", namespace: str, name: str) -> None:
        self._runtime = runtime
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._runtime, self.namespace, self.name, name)

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"


class Endpoint:
    def __init__(
        self, runtime: "DistributedRuntime", namespace: str, component: str, name: str
    ) -> None:
        self._runtime = runtime
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    async def serve_endpoint(
        self,
        handler: Any,
        *,
        instance_id: Optional[int] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> "ServedEndpoint":
        """Expose ``handler`` (an AsyncEngine or async generator function) as a
        live instance of this endpoint (ref: _core.pyi:153 serve_endpoint)."""
        engine = as_engine(handler)
        return await self._runtime._serve(self, engine, instance_id=instance_id, metadata=metadata or {})

    async def client(self, router_mode: RouterMode = RouterMode.ROUND_ROBIN) -> "Client":
        client = Client(self._runtime, self, router_mode)
        await client.start()
        return client


@dataclass
class ServedEndpoint:
    instance: Instance
    _runtime: "DistributedRuntime"
    _engine: AsyncEngine

    async def shutdown(self, grace_period: float = 30.0) -> None:
        await self._runtime._unserve(self, grace_period=grace_period)


class Client:
    """Routes requests to live instances of an endpoint.

    Reference parity: PushRouter (push_router.rs:41) + the client-side
    instance map fed by discovery watch (distributed.rs:394). KV-mode routing
    delegates instance selection to an injected picker (router layer).
    """

    def __init__(
        self,
        runtime: "DistributedRuntime",
        endpoint: Endpoint,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    ) -> None:
        self._runtime = runtime
        self._endpoint = endpoint
        self.router_mode = router_mode
        self._instances: Dict[int, Instance] = {}
        self._rr_index = 0
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._instances_nonempty = asyncio.Event()
        self._kv_picker = None  # async (request, instances) -> instance_id
        self._on_stream_done = None  # (instance_id, request) -> None
        self._instance_filter = None  # (instance_id) -> bool (health gating)
        # Crash plane (runtime/liveness.py): per-instance abort handles for
        # in-flight streams. Opt-in via enable_stream_aborts() — the
        # abortable iteration races each item against the abort future,
        # which costs one extra task per item; sessions without liveness
        # wiring keep the plain fast path.
        self._abortable = False
        self._abort_futures: Dict[int, set] = {}
        # Liveness-evicted instances, kept for revive_instance: a frozen
        # worker that rejoins under the SAME incarnation never re-PUTs
        # its key, so the watch alone cannot restore its capacity.
        self._evicted: Dict[int, Instance] = {}

    @property
    def endpoint_path(self) -> str:
        return self._endpoint.path

    @property
    def instance_ids(self) -> List[int]:
        return sorted(self._instances)

    def set_kv_picker(self, picker) -> None:
        import inspect

        self._kv_picker = picker
        # Trajectory plane: a context-aware picker ((request, instances,
        # context)) gets the request Context so its selection span joins
        # the request's trace; legacy 2-arg pickers keep working.
        try:
            params = inspect.signature(picker).parameters
            self._picker_takes_context = (
                "context" in params
                or any(
                    p.kind == inspect.Parameter.VAR_KEYWORD
                    for p in params.values()
                )
            )
        except (TypeError, ValueError):
            self._picker_takes_context = False

    def set_instance_filter(self, predicate) -> None:
        """``predicate(instance_id) -> bool``; False excludes the instance
        from routing (ref: worker_monitor.rs eviction of unhealthy workers).
        Direct routing (explicit instance_id) bypasses the filter."""
        self._instance_filter = predicate

    def set_stream_done_callback(self, callback) -> None:
        """``callback(instance_id, request)`` fires when a routed stream ends
        (normally or not) — lets a KV router release its in-flight load
        prediction (ref: kv_router sequence.rs free on completion)."""
        self._on_stream_done = callback

    # -- crash plane --------------------------------------------------------

    def enable_stream_aborts(self) -> None:
        """Arm per-stream abort handles (liveness wiring calls this once)."""
        self._abortable = True

    def abort_instance(self, instance_id: int, exc: BaseException) -> int:
        """Fail every in-flight stream routed to ``instance_id`` with
        ``exc`` RIGHT NOW — the liveness tracker's dead-worker hook. The
        typed exception (WorkerLostError) surfaces through the stream and
        the migration ladder re-dispatches immediately instead of the
        stream hanging until a TCP timeout. Returns streams aborted."""
        aborted = 0
        for fut in list(self._abort_futures.get(instance_id, ())):
            if not fut.done():
                fut.set_exception(exc)
                aborted += 1
        return aborted

    def evict_instance(self, instance_id: int) -> bool:
        """Drop a dead instance from routing immediately, ahead of its
        discovery lease expiring. The instance is stashed: a RESTARTED
        worker re-PUTs its key and the watch re-adds it with fresh
        transport, but a worker that merely froze past the budget (GC
        pause, short partition) resumes under the SAME incarnation with
        no new PUT — revive_instance is the only road back for it."""
        inst = self._instances.pop(instance_id, None)
        if inst is not None:
            self._evicted[instance_id] = inst
        if not self._instances:
            self._instances_nonempty.clear()
        return inst is not None

    def revive_instance(self, instance_id: int) -> bool:
        """Re-admit a liveness-evicted instance on rejoin. Same-incarnation
        rejoins (the process survived; its transport is unchanged) get
        their capacity back here; for a restarted worker the watch PUT
        overwrites this entry with the fresh transport anyway — at worst
        the stale address serves one connection error into migration."""
        inst = self._evicted.pop(instance_id, None)
        if inst is None or instance_id in self._instances:
            return False
        self._instances[instance_id] = inst
        self._instances_nonempty.set()
        return True

    async def start(self) -> None:
        prefix = instance_prefix(
            self._endpoint.namespace, self._endpoint.component, self._endpoint.name
        )
        watch = self._runtime.discovery.watch(prefix)
        self._watch = watch

        def _apply(event) -> None:
            if event.kind == EventKind.PUT and event.value is not None:
                inst = Instance.from_dict(event.value)
                self._instances[inst.instance_id] = inst
                # Authoritative re-registration supersedes any stash.
                self._evicted.pop(inst.instance_id, None)
                self._instances_nonempty.set()
            elif event.kind == EventKind.DELETE:
                iid = _instance_id_from_key(event.key)
                if iid is not None:
                    self._instances.pop(iid, None)
                    self._evicted.pop(iid, None)
                if not self._instances:
                    self._instances_nonempty.clear()

        # Apply the snapshot inline so the first request can route immediately.
        for event in watch.drain_snapshot():
            _apply(event)

        async def _run() -> None:
            async for event in watch:
                _apply(event)

        self._watch_task = asyncio.get_running_loop().create_task(
            _run(), name=f"client-watch:{self.endpoint_path}"
        )

    async def wait_for_instances(self, timeout: float = 10.0) -> List[int]:
        await asyncio.wait_for(self._instances_nonempty.wait(), timeout=timeout)
        return self.instance_ids

    async def close(self) -> None:
        if self._watch is not None:
            await self._watch.aclose()
            self._watch = None
        if self._watch_task is not None:
            self._watch_task.cancel()
            await reap_task(self._watch_task, "endpoint watch", logger)
            self._watch_task = None

    # -- routing ----------------------------------------------------------

    async def _pick(
        self,
        request: Any,
        instance_id: Optional[int],
        context: Optional[Context] = None,
    ) -> Instance:
        if not self._instances:
            raise NoInstancesError(self.endpoint_path)
        if instance_id is not None:
            inst = self._instances.get(instance_id)
            if inst is None:
                raise NoInstancesError(
                    f"{self.endpoint_path} instance {instance_id:#x} not found"
                )
            return inst
        eligible = self._instances
        if self._instance_filter is not None:
            eligible = {
                iid: inst
                for iid, inst in self._instances.items()
                if self._instance_filter(iid)
            }
            if not eligible:
                raise NoInstancesError(
                    f"{self.endpoint_path}: all instances excluded (unhealthy)"
                )
        ids = sorted(eligible)
        if self.router_mode == RouterMode.RANDOM:
            return eligible[random.choice(ids)]
        if self.router_mode == RouterMode.KV and self._kv_picker is not None:
            if getattr(self, "_picker_takes_context", False):
                chosen = await self._kv_picker(
                    request, dict(eligible), context=context
                )
            else:
                chosen = await self._kv_picker(request, dict(eligible))
            if chosen is not None and chosen in eligible:
                return eligible[chosen]
        # Round-robin default (also KV fallback when picker abstains).
        self._rr_index = (self._rr_index + 1) % len(ids)
        return eligible[ids[self._rr_index]]

    def generate(
        self,
        request: Any,
        context: Optional[Context] = None,
        *,
        instance_id: Optional[int] = None,
    ) -> AsyncIterator[Any]:
        ctx = context or Context()
        return self._generate(request, ctx, instance_id)

    async def _generate(
        self, request: Any, context: Context, instance_id: Optional[int]
    ) -> AsyncIterator[Any]:
        instance = None
        try:
            instance = await self._pick(request, instance_id, context)
            remote = self._runtime.request_plane_client(instance)
            if self._abortable:
                async for item in self._abortable_iter(
                    remote, request, context, instance.instance_id
                ):
                    yield item
            else:
                async for item in remote.generate(request, context):
                    yield item
        finally:
            # Fires even when _pick itself fails after the KV picker charged
            # the scheduler (the instance may have raced away) — otherwise
            # the router's in-flight accounting leaks.
            if self._on_stream_done is not None:
                try:
                    self._on_stream_done(
                        instance.instance_id if instance is not None else None,
                        request,
                    )
                except Exception:
                    logger.exception("stream-done callback failed")

    async def _abortable_iter(
        self, remote: AsyncEngine, request: Any, context: Context, iid: int
    ) -> AsyncIterator[Any]:
        """Iterate a remote stream racing every item against this
        instance's abort handle: when liveness declares the worker dead,
        ``abort_instance`` fails the handle and the stream raises the
        typed error immediately — it never waits out a kernel timeout on
        a socket whose peer no longer exists."""
        agen = remote.generate(request, context).__aiter__()
        abort: asyncio.Future = asyncio.get_running_loop().create_future()
        self._abort_futures.setdefault(iid, set()).add(abort)
        nxt: Optional[asyncio.Task] = None
        try:
            while True:
                nxt = asyncio.ensure_future(agen.__anext__())
                await asyncio.wait(
                    {nxt, abort}, return_when=asyncio.FIRST_COMPLETED
                )
                if abort.done() and not nxt.done():
                    nxt.cancel()
                    await reap_task(nxt, "aborted stream item", logger)
                    try:  # reap the dead worker's generator state
                        await asyncio.wait_for(agen.aclose(), timeout=1.0)
                    except Exception:
                        logger.debug("abort-path stream close failed",
                                     exc_info=True)
                    abort.result()  # raises the typed abort exception
                try:
                    item = nxt.result()
                except StopAsyncIteration:
                    return
                nxt = None
                yield item
        finally:
            if nxt is not None and not nxt.done():
                nxt.cancel()
                await reap_task(nxt, "stream item task", logger)
            handles = self._abort_futures.get(iid)
            if handles is not None:
                handles.discard(abort)
                if not handles:
                    self._abort_futures.pop(iid, None)
            if abort.done():
                abort.exception()  # mark retrieved (late abort after end)
            else:
                abort.cancel()

    def direct(self, request: Any, instance_id: int, context: Optional[Context] = None):
        """Route to a specific instance (RouterMode::Direct)."""
        return self.generate(request, context, instance_id=instance_id)


class NoInstancesError(RuntimeError):
    """No live instances for an endpoint (ref: 'no responders' NATS error —
    the trigger for migration, migration.rs:24)."""


def _instance_id_from_key(key: str) -> Optional[int]:
    try:
        return int(key.rsplit("/", 1)[1], 16)
    except (IndexError, ValueError):
        return None
