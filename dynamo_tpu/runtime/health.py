"""Active (canary) health checking of endpoint instances.

Reference parity: lib/runtime/src/health_check.rs (HealthCheckManager —
per-endpoint canary tasks with a registered payload, request timeout, and
idle gating via canary_wait_time) and
lib/llm/src/discovery/worker_monitor.rs (evicting sick-but-leased workers
from routing). A lease keeps a *dead* worker out of discovery; the canary
catches the worse case — a worker that is alive enough to renew its lease
but no longer serves (hung device loop, deadlocked executor).

Workers advertise their canary payload in instance metadata under
``health_payload`` at serve time; the checker prefers it over the default.
Unhealthy instances are excluded from routing via Client.set_instance_filter
and re-admitted the moment a canary succeeds.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Set

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# A minimal generation request every LLM-shaped engine accepts.
DEFAULT_CANARY_PAYLOAD: Dict[str, Any] = {
    "token_ids": [1],
    "request_id": "health-canary",
    "sampling": {"temperature": 0.0},
    "stop": {"max_tokens": 1, "ignore_eos": True},
    "annotations": ["health_check"],
}


@dataclass
class InstanceHealth:
    consecutive_failures: int = 0
    healthy: bool = True
    last_check: float = 0.0
    last_error: Optional[str] = None


class CanaryHealthChecker:
    """Periodically sends a canary request to every instance of a client.

    A worker is marked unhealthy after ``failure_threshold`` consecutive
    canary failures (timeout or error) and excluded from routing; one
    successful canary restores it. Checks are skipped for instances the
    client has seen traffic succeed on within ``canary_wait_time_s``
    (the reference's idle gating — don't spend canaries on a busy worker
    that is demonstrably serving).
    """

    def __init__(
        self,
        client: Any,  # runtime Client
        *,
        interval_s: float = 5.0,
        timeout_s: float = 10.0,
        failure_threshold: int = 2,
        canary_wait_time_s: float = 5.0,
        payload: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.client = client
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.failure_threshold = failure_threshold
        self.canary_wait_time_s = canary_wait_time_s
        self.payload = payload or dict(DEFAULT_CANARY_PAYLOAD)
        self.health: Dict[int, InstanceHealth] = {}
        self._activity: Dict[int, float] = {}  # last successful traffic
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        # Transition history: which worker went dark when, and what error
        # tripped it — the question the on-call asks first. Single writer:
        # every record happens on the checker's loop (DYN005 owner
        # "health").
        self.flight = FlightRecorder("health", capacity=256)
        client.set_instance_filter(self.is_healthy)

    # -- routing integration ----------------------------------------------

    def is_healthy(self, instance_id: int) -> bool:
        h = self.health.get(instance_id)
        return h is None or h.healthy

    def unhealthy_ids(self) -> Set[int]:
        return {iid for iid, h in self.health.items() if not h.healthy}

    def note_success(self, instance_id: int) -> None:
        """Report organic successful traffic (defers the canary)."""
        self._activity[instance_id] = time.monotonic()

    # -- checking ----------------------------------------------------------

    def _payload_for(self, instance: Any) -> Dict[str, Any]:
        meta = getattr(instance, "metadata", None) or {}
        return meta.get("health_payload") or self.payload

    async def check_instance(self, instance_id: int) -> bool:
        """One canary round-trip; updates state; returns health."""
        h = self.health.setdefault(instance_id, InstanceHealth())
        h.last_check = time.monotonic()
        instance = self.client._instances.get(instance_id)
        if instance is None:
            return h.healthy
        try:
            # Chaos seam: an injected canary failure must trip the same
            # exclusion/re-admission machinery a hung worker does.
            fault_point(fault_names.HEALTH_CANARY, instance=instance_id)
            stream = self.client.direct(self._payload_for(instance), instance_id)

            async def _consume():
                async for _ in stream:
                    break  # first item proves liveness

            await asyncio.wait_for(_consume(), timeout=self.timeout_s)
        except Exception as exc:
            h.consecutive_failures += 1
            h.last_error = f"{type(exc).__name__}: {exc}"
            if h.consecutive_failures >= self.failure_threshold and h.healthy:
                h.healthy = False
                self.flight.record(
                    "unhealthy", instance=instance_id,
                    failures=h.consecutive_failures, error=h.last_error,
                )
                logger.warning(
                    "instance %#x marked UNHEALTHY after %d canary failures (%s)",
                    instance_id, h.consecutive_failures, h.last_error,
                )
            return h.healthy
        if not h.healthy:
            self.flight.record(
                "recovered", instance=instance_id,
                after_failures=h.consecutive_failures,
            )
            logger.info("instance %#x recovered (canary ok)", instance_id)
        h.consecutive_failures = 0
        h.healthy = True
        h.last_error = None
        return True

    async def check_all(self) -> None:
        now = time.monotonic()
        for iid in list(self.client.instance_ids):
            recent = self._activity.get(iid, 0.0)
            h = self.health.get(iid)
            if (h is None or h.healthy) and now - recent < self.canary_wait_time_s:
                continue  # organically busy and healthy: skip the canary
            await self.check_instance(iid)
        # Forget departed instances so state doesn't leak.
        live = set(self.client.instance_ids)
        for iid in list(self.health):
            if iid not in live:
                self.health.pop(iid, None)
                self._activity.pop(iid, None)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._stop.clear()
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="canary-health"
            )

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.check_all()
            except Exception:
                logger.exception("health check sweep failed")
            try:
                await asyncio.wait_for(self._stop.wait(), timeout=self.interval_s)
            except asyncio.TimeoutError:
                pass

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None

    def status(self) -> Dict[str, Any]:
        """(ref: health_check.rs get_health_check_status)"""
        return {
            f"{iid:#x}": {
                "healthy": h.healthy,
                "consecutive_failures": h.consecutive_failures,
                "last_error": h.last_error,
            }
            for iid, h in self.health.items()
        }
