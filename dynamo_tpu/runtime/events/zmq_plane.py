"""ZMQ event plane: cross-process pub/sub through an XPUB/XSUB proxy.

Reference parity: lib/runtime/src/transports/event_plane/ — NATS is the
reference default with a brokerless ZMQ alternative (zmq_transport.rs,
"Harmony pattern"). NATS isn't available here, so the cross-process plane is
ZMQ with a tiny forwarder: publishers PUB→XSUB, subscribers SUB←XPUB.
Messages are ``topic-utf8 | msgpack payload`` two-frame multipart.

The broker runs standalone (python -m dynamo_tpu.discd --events) or embedded
in any process via ``EventBroker``. ZMQ prefix subscriptions over-match our
NATS-style patterns (``a.>``), so deliveries are re-checked with
``topic_matches`` client-side.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import zmq
import zmq.asyncio

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.events import Subscription, _SUB_CLOSED, topic_matches
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class EventBroker:
    """XSUB/XPUB forwarder (the 'nats-server' of this framework).

    With ``log_path`` set, every forwarded message is appended to a durable
    sequence-numbered log and a REP socket answers replay requests — the
    JetStream role (ref: lib/runtime/src/transports/nats.rs — the
    reference's default plane persists streams so a rejoining consumer
    resyncs from its last sequence instead of losing the gap). A broker
    restarted over the same log continues the sequence and serves history.
    Replay protocol (REQ/REP msgpack): {"from_seq": N, "max": M} →
    {"events": [[seq, topic, payload], ...], "next_seq": K, "end": bool}.
    """

    def __init__(
        self, host: str = "127.0.0.1", xsub_port: int = 0, xpub_port: int = 0,
        *, log_path: Optional[str] = None, replay_port: int = 0,
    ) -> None:
        self.host = host
        self._ctx = zmq.asyncio.Context.instance()
        self._xsub = self._ctx.socket(zmq.XSUB)
        self._xpub = self._ctx.socket(zmq.XPUB)
        self.xsub_port = xsub_port or self._bind_ephemeral(self._xsub, xsub_port)
        self.xpub_port = xpub_port or self._bind_ephemeral(self._xpub, xpub_port)
        if xsub_port:
            self._xsub.bind(f"tcp://{host}:{xsub_port}")
        if xpub_port:
            self._xpub.bind(f"tcp://{host}:{xpub_port}")
        self._task: Optional[asyncio.Task] = None
        self._replay_task: Optional[asyncio.Task] = None
        self.log_path = log_path
        self._log = None
        self.seq = 0
        self._rep: Optional[zmq.Socket] = None
        self.replay_port = 0
        self._offsets: dict = {}  # seq → byte offset (O(page) replay)
        if log_path:
            self.seq = self._recover_log(log_path)
            self._log = open(log_path, "ab")
            self._rep = self._ctx.socket(zmq.REP)
            self.replay_port = replay_port or self._rep.bind_to_random_port(
                f"tcp://{host}"
            )
            if replay_port:
                self._rep.bind(f"tcp://{host}:{replay_port}")

    def _recover_log(self, log_path: str) -> int:
        """Continue the sequence after a broker restart over the same log:
        index every record's byte offset (O(page) replay instead of a full
        rescan per request) and TRUNCATE any crash-torn tail — appending
        after garbage would poison every future replay."""
        import os

        if not os.path.exists(log_path):
            return 0
        last = 0
        valid_end = 0
        try:
            with open(log_path, "rb") as f:
                unpacker = msgpack.Unpacker(f, raw=False, strict_map_key=False)
                try:
                    for rec in unpacker:
                        if rec[0] % self.OFFSET_STRIDE == 1 or self.OFFSET_STRIDE == 1:
                            self._offsets[rec[0]] = valid_end
                        last = rec[0]
                        valid_end = unpacker.tell()
                except Exception:
                    logger.warning(
                        "event log %s has a torn tail after seq %d; truncating",
                        log_path, last,
                    )
            if valid_end < os.path.getsize(log_path):
                with open(log_path, "r+b") as f:
                    f.truncate(valid_end)
        except OSError as exc:
            # Continuing at seq 0 over an existing log would append
            # DUPLICATE sequence numbers and poison every future replay —
            # refuse to start instead.
            raise RuntimeError(
                f"durable event log {log_path} unreadable: {exc}"
            ) from exc
        return last

    def _bind_ephemeral(self, sock: zmq.Socket, port: int) -> int:
        return sock.bind_to_random_port(f"tcp://{self.host}")

    @property
    def address(self) -> str:
        """Connection string clients take: host:xsub:xpub."""
        return f"{self.host}:{self.xsub_port}:{self.xpub_port}"

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._forward(), name="event-broker"
            )
            logger.info("event broker on %s", self.address)
        if self._rep is not None and self._replay_task is None:
            self._replay_task = asyncio.get_running_loop().create_task(
                self._serve_replay(), name="event-broker-replay"
            )
            logger.info("event replay on %s:%d", self.host, self.replay_port)

    # Sparse offset index: one entry per stride bounds broker RAM on busy
    # planes (replay scans forward from the nearest indexed record).
    # Retention is operator-driven: rotate by restarting onto a fresh
    # --events-log path; consumers resync via snapshot if history rotated.
    OFFSET_STRIDE = 256

    def _append(self, frames) -> None:
        if self._log is None or len(frames) != 2:
            return
        self.seq += 1
        if self.seq % self.OFFSET_STRIDE == 1 or self.OFFSET_STRIDE == 1:
            self._offsets[self.seq] = self._log.tell()
        self._log.write(
            msgpack.packb(
                [self.seq, frames[0].decode(), frames[1]], use_bin_type=True
            )
        )
        self._log.flush()

    async def _forward(self) -> None:
        # Bidirectional proxy: data XSUB→XPUB, subscriptions XPUB→XSUB.
        if self._log is not None:
            # Durable mode must capture events even with ZERO live
            # subscribers: publishers' PUB sockets drop messages that match
            # no subscription, so the broker itself subscribes to
            # everything (the upstream \\x01 subscribe-all frame).
            await self._xsub.send(b"\x01")
        poller = zmq.asyncio.Poller()
        poller.register(self._xsub, zmq.POLLIN)
        poller.register(self._xpub, zmq.POLLIN)
        while True:
            events = dict(await poller.poll())
            if self._xsub in events:
                frames = await self._xsub.recv_multipart()
                self._append(frames)
                await self._xpub.send_multipart(frames)
            if self._xpub in events:
                await self._xsub.send_multipart(await self._xpub.recv_multipart())

    async def _serve_replay(self) -> None:
        assert self._rep is not None
        while True:
            try:
                req = msgpack.unpackb(await self._rep.recv(), raw=False)
                from_seq = int(req.get("from_seq", 1))
                limit = int(req.get("max", 1024))
                if from_seq > self.seq:
                    # Fully caught up: O(1) empty page, no log scan.
                    await self._rep.send(
                        msgpack.packb(
                            {"events": [], "next_seq": from_seq, "end": True},
                            use_bin_type=True,
                        )
                    )
                    continue
                out = []
                # Seek to the nearest indexed record at or before from_seq
                # (sparse index; the parse loop skips the remainder).
                start_seq = max(from_seq, 1)
                while start_seq > 1 and start_seq not in self._offsets:
                    start_seq -= 1
                with open(self.log_path, "rb") as f:  # type: ignore[arg-type]
                    f.seek(self._offsets.get(start_seq, 0))
                    unpacker = msgpack.Unpacker(
                        f, raw=False, strict_map_key=False
                    )
                    for rec in unpacker:
                        if rec[0] >= from_seq:
                            out.append(rec)
                            if len(out) >= limit:
                                break
                next_seq = (out[-1][0] + 1) if out else from_seq
                await self._rep.send(
                    msgpack.packb(
                        {
                            "events": out,
                            "next_seq": next_seq,
                            "end": next_seq > self.seq,
                        },
                        use_bin_type=True,
                    )
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("event replay request failed")
                try:
                    await self._rep.send(msgpack.packb({"error": "replay failed"}))
                except Exception as exc:
                    # The requester already sees a timeout; the socket
                    # state is what matters here.
                    logger.debug("replay error reply also failed: %s", exc)

    async def close(self) -> None:
        for task in (self._task, self._replay_task):
            if task is not None:
                task.cancel()
                await reap_task(task, "event-broker pump", logger)
        self._xsub.close(0)
        self._xpub.close(0)
        if self._rep is not None:
            self._rep.close(0)
        if self._log is not None:
            self._log.close()


async def replay_events(
    host: str, replay_port: int, from_seq: int = 1, *, timeout_s: float = 10.0,
):
    """Fetch the broker's durable history from ``from_seq`` onward. Returns
    a list of (seq, topic, payload) — a rejoining consumer applies these
    before switching to the live subscription (the JetStream resync flow)."""
    ctx = zmq.asyncio.Context.instance()
    sock = ctx.socket(zmq.REQ)
    sock.setsockopt(zmq.RCVTIMEO, int(timeout_s * 1000))
    sock.setsockopt(zmq.SNDTIMEO, int(timeout_s * 1000))
    sock.connect(f"tcp://{host}:{replay_port}")
    out = []
    try:
        while True:
            await sock.send(
                msgpack.packb({"from_seq": from_seq}, use_bin_type=True)
            )
            resp = msgpack.unpackb(await sock.recv(), raw=False, strict_map_key=False)
            if "error" in resp:
                raise RuntimeError(resp["error"])
            for seq, topic, raw in resp["events"]:
                out.append(
                    (seq, topic, msgpack.unpackb(raw, raw=False, strict_map_key=False))
                )
            from_seq = resp["next_seq"]
            if resp["end"] or not resp["events"]:
                return out
    finally:
        sock.close(0)


class ZmqEventPlane:
    """EventPlane over a broker at ``host:xsub_port:xpub_port``."""

    def __init__(self, address: str) -> None:
        host, xsub, xpub = address.rsplit(":", 2)
        self._ctx = zmq.asyncio.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        self._pub.connect(f"tcp://{host}:{xsub}")
        self._sub_addr = f"tcp://{host}:{xpub}"
        self._subs: List[Tuple[str, Subscription, zmq.Socket, asyncio.Task]] = []

    async def publish(self, topic: str, payload: Any) -> None:
        # Chaos seam: publishers (KV events, load reports) must tolerate a
        # lost publish — their pumps log and continue; the router heals via
        # event-id gap detection + snapshot resync.
        fault_point(fault_names.NET_ZMQ_SEND, topic=topic)
        await self._pub.send_multipart(
            [topic.encode(), msgpack.packb(payload, use_bin_type=True)]
        )

    def subscribe(self, topic: str) -> Subscription:
        sock = self._ctx.socket(zmq.SUB)
        sock.connect(self._sub_addr)
        prefix = topic[:-1] if topic.endswith(".>") else topic
        sock.setsockopt(zmq.SUBSCRIBE, prefix.encode())
        queue: asyncio.Queue = asyncio.Queue()

        sub = Subscription(topic, queue, on_close=lambda s: self._close_sub(s))

        async def pump() -> None:
            try:
                while True:
                    raw_topic, raw_payload = await sock.recv_multipart()
                    fault_point(fault_names.NET_ZMQ_RECV, topic=topic)
                    t = raw_topic.decode()
                    if topic_matches(topic, t):
                        queue.put_nowait((t, msgpack.unpackb(
                            raw_payload, raw=False, strict_map_key=False
                        )))
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("zmq subscription pump died (%s)", topic)
                queue.put_nowait(_SUB_CLOSED)

        task = asyncio.get_running_loop().create_task(pump(), name=f"zmq-sub:{topic}")
        self._subs.append((topic, sub, sock, task))
        return sub

    def _close_sub(self, sub: Subscription) -> None:
        for i, (topic, s, sock, task) in enumerate(self._subs):
            if s is sub:
                task.cancel()
                sock.close(0)
                sub._queue.put_nowait(_SUB_CLOSED)
                del self._subs[i]
                return

    async def close(self) -> None:
        for _, sub, sock, task in list(self._subs):
            task.cancel()
            await reap_task(task, "zmq subscription pump", logger)
            sock.close(0)
        self._subs.clear()
        self._pub.close(0)
