"""ZMQ event plane: cross-process pub/sub through an XPUB/XSUB proxy.

Reference parity: lib/runtime/src/transports/event_plane/ — NATS is the
reference default with a brokerless ZMQ alternative (zmq_transport.rs,
"Harmony pattern"). NATS isn't available here, so the cross-process plane is
ZMQ with a tiny forwarder: publishers PUB→XSUB, subscribers SUB←XPUB.
Messages are ``topic-utf8 | msgpack payload`` two-frame multipart.

The broker runs standalone (python -m dynamo_tpu.discd --events) or embedded
in any process via ``EventBroker``. ZMQ prefix subscriptions over-match our
NATS-style patterns (``a.>``), so deliveries are re-checked with
``topic_matches`` client-side.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import zmq
import zmq.asyncio

from dynamo_tpu.runtime.events import Subscription, _SUB_CLOSED, topic_matches
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class EventBroker:
    """XSUB/XPUB forwarder (the 'nats-server' of this framework)."""

    def __init__(self, host: str = "127.0.0.1", xsub_port: int = 0, xpub_port: int = 0) -> None:
        self.host = host
        self._ctx = zmq.asyncio.Context.instance()
        self._xsub = self._ctx.socket(zmq.XSUB)
        self._xpub = self._ctx.socket(zmq.XPUB)
        self.xsub_port = xsub_port or self._bind_ephemeral(self._xsub, xsub_port)
        self.xpub_port = xpub_port or self._bind_ephemeral(self._xpub, xpub_port)
        if xsub_port:
            self._xsub.bind(f"tcp://{host}:{xsub_port}")
        if xpub_port:
            self._xpub.bind(f"tcp://{host}:{xpub_port}")
        self._task: Optional[asyncio.Task] = None

    def _bind_ephemeral(self, sock: zmq.Socket, port: int) -> int:
        return sock.bind_to_random_port(f"tcp://{self.host}")

    @property
    def address(self) -> str:
        """Connection string clients take: host:xsub:xpub."""
        return f"{self.host}:{self.xsub_port}:{self.xpub_port}"

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._forward(), name="event-broker"
            )
            logger.info("event broker on %s", self.address)

    async def _forward(self) -> None:
        # Bidirectional proxy: data XSUB→XPUB, subscriptions XPUB→XSUB.
        poller = zmq.asyncio.Poller()
        poller.register(self._xsub, zmq.POLLIN)
        poller.register(self._xpub, zmq.POLLIN)
        while True:
            events = dict(await poller.poll())
            if self._xsub in events:
                await self._xpub.send_multipart(await self._xsub.recv_multipart())
            if self._xpub in events:
                await self._xsub.send_multipart(await self._xpub.recv_multipart())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
        self._xsub.close(0)
        self._xpub.close(0)


class ZmqEventPlane:
    """EventPlane over a broker at ``host:xsub_port:xpub_port``."""

    def __init__(self, address: str) -> None:
        host, xsub, xpub = address.rsplit(":", 2)
        self._ctx = zmq.asyncio.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        self._pub.connect(f"tcp://{host}:{xsub}")
        self._sub_addr = f"tcp://{host}:{xpub}"
        self._subs: List[Tuple[str, Subscription, zmq.Socket, asyncio.Task]] = []

    async def publish(self, topic: str, payload: Any) -> None:
        await self._pub.send_multipart(
            [topic.encode(), msgpack.packb(payload, use_bin_type=True)]
        )

    def subscribe(self, topic: str) -> Subscription:
        sock = self._ctx.socket(zmq.SUB)
        sock.connect(self._sub_addr)
        prefix = topic[:-1] if topic.endswith(".>") else topic
        sock.setsockopt(zmq.SUBSCRIBE, prefix.encode())
        queue: asyncio.Queue = asyncio.Queue()

        sub = Subscription(topic, queue, on_close=lambda s: self._close_sub(s))

        async def pump() -> None:
            try:
                while True:
                    raw_topic, raw_payload = await sock.recv_multipart()
                    t = raw_topic.decode()
                    if topic_matches(topic, t):
                        queue.put_nowait((t, msgpack.unpackb(
                            raw_payload, raw=False, strict_map_key=False
                        )))
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("zmq subscription pump died (%s)", topic)
                queue.put_nowait(_SUB_CLOSED)

        task = asyncio.get_running_loop().create_task(pump(), name=f"zmq-sub:{topic}")
        self._subs.append((topic, sub, sock, task))
        return sub

    def _close_sub(self, sub: Subscription) -> None:
        for i, (topic, s, sock, task) in enumerate(self._subs):
            if s is sub:
                task.cancel()
                sock.close(0)
                sub._queue.put_nowait(_SUB_CLOSED)
                del self._subs[i]
                return

    async def close(self) -> None:
        for _, sub, sock, task in list(self._subs):
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
            sock.close(0)
        self._subs.clear()
        self._pub.close(0)
