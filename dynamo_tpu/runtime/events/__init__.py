"""Event plane: pub/sub for KV events and load metrics.

Reference parity: lib/runtime/src/transports/event_plane/ (NATS default,
brokerless ZMQ alternative; framed msgpack codec). Backends here:

  - ``MemoryEventPlane`` — process-local shared bus for tests/process-local
    runtimes.
  - ``ZmqEventPlane`` (runtime/events/zmq_plane.py) — brokerless pub/sub over
    ZMQ, the cross-process default (the environment has pyzmq but no NATS).

Topics are dotted strings; subscriptions match exact topics or prefixes with a
trailing ``.>`` wildcard (NATS-style).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Dict, List, Optional, Protocol, Tuple


def topic_matches(pattern: str, topic: str) -> bool:
    if pattern == topic:
        return True
    if pattern.endswith(".>"):
        return topic.startswith(pattern[:-1]) or topic == pattern[:-2]
    return False


class EventPlane(Protocol):
    async def publish(self, topic: str, payload: Any) -> None: ...
    def subscribe(self, topic: str) -> "Subscription": ...
    async def close(self) -> None: ...


_SUB_CLOSED = object()


class Subscription:
    """Async iterator of (topic, payload) pairs."""

    def __init__(self, pattern: str, queue: "asyncio.Queue", on_close=None) -> None:
        self.pattern = pattern
        self._queue = queue
        self._closed = False
        self._on_close = on_close

    def __aiter__(self) -> "Subscription":
        return self

    async def __anext__(self) -> Tuple[str, Any]:
        if self._closed:
            raise StopAsyncIteration
        item = await self._queue.get()
        if item is _SUB_CLOSED:
            self._closed = True
            raise StopAsyncIteration
        return item

    async def get(self, timeout: Optional[float] = None) -> Tuple[str, Any]:
        if timeout is None:
            return await self.__anext__()
        return await asyncio.wait_for(self.__anext__(), timeout=timeout)

    async def aclose(self) -> None:
        if not self._closed:
            self._closed = True
            if self._on_close is not None:
                self._on_close(self)

    async def __aenter__(self) -> "Subscription":
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.aclose()


class MemoryEventPlane:
    _buses: Dict[str, "MemoryEventPlane"] = {}

    def __init__(self) -> None:
        self._subs: List[Tuple[str, asyncio.Queue, asyncio.AbstractEventLoop]] = []

    @classmethod
    def shared(cls, bus: str = "default") -> "MemoryEventPlane":
        if bus not in cls._buses:
            cls._buses[bus] = cls()
        return cls._buses[bus]

    @classmethod
    def reset(cls, bus: Optional[str] = None) -> None:
        if bus is None:
            cls._buses.clear()
        else:
            cls._buses.pop(bus, None)

    async def publish(self, topic: str, payload: Any) -> None:
        for pattern, queue, loop in list(self._subs):
            if topic_matches(pattern, topic):
                try:
                    loop.call_soon_threadsafe(queue.put_nowait, (topic, payload))
                except RuntimeError:
                    self._subs = [s for s in self._subs if s[1] is not queue]

    def subscribe(self, topic: str) -> Subscription:
        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_running_loop()
        self._subs.append((topic, queue, loop))

        def _close(sub: Subscription) -> None:
            self._subs = [s for s in self._subs if s[1] is not queue]
            try:
                loop.call_soon_threadsafe(queue.put_nowait, _SUB_CLOSED)
            except RuntimeError:
                pass

        return Subscription(topic, queue, on_close=_close)

    async def close(self) -> None:
        for _, queue, loop in list(self._subs):
            try:
                loop.call_soon_threadsafe(queue.put_nowait, _SUB_CLOSED)
            except RuntimeError:
                pass
        self._subs.clear()
