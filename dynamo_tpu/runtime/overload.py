"""Overload armor: deadline-aware admission control + graceful brownout.

The serving stack is SLA-driven end to end (planner sizing, KV-aware
routing, disagg placement) — but SLAs are only meaningful if the system
refuses work it cannot serve inside them. FlowKV's observation (PAPERS.md)
is that disaggregated serving stays stable under pressure only when the
scheduler is load-aware; Nexus shows ITL isolation under mixed load is a
policy problem. Both presuppose an overload plane: without one, a
saturating burst queues unboundedly at the frontend, admits work whose
deadline has already expired, and blows every TTFT/ITL SLA at once —
for every client, not just the excess.

This module is that plane. One :class:`OverloadController` per frontend
owns three cooperating mechanisms:

  * **Bounded EDF admission.** In-flight streams are capped at
    ``max_concurrency``; excess waits in an earliest-deadline-first queue
    bounded by ``max_queue_depth``. Requests without a deadline sort after
    every deadline-carrying request, FIFO among themselves. A full queue
    or a predicted queue delay (EWMA service time × queue position ÷
    concurrency — fed by the same observations the PR 1 engine-step
    families aggregate) beyond ``max_queue_delay_s`` sheds with a typed
    429 + ``Retry-After`` instead of queueing forever.
  * **Deadline enforcement.** A request whose ``Context`` deadline is
    already past sheds immediately (never admitted); a queued request
    whose budget expires mid-wait is shed at that moment — before any
    prefill work — and a granted waiter is re-checked at grant time, so
    expired work can never reach an engine through this gate.
  * **Brownout state machine.** ``healthy → brownout → shed`` driven by
    observed p50 ITL vs the SLA and (optionally) KV-pool occupancy, with
    consecutive-evaluation hysteresis in BOTH directions so a single
    noisy sample can neither trip nor clear a state (no flapping).
    Brownout clamps ``max_tokens`` (``clamp_max_tokens``) and disables
    speculative decode (``spec_enabled`` / the transition callbacks, wired
    to ``JaxEngine.set_spec_suspended``); shed refuses new admissions with
    503 while admitted streams run to completion.

Every shed, admission, and state transition lands on the ``"overload"``
flight ring and the lint-pinned ``ALL_OVERLOAD`` metric families, and the
``overload.admit`` fault seam (runtime/fault_names.py) lets a chaos plan
expire a specific queued request's budget DETERMINISTICALLY — the
saturation tests replay bit-identically instead of racing wall clocks.

Process-wide ``note_activity`` counters (``sheds``,
``brownout_transitions``, ``deadline_expired``) extend the PR 7
zero-spurious-activation contract: bench legs record them, so a chaos-free
under-capacity run PROVES the overload plane sat idle.
"""

from __future__ import annotations

import asyncio
import collections
import heapq
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import fault_point, note_activity
from dynamo_tpu.runtime.metrics_core import MetricsRegistry
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Brownout states, ordered by severity. Gauge values ARE the wire form
# (dashboards alert on state >= 1).
HEALTHY = 0
BROWNOUT = 1
SHED = 2

STATE_NAMES = {HEALTHY: "healthy", BROWNOUT: "brownout", SHED: "shed"}


@dataclass(frozen=True)
class OverloadConfig:
    """Admission + brownout knobs (docs/design_docs/overload_control.md
    has the full table). Defaults are deliberately permissive: the caps
    exist but only bite under genuine saturation, and the brownout
    machine is inert until an ITL SLA (or occupancy source) is set."""

    # -- admission ---------------------------------------------------------
    # Streams generating concurrently; excess queues.
    max_concurrency: int = 256
    # Waiters beyond the concurrency cap; the (N+1)th sheds queue_full.
    max_queue_depth: int = 1024
    # Shed when the PREDICTED wait (EWMA service time × position ÷
    # concurrency) exceeds this — a queue that cannot drain inside the
    # bound is already failing its SLA, admitting more only spreads it.
    max_queue_delay_s: float = 30.0
    # Deadline stamped on requests that carry none (None = unbounded).
    default_deadline_s: Optional[float] = None
    # Retry-After floor on shed responses (predicted drain time wins
    # when larger).
    retry_after_s: float = 1.0
    # EWMA weight for observed per-request service seconds.
    service_ewma_alpha: float = 0.25
    # -- brownout ----------------------------------------------------------
    # p50 ITL SLA driving the state machine; None = brownout disabled
    # (admission caps still enforce).
    itl_sla_s: Optional[float] = None
    # Escalate brownout → shed when p50 ITL exceeds factor × SLA.
    shed_itl_factor: float = 3.0
    # Sliding ITL sample window for the p50, and how many samples the
    # p50 needs before it is trusted at all.
    itl_window: int = 128
    min_itl_samples: int = 16
    # Samples older than this are dropped before every p50 — otherwise a
    # SHED controller that stops admitting (so no tokens flow and no new
    # samples arrive) would re-read its congested-era window forever and
    # never gather recovery evidence: a permanent lockout.
    itl_sample_ttl_s: float = 60.0
    # Hysteresis time floor: evaluations closer together than this don't
    # advance the streaks, so brownout_after/recover_after denominate
    # TIME (≥ brownout_after × this much evidence), not request rate — at
    # 1000 rps per-admission evaluation would otherwise turn "3
    # consecutive evaluations" into 3 ms of evidence and flap at
    # millisecond granularity.
    min_eval_interval_s: float = 0.25
    # KV-pool occupancy triggers (require an occupancy_source).
    occupancy_high: float = 0.95
    occupancy_critical: float = 0.995
    # Hysteresis: consecutive breached evaluations before stepping UP one
    # state, consecutive healthy evaluations before stepping DOWN one —
    # recovery resets the streak per step, so shed → healthy takes
    # 2 × recover_after clean evaluations (no flapping).
    brownout_after: int = 3
    recover_after: int = 6
    # max_tokens clamp applied while state >= brownout.
    brownout_max_tokens: int = 256


def config_from_env() -> OverloadConfig:
    """OverloadConfig from the DYN_TPU_OVERLOAD_* env knobs (config.py)
    — what the frontend entrypoint arms by default."""
    from dynamo_tpu import config as cfg

    itl_sla_ms = cfg.OVERLOAD_ITL_SLA_MS.get()
    default_deadline = cfg.OVERLOAD_DEFAULT_DEADLINE_S.get()
    return OverloadConfig(
        max_concurrency=cfg.OVERLOAD_MAX_CONCURRENCY.get(),
        max_queue_depth=cfg.OVERLOAD_MAX_QUEUE.get(),
        max_queue_delay_s=cfg.OVERLOAD_MAX_QUEUE_DELAY_S.get(),
        default_deadline_s=default_deadline or None,
        itl_sla_s=(itl_sla_ms / 1000.0) if itl_sla_ms > 0 else None,
        brownout_max_tokens=cfg.OVERLOAD_BROWNOUT_MAX_TOKENS.get(),
    )


class OverloadShedError(Exception):
    """One admission refused. ``reason`` is the shed_total label
    (queue_full | predicted_delay | deadline_expired | brownout_shed),
    ``status`` the HTTP mapping (429 load shed, 503 brownout shed, 504
    dead-on-arrival deadline), ``retry_after`` the drain estimate the
    Retry-After header carries (None on deadline sheds — retrying an
    expired budget is the client's call, not a pacing hint)."""

    def __init__(
        self, reason: str, status: int, retry_after: Optional[float] = None
    ) -> None:
        super().__init__(f"request shed ({reason})")
        self.reason = reason
        self.status = status
        self.retry_after = retry_after


@dataclass
class AdmissionTicket:
    """One granted admission; hand it back to ``release``."""

    request_id: str
    t_enqueue: float
    t_admit: float = 0.0
    released: bool = False

    @property
    def queue_delay_s(self) -> float:
        return max(0.0, self.t_admit - self.t_enqueue)


@dataclass
class _Waiter:
    """One queued admission. ``key`` orders the EDF heap: (deadline or
    +inf, arrival seq) — deadline-carrying requests first, FIFO among
    equals. ``abandoned`` marks a waiter whose admit() call already
    resolved (shed/cancelled); the heap entry is skipped lazily at grant
    (cheaper than heap surgery on every shed)."""

    deadline: Optional[float]
    seq: int
    context: Context
    future: "asyncio.Future[float]"  # resolves to t_admit
    t_enqueue: float = 0.0
    abandoned: bool = False

    @property
    def key(self):
        return (self.deadline if self.deadline is not None else float("inf"), self.seq)

    def __lt__(self, other: "_Waiter") -> bool:
        return self.key < other.key


class OverloadMetrics:
    """Canonical overload families (runtime/metric_names.py ALL_OVERLOAD)
    on a private registry; ``render`` plugs into the system server's
    ``register_metrics`` seam like every other subsystem."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.state = self.registry.gauge(
            mn.OVERLOAD_STATE,
            "Brownout state machine: 0 healthy, 1 brownout (max_tokens "
            "clamped, speculative decode off), 2 shed (new admissions "
            "refused 503)",
        )
        self.transitions = self.registry.counter(
            mn.OVERLOAD_TRANSITIONS_TOTAL,
            "Brownout state transitions, labeled by the state entered",
            ["to"],
        )
        self.shed = self.registry.counter(
            mn.OVERLOAD_SHED_TOTAL,
            "Admissions refused, by reason (queue_full | predicted_delay "
            "| deadline_expired | brownout_shed). Every shed is a typed "
            "429/503/504 the client saw — nonzero under nominal load is "
            "an incident",
            ["reason"],
        )
        self.admitted = self.registry.counter(
            mn.OVERLOAD_ADMITTED_TOTAL,
            "Admissions granted (immediately or after queueing)",
        )
        self.queue_depth = self.registry.gauge(
            mn.OVERLOAD_QUEUE_DEPTH,
            "Requests waiting in the EDF admission queue right now",
        )
        self.queue_delay = self.registry.histogram(
            mn.OVERLOAD_QUEUE_DELAY,
            "Seconds a granted request waited in the admission queue",
        )
        self.deadline_expired = self.registry.counter(
            mn.OVERLOAD_DEADLINE_EXPIRED_TOTAL,
            "Requests whose deadline expired before admission (arrived "
            "dead or expired mid-queue) — shed before any prefill work",
        )

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class OverloadController:
    """The frontend's overload plane: bounded EDF admission + brownout.

    Threading contract: every method runs on the frontend's event loop
    (the same single-writer discipline as the other flight rings — DYN005
    owner \"overload\"). ``clock`` is injectable so the brownout tests
    drive the hysteresis with a fake clock; asyncio waits still use loop
    time (only the state machine's decisions are clocked).
    """

    def __init__(
        self,
        config: Optional[OverloadConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        occupancy_source: Optional[Callable[[], Optional[float]]] = None,
    ) -> None:
        self.config = config or OverloadConfig()
        self._clock = clock
        # () -> current KV-pool occupancy in [0, 1] (None = unknown);
        # worker-side deployments wire engine stats here, frontend-only
        # deployments leave it unset and brownout runs on ITL alone.
        self._occupancy_source = occupancy_source
        self._state = HEALTHY
        self._breach_streak = 0
        self._critical_streak = 0
        self._ok_streak = 0
        # (observed-at, itl_s) pairs; maxlen bounds memory, the TTL prune
        # in _itl_p50 bounds staleness.
        self._itl_samples: "collections.deque" = collections.deque(
            maxlen=self.config.itl_window
        )
        self._last_eval_at: Optional[float] = None
        self._active = 0
        self._heap: List[_Waiter] = []
        self._queued = 0  # live (non-abandoned) waiters — len(heap) lies
        self._seq = 0
        self._svc_ewma: Optional[float] = None  # observed service seconds
        self._transition_cbs: List[Callable[[int, int], None]] = []
        # Budget-squeeze rung (engines/tpu/tick_budget.py): levers
        # registered by worker wiring (JaxEngine.set_budget_pressure).
        # With levers present, the FIRST filled breach streak squeezes the
        # prefill budget instead of transitioning — brownout (and its
        # max_tokens clamp) needs a fresh filled streak on top of the
        # squeeze, so the cheapest lever always fires first. No levers =
        # the pre-budgeter ladder, unchanged.
        self._budget_levers: List[Callable[[bool], None]] = []
        self._budget_squeezed = False
        self.budget_squeezes = 0
        # Lifetime counters (bench + /debug snapshots; the metric
        # families are their scrapeable form).
        self.sheds: Dict[str, int] = {}
        self.admitted = 0
        self.transitions: Dict[str, int] = {}
        self.peak_queue_depth = 0
        self.flight = FlightRecorder("overload", capacity=512)
        self.metrics = OverloadMetrics()
        self.metrics.registry.on_render(self._refresh_gauges)

    # -- observability ------------------------------------------------------

    def _refresh_gauges(self) -> None:
        self.metrics.state.set(self._state)
        self.metrics.queue_depth.set(self._queued)

    def register_metrics(self, server: Any) -> None:
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)

    def snapshot(self) -> Dict[str, Any]:
        """Controller state for bench legs / debug surfaces."""
        return {
            "state": STATE_NAMES[self._state],
            "active": self._active,
            "queue_depth": self._queued,
            "peak_queue_depth": self.peak_queue_depth,
            "admitted": self.admitted,
            "sheds": dict(self.sheds),
            "deadline_expired": self.sheds.get("deadline_expired", 0),
            "transitions": dict(self.transitions),
            "budget_squeezed": self._budget_squeezed,
            "budget_squeezes": self.budget_squeezes,
            "itl_p50_ms": (
                round(1000 * p50, 3)
                if (p50 := self._itl_p50()) is not None
                else None
            ),
            "service_ewma_ms": (
                round(1000 * self._svc_ewma, 3)
                if self._svc_ewma is not None
                else None
            ),
        }

    # -- state machine ------------------------------------------------------

    @property
    def state(self) -> int:
        return self._state

    def on_transition(self, cb: Callable[[int, int], None]) -> None:
        """``cb(old_state, new_state)`` on every transition — the seam
        worker wiring uses to suspend speculative decode on brownout."""
        self._transition_cbs.append(cb)

    def on_budget_pressure(self, cb: Callable[[bool], None]) -> None:
        """Register a budget-squeeze lever: ``cb(True)`` pins the
        engine's per-tick prefill budget at its starvation floor,
        ``cb(False)`` releases it back to the control law. Registering a
        lever INSERTS the rung below brownout: the squeeze fires one
        filled breach streak before any max_tokens clamp, and releases
        one filled recovery streak after every state stepped down —
        first lever pulled, last lever released."""
        self._budget_levers.append(cb)

    def observe_itl(self, itl_s: float) -> None:
        """One inter-token latency observation (the frontend's
        RequestTimer feeds this from the same deltas the PR 1 ITL
        histogram observes). Sliding window, O(1) per token."""
        self._itl_samples.append((self._clock(), itl_s))

    def _itl_p50(self) -> Optional[float]:
        # Age out stale samples FIRST: once traffic stops (e.g. the shed
        # state refusing admissions), the congested-era window must decay
        # to "unknown" rather than testify against recovery forever.
        horizon = self._clock() - self.config.itl_sample_ttl_s
        while self._itl_samples and self._itl_samples[0][0] < horizon:
            self._itl_samples.popleft()
        if len(self._itl_samples) < self.config.min_itl_samples:
            return None
        s = sorted(v for _, v in self._itl_samples)
        return s[len(s) // 2]

    def _occupancy(self) -> Optional[float]:
        if self._occupancy_source is None:
            return None
        try:
            return self._occupancy_source()
        except Exception:
            logger.exception("overload occupancy source failed")
            return None

    def evaluate(self) -> int:
        """Run one state-machine evaluation; returns the (possibly new)
        state. Called on every admission and by the worker's load-report
        cadence loop. Calls closer together than min_eval_interval_s are
        no-ops (state returned, streaks untouched): a hysteresis step is
        a unit of TIME, not a unit of request rate."""
        cfg = self.config
        now = self._clock()
        if (
            self._last_eval_at is not None
            and now - self._last_eval_at < cfg.min_eval_interval_s
        ):
            return self._state
        self._last_eval_at = now
        p50 = self._itl_p50() if cfg.itl_sla_s is not None else None
        occ = self._occupancy()
        breach = False
        critical = False
        if p50 is not None and cfg.itl_sla_s is not None:
            breach = p50 > cfg.itl_sla_s
            critical = p50 > cfg.shed_itl_factor * cfg.itl_sla_s
        if occ is not None:
            breach = breach or occ >= cfg.occupancy_high
            critical = critical or occ >= cfg.occupancy_critical
        if breach:
            self._breach_streak += 1
            # Escalation keeps its own streak: brownout → shed needs
            # brownout_after CONSECUTIVE critical evaluations, not one
            # noisy critical sample on top of an old breach streak.
            self._critical_streak = self._critical_streak + 1 if critical else 0
            self._ok_streak = 0
        else:
            self._ok_streak += 1
            self._breach_streak = 0
            self._critical_streak = 0
        if self._state == HEALTHY and self._breach_streak >= cfg.brownout_after:
            if self._budget_levers and not self._budget_squeezed:
                # First rung: shrink the prefill budget BEFORE clamping
                # max_tokens or shedding. Brownout needs a FRESH filled
                # streak on top of the squeeze — the flight ring's event
                # order (budget_squeeze, then state healthy→brownout)
                # proves the lever ordering.
                self._squeeze_budget(True, p50, occ)
            else:
                self._transition(BROWNOUT, p50, occ)
            self._breach_streak = 0
            self._critical_streak = 0
        elif (
            self._state == BROWNOUT
            and self._critical_streak >= cfg.brownout_after
        ):
            self._transition(SHED, p50, occ)
            self._breach_streak = 0
            self._critical_streak = 0
        elif self._ok_streak >= cfg.recover_after and (
            self._state != HEALTHY or self._budget_squeezed
        ):
            # Step DOWN one state per filled recovery streak: shed →
            # brownout → healthy needs two clean streaks, so recovery
            # re-arms gradually instead of slamming the floodgates open.
            # The budget squeeze outlives every state step-down — it was
            # the first lever pulled, so it is the LAST one released.
            if self._state != HEALTHY:
                self._transition(self._state - 1, p50, occ)
            else:
                self._squeeze_budget(False, p50, occ)
            self._ok_streak = 0
        return self._state

    def _squeeze_budget(
        self, on: bool, p50: Optional[float], occ: Optional[float]
    ) -> None:
        self._budget_squeezed = on
        if on:
            self.budget_squeezes += 1
        self.flight.record(
            "budget_squeeze" if on else "budget_release",
            itl_p50_ms=round(1000 * p50, 3) if p50 is not None else None,
            occupancy=round(occ, 4) if occ is not None else None,
        )
        logger.warning(
            "overload budget %s (p50 ITL %s, occupancy %s)",
            "squeeze" if on else "release",
            f"{1000 * p50:.1f}ms" if p50 is not None else "n/a",
            f"{occ:.3f}" if occ is not None else "n/a",
        )
        for cb in self._budget_levers:
            try:
                cb(on)
            except Exception:
                logger.exception("overload budget lever failed")

    def _transition(self, new_state: int, p50: Optional[float], occ: Optional[float]) -> None:
        old, self._state = self._state, new_state
        name = STATE_NAMES[new_state]
        self.transitions[name] = self.transitions.get(name, 0) + 1
        self.metrics.transitions.inc(to=name)
        if new_state > HEALTHY:
            note_activity("brownout_transitions")
        self.flight.record(
            "state",
            frm=STATE_NAMES[old],
            to=name,
            itl_p50_ms=round(1000 * p50, 3) if p50 is not None else None,
            occupancy=round(occ, 4) if occ is not None else None,
        )
        logger.warning(
            "overload state %s -> %s (p50 ITL %s, occupancy %s)",
            STATE_NAMES[old], name,
            f"{1000 * p50:.1f}ms" if p50 is not None else "n/a",
            f"{occ:.3f}" if occ is not None else "n/a",
        )
        for cb in self._transition_cbs:
            try:
                cb(old, new_state)
            except Exception:
                logger.exception("overload transition callback failed")

    # -- brownout actions ---------------------------------------------------

    def clamp_max_tokens(self, requested: Optional[int]) -> Optional[int]:
        """Brownout's output clamp: while degraded, no request may ask
        for more than ``brownout_max_tokens``; healthy passes through.
        Non-integer junk also passes through — downstream validation owns
        rejecting it with a 400 (a clamp must never be the thing that
        500s a request, or leaks its admission slot by raising)."""
        if self._state < BROWNOUT:
            return requested
        cap = self.config.brownout_max_tokens
        if requested is None:
            return cap
        if isinstance(requested, bool) or not isinstance(requested, int):
            return requested
        return min(requested, cap)

    def spec_enabled(self) -> bool:
        """Speculative decode is a throughput-for-latency gamble that
        loses under pressure (rejected proposals burn decode ticks) —
        off in every degraded state."""
        return self._state == HEALTHY

    # -- admission ----------------------------------------------------------

    def apply_default_deadline(self, context: Context) -> None:
        """Stamp ``default_deadline_s`` on a deadline-less context (the
        frontend calls this after header parsing so a client-supplied
        deadline always wins)."""
        if (
            self.config.default_deadline_s is not None
            and context.deadline is None
        ):
            context.set_deadline(
                time.monotonic() + self.config.default_deadline_s
            )

    def _shed(
        self, reason: str, status: int, request_id: str,
        retry_after: Optional[float] = None,
    ) -> OverloadShedError:
        self.sheds[reason] = self.sheds.get(reason, 0) + 1
        self.metrics.shed.inc(reason=reason)
        note_activity("sheds")
        if reason == "deadline_expired":
            self.metrics.deadline_expired.inc()
            note_activity("deadline_expired")
        self.flight.record(
            "shed", request_id=request_id, reason=reason,
            queue_depth=self._queued, active=self._active,
        )
        return OverloadShedError(reason, status, retry_after)

    def _predicted_queue_delay(self, position: int) -> Optional[float]:
        """Expected wait at queue ``position`` (0-based): every request
        ahead plus this one must each clear a service slot, at the EWMA
        service time over ``max_concurrency`` parallel servers. None
        until a service time has been observed (never shed on a guess)."""
        if self._svc_ewma is None:
            return None
        return (
            (position + 1)
            * self._svc_ewma
            / max(self.config.max_concurrency, 1)
        )

    def _retry_after(self, predicted: Optional[float]) -> float:
        return max(self.config.retry_after_s, predicted or 0.0)

    async def admit(
        self, context: Context, *, request_id: Optional[str] = None
    ) -> AdmissionTicket:
        """Admit one request or raise :class:`OverloadShedError`.

        The ``overload.admit`` fault seam fires once per attempt, BEFORE
        the queue wait: a chaos rule injecting a timeout at hit N expires
        exactly the Nth request's queue budget — the deterministic
        mid-queue-expiry schedule the saturation tests replay.
        """
        rid = request_id or context.id
        self.evaluate()
        if self._state >= SHED:
            raise self._shed(
                "brownout_shed", 503, rid,
                self._retry_after(self._predicted_queue_delay(self._queued)),
            )
        remaining = context.time_remaining()
        if remaining is not None and remaining <= 0:
            raise self._shed("deadline_expired", 504, rid)
        now = self._clock()
        if self._active < self.config.max_concurrency and self._queued == 0:
            self._active += 1
            self.admitted += 1
            self.metrics.admitted.inc()
            self.metrics.queue_delay.observe(0.0)
            self.flight.record("admit", request_id=rid, queued_s=0.0)
            return AdmissionTicket(request_id=rid, t_enqueue=now, t_admit=now)
        if self._queued >= self.config.max_queue_depth:
            raise self._shed(
                "queue_full", 429, rid,
                self._retry_after(
                    self._predicted_queue_delay(self._queued)
                ),
            )
        predicted = self._predicted_queue_delay(self._queued)
        budget = self.config.max_queue_delay_s
        if remaining is not None:
            budget = min(budget, remaining)
        if predicted is not None and predicted > budget:
            raise self._shed(
                "predicted_delay", 429, rid, self._retry_after(predicted)
            )
        waiter = _Waiter(
            deadline=context.deadline,
            seq=self._seq,
            context=context,
            future=asyncio.get_running_loop().create_future(),
            t_enqueue=now,
        )
        self._seq += 1
        heapq.heappush(self._heap, waiter)
        self._queued += 1
        self.peak_queue_depth = max(self.peak_queue_depth, self._queued)
        # Abandoned waiters (deadline timeouts, disconnects) are skipped
        # lazily at grant — but grants only happen on release, and long
        # streams can hold every slot for minutes while short-deadline
        # arrivals churn the heap. Compact when dead entries dominate so
        # the heap is bounded by LIVE waiters, not arrival history.
        if len(self._heap) > 64 and len(self._heap) > 2 * self._queued:
            self._heap = [
                w for w in self._heap
                if not w.abandoned and not w.future.done()
            ]
            heapq.heapify(self._heap)
        self.flight.record(
            "enqueue", request_id=rid, queue_depth=self._queued,
            deadline_in_s=(
                round(remaining, 3) if remaining is not None else None
            ),
        )
        try:
            # Chaos seam (see docstring): an injected timeout here is the
            # queued request's budget expiring, deterministically.
            fault_point(fault_names.OVERLOAD_ADMIT, request_id=rid)
            if remaining is not None:
                t_admit = await asyncio.wait_for(waiter.future, remaining)
            else:
                t_admit = await waiter.future
        except (TimeoutError, asyncio.TimeoutError):
            # A cancelled future is the NORMAL timeout shape (wait_for
            # cancels it before raising): never granted, still queued. A
            # RESOLVED future means the grant raced the expiry (3.12+
            # wait_for can raise over a completed future) — decrementing
            # _queued again there would double-count and leak the _active
            # slot _grant_next just took.
            if not waiter.future.done() or waiter.future.cancelled():
                waiter.abandoned = True
                self._queued -= 1
                raise self._shed("deadline_expired", 504, rid) from None
            exc = waiter.future.exception()
            if exc is not None:
                # Grant-time shed raced the timeout: _grant_next already
                # dequeued and counted it.
                raise exc
            # A real grant raced the expiry: the budget is spent either
            # way — return the capacity, then shed.
            self._active -= 1
            self._grant_next()
            raise self._shed("deadline_expired", 504, rid) from None
        except OverloadShedError:
            # Grant-time shed: _grant_next already dequeued and counted it.
            raise
        except BaseException:
            # Cancellation (client gone mid-queue) or an injected
            # error-kind fault: vacate the slot either way. Not a shed —
            # grant skips abandoned waiters lazily. A CANCELLED future is
            # the normal cancellation shape (the task machinery cancels
            # the awaited future): never granted, still queued.
            if not waiter.future.done() or waiter.future.cancelled():
                waiter.abandoned = True
                self._queued -= 1
            elif waiter.future.exception() is None:
                # A real GRANT raced the failure: give the capacity back.
                # (A grant-time shed exception on the future took no slot
                # and was already dequeued/counted by _grant_next; the
                # exception() call above also marks it retrieved.)
                self._active -= 1
                self._grant_next()
            raise
        ticket = AdmissionTicket(
            request_id=rid, t_enqueue=waiter.t_enqueue, t_admit=t_admit
        )
        self.metrics.queue_delay.observe(ticket.queue_delay_s)
        self.flight.record(
            "admit", request_id=rid,
            queued_s=round(ticket.queue_delay_s, 4),
        )
        return ticket

    def _grant_next(self) -> None:
        """Hand freed capacity to the earliest-deadline waiter. Waiters
        whose deadline already passed are shed HERE — a grant is the last
        gate an expired request could slip through."""
        while self._active < self.config.max_concurrency and self._heap:
            waiter = heapq.heappop(self._heap)
            if waiter.abandoned or waiter.future.done():
                continue
            self._queued -= 1
            now = self._clock()
            rem = waiter.context.time_remaining()
            if rem is not None and rem <= 0:
                waiter.future.set_exception(
                    self._shed(
                        "deadline_expired", 504, waiter.context.id
                    )
                )
                continue
            self._active += 1
            self.admitted += 1
            self.metrics.admitted.inc()
            waiter.future.set_result(now)

    def release(self, ticket: AdmissionTicket, *, ok: bool = True) -> None:
        """Return one admission slot; feeds the service-time EWMA the
        predicted-delay shed uses (successful completions only — an
        early error says nothing about how long real service takes)."""
        if ticket.released:
            return
        ticket.released = True
        self._active = max(0, self._active - 1)
        if ok:
            service_s = max(0.0, self._clock() - ticket.t_admit)
            alpha = self.config.service_ewma_alpha
            self._svc_ewma = (
                service_s if self._svc_ewma is None
                else alpha * service_s + (1 - alpha) * self._svc_ewma
            )
        self._grant_next()
