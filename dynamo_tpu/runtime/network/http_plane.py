"""HTTP request plane: alternative transport for router→worker streaming.

Reference parity: lib/runtime/src/pipeline/network/egress/http_router.rs —
the reference offers an HTTP/2 egress next to the default raw-TCP plane for
environments where plain sockets don't traverse (service meshes, L7-only
networks). Here: aiohttp chunked streaming; one POST per request stream.

Wire format: POST /stream, headers carry the instance key and context id,
the body is the msgpack request; the response is a chunked stream of
length-prefixed msgpack frames `(kind, payload)` with kind ∈
{"item", "end", "err"}. Cancellation is connection close (the HTTP-native
signal — ref disconnect.rs), which the server maps to context cancellation
exactly like the TCP plane's cancel frame.

Select with DYN_TPU_REQUEST_PLANE=http.
"""

from __future__ import annotations

import asyncio
import os
import struct
import time
from typing import Any, AsyncIterator, Dict, Optional, Tuple

import msgpack
import aiohttp
from aiohttp import ClientSession, ClientTimeout, web

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.network.codec import _default as _msgpack_default
from dynamo_tpu.runtime.network.tcp import StreamDisconnectedError
from dynamo_tpu.runtime.tasks import TaskTracker
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_LEN = struct.Struct("!I")
MAX_FRAME = 256 * 1024 * 1024


def _pack_frame(kind: str, payload: Any) -> bytes:
    body = msgpack.packb(
        (kind, payload), default=_msgpack_default, use_bin_type=True
    )
    return _LEN.pack(len(body)) + body


class HttpRequestPlane:
    kind = "http"

    def __init__(self, host: Optional[str] = None, port: int = 0) -> None:
        self.host = host or os.environ.get("DYN_TCP_HOST", "127.0.0.1")
        self.port = port
        self._engines: Dict[str, Tuple[AsyncEngine, TaskTracker]] = {}
        self._runner: Optional[web.AppRunner] = None
        self._bound_port: Optional[int] = None
        self._session: Optional[ClientSession] = None

    # -- server side -------------------------------------------------------

    async def serve(
        self, instance: Any, engine: AsyncEngine, tracker: TaskTracker
    ) -> Dict[str, Any]:
        if self._runner is None:
            app = web.Application(client_max_size=MAX_FRAME)
            app.router.add_post("/stream", self._handle)
            # handler_cancellation: client disconnect cancels the handler —
            # the HTTP cancel signal must reach the engine promptly, not on
            # the next failed write (ref: disconnect.rs). shutdown_timeout
            # is short because graceful draining is the TaskTracker's job
            # (endpoint shutdown grace), not the transport's.
            self._runner = web.AppRunner(
                app, access_log=None, handler_cancellation=True,
                shutdown_timeout=0.25,
            )
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.host, self.port)
            await site.start()
            server = site._server  # noqa: SLF001
            self._bound_port = server.sockets[0].getsockname()[1]
            logger.info(
                "http request plane listening on %s:%s", self.host, self._bound_port
            )
        self._engines[instance.key] = (engine, tracker)
        return {
            "kind": "http",
            "host": self.host,
            "port": self._bound_port,
            "key": instance.key,
        }

    async def unserve(self, instance: Any) -> None:
        self._engines.pop(instance.key, None)

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        key = request.headers.get("X-Dynamo-Key", "")
        entry = self._engines.get(key)
        body = await request.read()
        payload = (
            msgpack.unpackb(body, raw=False, strict_map_key=False) if body else None
        )
        response = web.StreamResponse(
            headers={"Content-Type": "application/x-dynamo-stream"}
        )
        response.enable_chunked_encoding()
        await response.prepare(request)
        if entry is None:
            await response.write(
                _pack_frame("err", f"no such endpoint instance: {key}")
            )
            return response
        engine, tracker = entry
        # Deadline propagation (parity with the TCP plane's ctx envelope):
        # the header carries REMAINING seconds — monotonic clocks don't
        # cross hosts — re-anchored onto this host's clock.
        deadline_hdr = request.headers.get("X-Dynamo-Deadline-S")
        try:
            deadline_s = float(deadline_hdr) if deadline_hdr is not None else None
        except ValueError:
            deadline_s = None
        ctx = Context(
            id=request.headers.get("X-Request-Id") or None,
            baggage=_baggage_from(request.headers),
            deadline=(
                time.monotonic() + deadline_s
                if deadline_s is not None
                else None
            ),
        )
        try:
            if tracker.draining:
                await response.write(_pack_frame(
                    "err",
                    {"message": "endpoint draining; re-dispatch",
                     "kind": "draining"},
                ))
                return response
            from dynamo_tpu.utils.tracing import span

            with tracker.guard(), span("endpoint.serve", ctx, endpoint=key) as sp:
                n_items = 0
                async for item in engine.generate(payload, ctx):
                    await response.write(_pack_frame("item", item))
                    n_items += 1
                sp.attributes["items"] = n_items
            await response.write(_pack_frame("end", None))
        except asyncio.CancelledError:
            # aiohttp cancels the handler on client disconnect — the
            # HTTP-native cancellation signal.
            ctx.stop_generating(reason="client-disconnected")
            raise
        except (ConnectionResetError, BrokenPipeError):
            ctx.stop_generating(reason="connection-lost")
        except Exception as exc:
            logger.exception("http stream handler failed")
            try:
                # Typed err (parity with the TCP plane): connection/timeout
                # failures and drain refusals must stay migratable across
                # the wire.
                from dynamo_tpu.runtime.network.errors import err_kind

                await response.write(_pack_frame(
                    "err", {"message": repr(exc), "kind": err_kind(exc)}
                ))
            except (ConnectionError, RuntimeError):
                pass
        return response

    # -- client side -------------------------------------------------------

    def client_for(self, instance: Any) -> AsyncEngine:
        host = instance.transport["host"]
        port = instance.transport["port"]
        key = instance.transport.get("key", instance.key)
        return _HttpClientEngine(self, f"http://{host}:{port}/stream", key)

    def _client_session(self) -> ClientSession:
        if self._session is None or self._session.closed:
            self._session = ClientSession(
                timeout=ClientTimeout(total=None, sock_connect=10)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


def _baggage_from(headers) -> Dict[str, str]:
    out = {}
    raw = headers.get("X-Dynamo-Baggage")
    if raw:
        for part in raw.split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
    return out


class _HttpClientEngine:
    """AsyncEngine view of a remote instance over the HTTP plane."""

    def __init__(self, plane: HttpRequestPlane, url: str, key: str) -> None:
        self._plane = plane
        self._url = url
        self._key = key

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        session = self._plane._client_session()
        headers = {"X-Dynamo-Key": self._key}
        if context.id:
            headers["X-Request-Id"] = context.id
        if context.baggage:
            headers["X-Dynamo-Baggage"] = ",".join(
                f"{k}={v}" for k, v in context.baggage.items()
            )
        remaining = context.time_remaining()
        if remaining is not None:
            # Relative, not absolute: the worker re-anchors onto its own
            # monotonic clock (same contract as the TCP plane).
            headers["X-Dynamo-Deadline-S"] = f"{remaining:.6f}"
        body = msgpack.packb(request, default=_msgpack_default, use_bin_type=True)
        try:
            resp = await session.post(self._url, data=body, headers=headers)
        except (OSError, aiohttp.ClientError) as exc:
            raise StreamDisconnectedError(f"connect {self._url}: {exc}") from exc
        if resp.status != 200:
            # Our stream handler always answers 200 (errors ride in frames);
            # a non-200 is an aiohttp-level failure. 5xx = the worker is in
            # trouble → disconnect semantics (migration trigger); 4xx = this
            # request can never succeed anywhere.
            text = (await resp.text())[:200]
            resp.close()
            if resp.status >= 500:
                raise StreamDisconnectedError(
                    f"worker http error {resp.status}: {text}"
                )
            raise RuntimeError(f"http plane rejected request {resp.status}: {text}")

        async def watch_cancel() -> None:
            await context.wait_stopped()
            resp.close()  # connection close IS the cancel signal

        cancel_task = asyncio.get_running_loop().create_task(watch_cancel())
        buf = b""
        clean_end = False
        try:
            async for chunk in resp.content.iter_any():
                buf += chunk
                while len(buf) >= _LEN.size:
                    (n,) = _LEN.unpack(buf[: _LEN.size])
                    if n > MAX_FRAME:
                        raise ValueError(f"frame too large: {n}")
                    if len(buf) < _LEN.size + n:
                        break
                    frame = buf[_LEN.size : _LEN.size + n]
                    buf = buf[_LEN.size + n :]
                    kind, payload = msgpack.unpackb(
                        frame, raw=False, strict_map_key=False
                    )
                    if kind == "item":
                        yield payload
                    elif kind == "end":
                        clean_end = True
                        return
                    elif kind == "err":
                        from dynamo_tpu.runtime.network.errors import (
                            err_exception,
                        )

                        if isinstance(payload, dict):
                            raise err_exception(
                                payload.get("kind", "other"),
                                payload.get("message", "remote error"),
                            )
                        # Old peer: bare string payload.
                        raise RuntimeError(payload)
            # Stream ended without an "end" frame: the worker vanished.
            if not context.stopped:
                raise StreamDisconnectedError(
                    f"worker connection lost: {self._url}"
                )
        except (
            ConnectionError, asyncio.IncompleteReadError, aiohttp.ClientError
        ) as exc:
            if isinstance(exc, StreamDisconnectedError):
                raise
            if context.stopped:
                return  # we closed the connection ourselves (cancel)
            raise StreamDisconnectedError(
                f"worker connection lost: {self._url}: {exc}"
            ) from exc
        finally:
            cancel_task.cancel()
            if clean_end:
                # Release the connection back to the session pool for
                # keep-alive reuse (the stream is fully consumed up to the
                # chunked terminator); close() would force a fresh TCP
                # connect per request.
                resp.release()
            else:
                resp.close()
