"""Transport-agnostic typed-error classification for request planes.

Every request plane (tcp, http) ships stream failures as ``err`` frames
carrying a ``kind`` so TYPED remote failures re-raise as the matching
exception class on the client instead of a flat RuntimeError:
connection/timeout errors and drain refusals (WorkerDrainingError,
"endpoint draining") must stay MIGRATABLE across the wire, or the drain
ladder's typed-requeue rung dead-ends at the frontend. Old peers that
omit ``kind`` keep the RuntimeError behavior.

Shared here (not private to one plane) so the classification pair cannot
drift between transports.
"""

from __future__ import annotations

import asyncio


def err_kind(exc: BaseException) -> str:
    """Classify a server-side handler failure for the err frame's ``kind``
    (the client re-raises the matching type — migratability must survive
    the wire). Name-based where importing the class would cycle."""
    if type(exc).__name__ == "WorkerDrainingError":
        return "draining"
    if type(exc).__name__ == "NoInstancesError":
        return "no_instances"
    if type(exc).__name__ == "ToolCallParseError":
        # Tool-call parser BUG (parsers/jail.py): typed so an agent SDK
        # can distinguish a parse failure (retryable with tools off /
        # another dialect) from a transport death — and so the stream it
        # ends reads as a terminal typed frame, never a drop.
        return "tool_call_parse"
    if isinstance(exc, (TimeoutError, asyncio.TimeoutError)):
        return "timeout"
    if isinstance(exc, ConnectionError):
        return "connection"
    return "other"


def err_exception(kind: str, message: str) -> BaseException:
    """Client-side inverse of err_kind."""
    if kind == "draining":
        from dynamo_tpu.runtime.drain import WorkerDrainingError

        return WorkerDrainingError(message)
    if kind == "no_instances":
        from dynamo_tpu.runtime.component import NoInstancesError

        return NoInstancesError(message)
    if kind == "tool_call_parse":
        from dynamo_tpu.parsers.incremental import ToolCallParseError

        return ToolCallParseError(message)
    if kind == "timeout":
        return TimeoutError(message)
    if kind == "connection":
        return ConnectionError(message)
    return RuntimeError(message)
