"""Cross-process request plane transports.

Reference parity: lib/runtime/src/pipeline/network/ — the default raw-TCP
request plane (tcp/{server,client}.rs) with the two-part msgpack codec
(codec/two_part.rs) and a per-process shared ingress listener
(ingress/shared_tcp_endpoint.rs). HTTP/2 and NATS request planes are
alternatives in the reference; here TCP is the cross-process default and the
in-process LocalRequestPlane covers process-local mode.
"""

from dynamo_tpu.runtime.network.codec import (
    FrameReader,
    FrameWriter,
    pack_frame,
)
from dynamo_tpu.runtime.network.tcp import TcpRequestPlane

__all__ = ["FrameReader", "FrameWriter", "pack_frame", "TcpRequestPlane"]
