"""TCP request plane: multiplexed request/response streaming.

Reference parity: lib/runtime/src/pipeline/network/tcp/{server,client}.rs +
ingress/shared_tcp_endpoint.rs (one listener per process shared by every
served endpoint) + egress/push_router.rs client side. Frames use the
two-part codec; one connection multiplexes many request streams.

Frame headers:
  {"type": "req",    "stream": id, "key": instance_key, "ctx": {...}}  payload=request
  {"type": "cancel", "stream": id}                                     (client→server)
  {"type": "item",   "stream": id}  payload=response item              (server→client)
  {"type": "end",    "stream": id}                                     stream done
  {"type": "err",    "stream": id, "message": str, "kind": str}        stream failed

A dropped connection cancels every stream riding it — on the client side this
surfaces as StreamDisconnectedError, the trigger for request migration
(ref: migration.rs no-responder handling).

``err`` frames carry a ``kind`` so TYPED remote failures re-raise as the
matching exception class on the client instead of a flat RuntimeError:
connection/timeout errors and drain refusals (WorkerDrainingError,
"endpoint draining") must stay MIGRATABLE across the wire, or the drain
ladder's typed-requeue rung dead-ends at the frontend. Old peers that omit
``kind`` keep the RuntimeError behavior.

Incarnation fencing (runtime/liveness.py): every server→client frame is
stamped with the serving process's incarnation (``inc``). One stream's
frames must all carry ONE incarnation — a frame claiming a different one
(a zombie's late packets, or a restarted listener conflated with its
predecessor) is counted (``stale_incarnation_drops_total{seam="tcp"}``)
and dropped, never delivered. Old peers that omit ``inc`` skip the check.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import time
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.liveness import note_stale_drop, process_incarnation
from dynamo_tpu.runtime.network.codec import FrameReader, FrameWriter
from dynamo_tpu.runtime.network.errors import err_exception, err_kind
from dynamo_tpu.runtime.tasks import TaskTracker, reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

CANCEL_GRACE_S = 2.0  # cooperative-cancel window before hard task cancel


class StreamDisconnectedError(ConnectionError):
    """Worker connection died mid-stream (migration trigger)."""


class TcpRequestPlane:
    kind = "tcp"

    def __init__(self, host: Optional[str] = None, port: int = 0) -> None:
        self.host = host or os.environ.get("DYN_TCP_HOST", "127.0.0.1")
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._engines: Dict[str, Tuple[AsyncEngine, TaskTracker]] = {}
        self._bound_port: Optional[int] = None
        self._conns: Dict[Tuple[str, int], "_ClientConn"] = {}
        self._conn_lock: Optional[asyncio.Lock] = None
        self._ingress_writers: set = set()  # live server-side connections

    # -- server side -------------------------------------------------------

    async def serve(
        self, instance: Any, engine: AsyncEngine, tracker: TaskTracker
    ) -> Dict[str, Any]:
        if self._server is None:
            self._server = await asyncio.start_server(
                self._handle_conn, host=self.host, port=self.port
            )
            self._bound_port = self._server.sockets[0].getsockname()[1]
            logger.info("tcp request plane listening on %s:%s", self.host, self._bound_port)
        self._engines[instance.key] = (engine, tracker)
        return {
            "kind": "tcp",
            "host": self.host,
            "port": self._bound_port,
            "key": instance.key,
        }

    async def unserve(self, instance: Any) -> None:
        self._engines.pop(instance.key, None)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        fr = FrameReader(reader)
        fw = FrameWriter(writer)
        self._ingress_writers.add(writer)
        loop = asyncio.get_running_loop()
        streams: Dict[int, Tuple[asyncio.Task, Context]] = {}
        try:
            while True:
                frame = await fr.recv()
                if frame is None:
                    break
                header, payload = frame
                ftype = header.get("type")
                sid = header.get("stream")
                if ftype == "req":
                    ctx_info = header.get("ctx") or {}
                    # Deadline propagation: the wire carries REMAINING
                    # seconds (monotonic clocks don't cross hosts); the
                    # server re-anchors it so engine admission and the
                    # disagg pull timeouts see the client's real budget.
                    deadline_s = ctx_info.get("deadline_s")
                    ctx = Context(
                        id=ctx_info.get("id"),
                        baggage=ctx_info.get("baggage") or {},
                        deadline=(
                            time.monotonic() + float(deadline_s)
                            if deadline_s is not None
                            else None
                        ),
                    )
                    task = loop.create_task(
                        self._run_stream(fw, sid, header, payload, ctx),
                        name=f"tcp-ingress:{sid}",
                    )
                    streams[sid] = (task, ctx)
                    task.add_done_callback(lambda t, s=sid: streams.pop(s, None))
                elif ftype == "cancel":
                    entry = streams.get(sid)
                    if entry is not None:
                        task, ctx = entry
                        # Cooperative first (engines check ctx between decode
                        # steps); hard-cancel as a backstop for stuck handlers.
                        ctx.stop_generating(reason="client-cancelled")
                        loop.call_later(
                            CANCEL_GRACE_S,
                            lambda t=task: t.cancel() if not t.done() else None,
                        )
                else:
                    logger.warning("unknown frame type %r", ftype)
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            for task, ctx in list(streams.values()):
                ctx.stop_generating(reason="connection-closed")
                task.cancel()
            for task, _ in list(streams.values()):
                await reap_task(task, "ingress stream", logger)
            fw.close()
            self._ingress_writers.discard(writer)

    async def _run_stream(
        self,
        fw: FrameWriter,
        sid: int,
        header: Dict[str, Any],
        request: Any,
        ctx: Context,
    ) -> None:
        key = header.get("key", "")
        entry = self._engines.get(key)
        if entry is None:
            await fw.send({"type": "err", "stream": sid,
                           "message": f"no such endpoint instance: {key}"})
            return
        engine, tracker = entry
        # Incarnation stamp on every response envelope: the client fences
        # a stream to ONE serving incarnation, so a zombie's late frames
        # can never be conflated with a restarted worker's.
        inc = process_incarnation()
        try:
            if tracker.draining:
                await fw.send({
                    "type": "err", "stream": sid, "inc": inc,
                    "message": "endpoint draining; re-dispatch",
                    "kind": "draining",
                })
                return
            from dynamo_tpu.utils.tracing import span

            with tracker.guard(), span("endpoint.serve", ctx, endpoint=key) as sp:
                n_items = 0
                async for item in engine.generate(request, ctx):
                    await fw.send(
                        {"type": "item", "stream": sid, "inc": inc}, item
                    )
                    n_items += 1
                sp.attributes["items"] = n_items
            await fw.send({"type": "end", "stream": sid, "inc": inc})
        except asyncio.CancelledError:
            ctx.stop_generating(reason="client-cancelled")
            raise
        except (ConnectionResetError, BrokenPipeError):
            ctx.stop_generating(reason="connection-lost")
        except Exception as exc:
            logger.exception("stream %s handler failed", sid)
            with _suppress_conn():
                await fw.send({
                    "type": "err", "stream": sid, "inc": inc,
                    "message": repr(exc), "kind": err_kind(exc),
                })

    # -- client side -------------------------------------------------------

    def client_for(self, instance: Any) -> AsyncEngine:
        host = instance.transport["host"]
        port = instance.transport["port"]
        key = instance.transport.get("key", instance.key)
        return _TcpClientEngine(self, (host, port), key)

    async def _conn(self, addr: Tuple[str, int]) -> "_ClientConn":
        # Serialized: concurrent first requests must not each open a
        # connection (the loser's socket + pump task would leak).
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            conn = self._conns.get(addr)
            if conn is None or conn.closed:
                conn = _ClientConn(addr)
                await conn.connect()
                self._conns[addr] = conn
            return conn

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # wait_closed() (3.12 semantics) waits for every live connection,
            # not just the accept loop — close established ingress
            # connections or it never returns.
            for writer in list(self._ingress_writers):
                writer.close()
            await self._server.wait_closed()
            self._server = None
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()


class _ClientConn:
    """One pooled connection; demuxes response frames to stream queues."""

    def __init__(self, addr: Tuple[str, int]) -> None:
        self.addr = addr
        self._ids = itertools.count(1)
        self._queues: Dict[int, asyncio.Queue] = {}
        self._fw: Optional[FrameWriter] = None
        self._pump: Optional[asyncio.Task] = None
        self.closed = False

    async def connect(self) -> None:
        reader, writer = await asyncio.open_connection(*self.addr)
        self._fw = FrameWriter(writer)
        fr = FrameReader(reader)

        async def pump() -> None:
            try:
                while True:
                    frame = await fr.recv()
                    if frame is None:
                        break
                    # Chaos seam: a fault here models the connection dying
                    # mid-stream — the finally below fans out "disconnect"
                    # to every stream, surfacing StreamDisconnectedError
                    # (the migration trigger) exactly like a real RST.
                    fault_point(fault_names.NET_TCP_RECV)
                    header, payload = frame
                    q = self._queues.get(header.get("stream"))
                    if q is None:
                        continue
                    ftype = header.get("type")
                    inc = header.get("inc")
                    if ftype == "item":
                        q.put_nowait(("item", payload, inc))
                    elif ftype == "end":
                        q.put_nowait(("end", None, inc))
                    elif ftype == "err":
                        q.put_nowait((
                            "err",
                            (
                                header.get("message", "remote error"),
                                header.get("kind", "other"),
                            ),
                            inc,
                        ))
            finally:
                self.closed = True
                for q in self._queues.values():
                    q.put_nowait(("disconnect", None, None))

        self._pump = asyncio.get_running_loop().create_task(
            pump(), name=f"tcp-client-pump:{self.addr}"
        )

    def open_stream(self) -> Tuple[int, asyncio.Queue]:
        sid = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._queues[sid] = q
        return sid, q

    def close_stream(self, sid: int) -> None:
        self._queues.pop(sid, None)

    async def send(self, header: Any, payload: Any = None) -> None:
        assert self._fw is not None
        fault_point(fault_names.NET_TCP_SEND)
        await self._fw.send(header, payload)

    async def close(self) -> None:
        self.closed = True
        if self._fw is not None:
            self._fw.close()
        if self._pump is not None:
            self._pump.cancel()
            await reap_task(self._pump, "tcp client pump", logger)


class _TcpClientEngine:
    """AsyncEngine view of a remote instance over the TCP plane."""

    def __init__(self, plane: TcpRequestPlane, addr: Tuple[str, int], key: str) -> None:
        self._plane = plane
        self._addr = addr
        self._key = key

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        try:
            conn = await self._plane._conn(self._addr)
        except OSError as exc:
            raise StreamDisconnectedError(f"connect {self._addr}: {exc}") from exc
        sid, q = conn.open_stream()
        ctx_env: Dict[str, Any] = {
            "id": context.id, "baggage": context.baggage,
        }
        remaining = context.time_remaining()
        if remaining is not None:
            # Relative, not absolute: the receiving host re-anchors onto
            # its own monotonic clock.
            ctx_env["deadline_s"] = remaining
        await conn.send(
            {
                "type": "req",
                "stream": sid,
                "key": self._key,
                "ctx": ctx_env,
            },
            request,
        )

        async def watch_cancel() -> None:
            await context.wait_stopped()
            with _suppress_conn():
                await conn.send({"type": "cancel", "stream": sid})

        cancel_task = asyncio.get_running_loop().create_task(watch_cancel())
        stream_inc: Optional[int] = None
        try:
            while True:
                kind, payload, inc = await q.get()
                if inc is not None:
                    # Incarnation fence: the stream belongs to whichever
                    # incarnation answered FIRST; frames claiming another
                    # (a zombie's late packets) are counted and dropped —
                    # a restarted listener cannot continue a stream it
                    # never held.
                    if stream_inc is None:
                        stream_inc = inc
                    elif inc != stream_inc:
                        note_stale_drop("tcp")
                        logger.warning(
                            "dropping frame from foreign incarnation on "
                            "stream %d of %s", sid, self._addr,
                        )
                        continue
                if kind == "item":
                    yield payload
                elif kind == "end":
                    return
                elif kind == "err":
                    message, ekind = payload
                    raise err_exception(ekind, message)
                elif kind == "disconnect":
                    raise StreamDisconnectedError(
                        f"worker connection lost: {self._addr}"
                    )
        finally:
            cancel_task.cancel()
            conn.close_stream(sid)


class _suppress_conn:
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        return et is not None and issubclass(
            et, (ConnectionError, BrokenPipeError, RuntimeError, AssertionError)
        )
