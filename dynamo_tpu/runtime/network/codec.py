"""Two-part wire codec: (header msgpack, payload msgpack) length-prefixed.

Reference parity: lib/runtime/src/pipeline/network/codec/two_part.rs — each
frame is a small control header plus an opaque payload, so routing/stream
bookkeeping never deserializes user data. Layout per frame:

    u32 header_len | u32 payload_len | header bytes | payload bytes

Both parts are msgpack. The reference's zero_copy_decoder.rs avoids copying
the payload out of the socket buffer; asyncio gives us `readexactly` into a
single bytes object, which is the Python equivalent of that goal.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional, Tuple

import msgpack

_LEN = struct.Struct("<II")
MAX_FRAME = 256 * 1024 * 1024  # defensive cap


def _default(obj: Any):
    to_dict = getattr(obj, "to_dict", None)
    if callable(to_dict):
        return to_dict()
    raise TypeError(f"unserializable type {type(obj).__name__}")


def pack_frame(header: Any, payload: Any) -> bytes:
    h = msgpack.packb(header, default=_default, use_bin_type=True)
    p = msgpack.packb(payload, default=_default, use_bin_type=True)
    return _LEN.pack(len(h), len(p)) + h + p


class FrameWriter:
    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer
        self._lock = asyncio.Lock()  # frames from concurrent streams interleave

    async def send(self, header: Any, payload: Any = None) -> None:
        frame = pack_frame(header, payload)
        async with self._lock:
            self._writer.write(frame)
            await self._writer.drain()

    def close(self) -> None:
        self._writer.close()


class FrameReader:
    def __init__(self, reader: asyncio.StreamReader) -> None:
        self._reader = reader

    async def recv(self) -> Optional[Tuple[Any, Any]]:
        """Next (header, payload), or None on clean EOF."""
        try:
            lens = await self._reader.readexactly(_LEN.size)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return None
        hlen, plen = _LEN.unpack(lens)
        if hlen > MAX_FRAME or plen > MAX_FRAME:
            raise ValueError(f"frame too large: {hlen}+{plen}")
        body = await self._reader.readexactly(hlen + plen)
        header = msgpack.unpackb(body[:hlen], raw=False)
        # strict_map_key=False: request payloads legitimately carry int-keyed
        # maps (OpenAI logit_bias is token-id → bias) between our own
        # processes; the strict default exists for untrusted internet input.
        payload = (
            msgpack.unpackb(body[hlen:], raw=False, strict_map_key=False)
            if plen
            else None
        )
        return header, payload
