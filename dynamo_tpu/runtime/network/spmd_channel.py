"""Lockstep op broadcast for SPMD multi-host workers.

The leader's engine emits every device-program invocation as an op frame;
followers execute the identical invocation so all processes enter each
global-mesh jit together (XLA SPMD requires every process to issue the same
program with the same global shapes). This is the control-plane analog of
the reference's leader/worker ZMQ hookup in distributed KVBM
(lib/llm/src/block_manager/distributed/leader.rs role) — here the payload
is the jit inputs themselves, because in the JAX runtime the *program* is
shared and only the host-side inputs need distributing.

Wire format: length-prefixed msgpack maps. Numpy arrays ride as
``{"__nd__": (dtype-str, shape, raw-bytes)}``. Blocking stdlib sockets —
both ends use them from their single device thread, so ordering and
backpressure come from TCP itself.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, List, Optional, Tuple

import msgpack
import numpy as np

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_LEN = struct.Struct("!Q")


def _pack_default(obj):
    if isinstance(obj, np.ndarray):
        return {"__nd__": (obj.dtype.str, list(obj.shape), obj.tobytes())}
    if isinstance(obj, (np.integer, np.floating, np.bool_)):
        return {"__np0__": (np.dtype(type(obj)).str, obj.item())}
    raise TypeError(f"unserializable SPMD arg type {type(obj)!r}")


def _unpack_hook(obj):
    if "__nd__" in obj:
        dt, shape, raw = obj["__nd__"]
        return np.frombuffer(raw, dtype=np.dtype(dt)).reshape(shape)
    if "__np0__" in obj:
        dt, val = obj["__np0__"]
        return np.dtype(dt).type(val)
    return obj


def _send_frame(sock: socket.socket, payload: Any) -> None:
    data = msgpack.packb(payload, default=_pack_default, use_bin_type=True)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("SPMD channel closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return msgpack.unpackb(
        _recv_exact(sock, n), raw=False, strict_map_key=False,
        object_hook=_unpack_hook,
    )


class SpmdBroadcaster:
    """Leader side: accept follower connections, fan out op frames."""

    def __init__(self, port: int, num_followers: int, host: str = "0.0.0.0",
                 accept_timeout_s: float = 120.0) -> None:
        self._server = socket.create_server((host, port))
        self._server.settimeout(accept_timeout_s)
        self._conns: List[socket.socket] = []
        self.num_followers = num_followers
        # Ops normally flow from the engine's single device thread, but
        # admin operations (LoRA load/unload, sleep) can reach the runner
        # from the event loop — serialize whole frames so interleaved
        # sendall calls can't corrupt the stream.
        self._lock = threading.Lock()

    def wait_for_followers(self) -> None:
        while len(self._conns) < self.num_followers:
            conn, addr = self._server.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            logger.info(
                "SPMD follower %d/%d connected from %s",
                len(self._conns), self.num_followers, addr,
            )

    def send(self, op: str, **kwargs: Any) -> None:
        frame = {"op": op, **kwargs}
        with self._lock:
            for conn in self._conns:
                _send_frame(conn, frame)

    def close(self) -> None:
        for conn in self._conns:
            try:
                _send_frame(conn, {"op": "stop"})
            except OSError:
                pass
            conn.close()
        self._conns = []
        self._server.close()


class SpmdFollower:
    """Follower side: connect to the leader and iterate op frames."""

    def __init__(self, leader_host: str, port: int,
                 connect_timeout_s: float = 120.0) -> None:
        # The leader binds its broadcaster only after constructing its
        # DeviceRunner (params init, cache alloc) — the follower commonly
        # gets here first. create_connection fails INSTANTLY on
        # ECONNREFUSED, so retry until the deadline instead of dying on
        # the startup race.
        import time

        deadline = time.monotonic() + connect_timeout_s
        while True:
            try:
                self._sock = socket.create_connection(
                    (leader_host, port), timeout=5.0
                )
                break
            except (ConnectionRefusedError, ConnectionResetError, socket.timeout):
                # NOT a broad OSError: configuration errors (gaierror on a
                # misspelled leader host) should fail fast, not hang 120 s.
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)
        self._sock.settimeout(None)  # ops arrive whenever traffic does
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def recv(self) -> Tuple[str, dict]:
        frame = _recv_frame(self._sock)
        op = frame.pop("op")
        return op, frame

    def close(self) -> None:
        self._sock.close()
