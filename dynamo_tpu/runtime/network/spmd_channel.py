"""Lockstep op broadcast for SPMD multi-host workers.

The leader's engine emits every device-program invocation as an op frame;
followers execute the identical invocation so all processes enter each
global-mesh jit together (XLA SPMD requires every process to issue the same
program with the same global shapes). This is the control-plane analog of
the reference's leader/worker ZMQ hookup in distributed KVBM
(lib/llm/src/block_manager/distributed/leader.rs role) — here the payload
is the jit inputs themselves, because in the JAX runtime the *program* is
shared and only the host-side inputs need distributing.

Wire format: length-prefixed msgpack maps. Numpy arrays ride as
``{"__nd__": (dtype-str, shape, raw-bytes)}``. Blocking stdlib sockets —
both ends use them from their single device thread, so ordering and
backpressure come from TCP itself.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, List, Optional, Tuple

import msgpack
import numpy as np

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_LEN = struct.Struct("!Q")


class SpmdChannelError(ConnectionError):
    """The lockstep op channel to a follower broke. Unrecoverable for the
    worker group: a follower that missed even one op can never re-enter
    the collective (every process must issue every global program), so
    callers must fail the whole worker fast and let the supervisor restart
    the group together (deploy/pod_connector.py group restart)."""


def _pack_default(obj):
    if isinstance(obj, np.ndarray):
        return {"__nd__": (obj.dtype.str, list(obj.shape), obj.tobytes())}
    if isinstance(obj, (np.integer, np.floating, np.bool_)):
        return {"__np0__": (np.dtype(type(obj)).str, obj.item())}
    raise TypeError(f"unserializable SPMD arg type {type(obj)!r}")


def _unpack_hook(obj):
    if "__nd__" in obj:
        dt, shape, raw = obj["__nd__"]
        return np.frombuffer(raw, dtype=np.dtype(dt)).reshape(shape)
    if "__np0__" in obj:
        dt, val = obj["__np0__"]
        return np.dtype(dt).type(val)
    return obj


def _send_frame(sock: socket.socket, payload: Any) -> None:
    data = msgpack.packb(payload, default=_pack_default, use_bin_type=True)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("SPMD channel closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return msgpack.unpackb(
        _recv_exact(sock, n), raw=False, strict_map_key=False,
        object_hook=_unpack_hook,
    )


class SpmdBroadcaster:
    """Leader side: accept follower connections, fan out op frames."""

    def __init__(self, port: int, num_followers: int, host: str = "0.0.0.0",
                 accept_timeout_s: float = 120.0) -> None:
        self._server = socket.create_server((host, port))
        self._server.settimeout(accept_timeout_s)
        self._conns: List[socket.socket] = []
        self.num_followers = num_followers
        # Ops normally flow from the engine's single device thread, but
        # admin operations (LoRA load/unload, sleep) can reach the runner
        # from the event loop — serialize whole frames so interleaved
        # sendall calls can't corrupt the stream.
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        """Actual bound port (pass 0 to the constructor to let the OS pick
        — bind-before-publish eliminates probe-then-bind port races)."""
        return self._server.getsockname()[1]

    def wait_for_followers(self) -> None:
        while len(self._conns) < self.num_followers:
            conn, addr = self._server.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            logger.info(
                "SPMD follower %d/%d connected from %s",
                len(self._conns), self.num_followers, addr,
            )

    def send(self, op: str, **kwargs: Any) -> None:
        frame = {"op": op, **kwargs}
        with self._lock:
            for conn in self._conns:
                try:
                    _send_frame(conn, frame)
                except OSError as exc:
                    raise SpmdChannelError(
                        f"SPMD follower channel broke sending {op!r}: {exc}"
                    ) from exc

    def start_death_watch(self, on_dead) -> None:
        """Watch every follower socket for EOF/RST from a daemon thread and
        invoke ``on_dead(index, exc)`` the moment one dies.

        Needed because the op stream alone cannot fail fast: the leader's
        FIRST send after a follower dies lands in the kernel buffer, and
        the next global-mesh dispatch then blocks inside a collective that
        will never complete — the break must be detected out-of-band.
        Followers never send on this socket, so a blocking recv returns
        only at death (or our own close, which sets _closing first)."""
        self._closing = False

        def watch(i: int, conn: socket.socket) -> None:
            try:
                data = conn.recv(1)
            except OSError as exc:
                data, err = b"", exc
            else:
                err = None
            if not getattr(self, "_closing", False) and not data:
                on_dead(i, err or ConnectionError("follower EOF"))

        for i, conn in enumerate(self._conns):
            threading.Thread(
                target=watch, args=(i, conn),
                name=f"spmd-death-watch-{i}", daemon=True,
            ).start()

    def close(self) -> None:
        self._closing = True
        for conn in self._conns:
            try:
                _send_frame(conn, {"op": "stop"})
            except OSError:
                pass
            conn.close()
        self._conns = []
        self._server.close()


class SpmdFollower:
    """Follower side: connect to the leader and iterate op frames."""

    def __init__(self, leader_host: str, port: int,
                 connect_timeout_s: float = 120.0) -> None:
        # The leader binds its broadcaster only after constructing its
        # DeviceRunner (params init, cache alloc) — the follower commonly
        # gets here first. create_connection fails INSTANTLY on
        # ECONNREFUSED, so retry until the deadline instead of dying on
        # the startup race.
        import time

        deadline = time.monotonic() + connect_timeout_s
        while True:
            try:
                self._sock = socket.create_connection(
                    (leader_host, port), timeout=5.0
                )
                break
            except (ConnectionRefusedError, ConnectionResetError, socket.timeout):
                # NOT a broad OSError: configuration errors (gaierror on a
                # misspelled leader host) should fail fast, not hang 120 s.
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)
        self._sock.settimeout(None)  # ops arrive whenever traffic does
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def recv(self) -> Tuple[str, dict]:
        frame = _recv_frame(self._sock)
        op = frame.pop("op")
        return op, frame

    def close(self) -> None:
        self._sock.close()
