"""Host memory arena: preallocated, region-based staging buffers.

Reference parity: the dynamo-memory crate (lib/memory — arena/pinned-pool
abstractions under KVBM and the NIXL staging paths). On TPU hosts there is
no cudaHostAlloc; the analogous win is *bounded, reusable* staging memory:
one up-front allocation, O(1) region alloc/free, zero per-block allocator
churn for KV offload and disagg transfers — and a hard cap so a busy host
tier cannot OOM the process.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


class ArenaExhausted(MemoryError):
    """No region large enough (capacity or fragmentation)."""


@dataclass
class Region:
    """A leased slice of the arena."""

    offset: int
    nbytes: int
    _freed: bool = False


class Arena:
    """First-fit region allocator over one preallocated buffer.

    Free regions are kept sorted by offset and coalesced on free. Designed
    for few, large, similarly-sized regions (KV blocks), where first-fit's
    fragmentation behavior is excellent and allocation is O(#free regions).
    Thread-safe: device/staging threads allocate while the loop frees.
    """

    def __init__(self, capacity_bytes: int) -> None:
        self.capacity = int(capacity_bytes)
        self._buf = np.zeros(self.capacity, dtype=np.uint8)
        self._free: List[List[int]] = [[0, self.capacity]]  # [offset, size]
        self._lock = threading.Lock()
        self.allocated_bytes = 0
        self.peak_bytes = 0

    def alloc(self, nbytes: int) -> Region:
        nbytes = int(nbytes)
        if nbytes <= 0:
            raise ValueError("nbytes must be positive")
        # 64-byte alignment: keeps numpy views cache/DMA friendly.
        nbytes = (nbytes + 63) & ~63
        with self._lock:
            for i, (off, size) in enumerate(self._free):
                if size >= nbytes:
                    if size == nbytes:
                        self._free.pop(i)
                    else:
                        self._free[i] = [off + nbytes, size - nbytes]
                    self.allocated_bytes += nbytes
                    self.peak_bytes = max(self.peak_bytes, self.allocated_bytes)
                    return Region(offset=off, nbytes=nbytes)
        raise ArenaExhausted(
            f"arena cannot satisfy {nbytes}B "
            f"(capacity {self.capacity}B, allocated {self.allocated_bytes}B)"
        )

    def free(self, region: Region) -> None:
        with self._lock:
            if region._freed:
                return
            region._freed = True
            self.allocated_bytes -= region.nbytes
            # Insert sorted by offset, then coalesce neighbors.
            entry = [region.offset, region.nbytes]
            lo, hi = 0, len(self._free)
            while lo < hi:
                mid = (lo + hi) // 2
                if self._free[mid][0] < entry[0]:
                    lo = mid + 1
                else:
                    hi = mid
            self._free.insert(lo, entry)
            # coalesce with next
            if lo + 1 < len(self._free) and entry[0] + entry[1] == self._free[lo + 1][0]:
                entry[1] += self._free[lo + 1][1]
                self._free.pop(lo + 1)
            # coalesce with prev
            if lo > 0 and self._free[lo - 1][0] + self._free[lo - 1][1] == entry[0]:
                self._free[lo - 1][1] += entry[1]
                self._free.pop(lo)

    def view(self, region: Region, dtype=np.uint8, shape=None) -> np.ndarray:
        """Zero-copy numpy view of a region."""
        if region._freed:
            raise ValueError("region already freed")
        raw = self._buf[region.offset : region.offset + region.nbytes]
        out = raw.view(dtype)
        if shape is not None:
            n = int(np.prod(shape))
            out = out[:n].reshape(shape)
        return out

    def store(self, array: np.ndarray) -> Region:
        """Copy an array into a fresh region (view(r, dt, shape) reads it)."""
        a = np.ascontiguousarray(array)
        region = self.alloc(a.nbytes)
        self.view(region, a.dtype, a.shape)[...] = a
        return region

    @property
    def free_bytes(self) -> int:
        with self._lock:
            return sum(size for _, size in self._free)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "allocated": self.allocated_bytes,
                "peak": self.peak_bytes,
                "free_regions": len(self._free),
            }


class BlockStagingPool:
    """Arena-backed block store for the KVBM host tier.

    A block is a tuple of arrays — (k, v) dense, or the quantized wire form
    (k_q8, v_q8, k_scale, v_scale) whose int8 payloads halve the arena
    bytes a block occupies (kvbm/tiers.py block forms). Bounds the host
    tier's memory to exactly ``capacity_bytes`` no matter how many blocks
    pass through, replacing per-block numpy allocations."""

    def __init__(self, capacity_bytes: int) -> None:
        self.arena = Arena(capacity_bytes)
        # hash → tuple of (region, dtype, shape) per stored array
        self._meta: Dict[int, tuple] = {}

    def put(self, block_hash: int, *arrays: np.ndarray) -> bool:
        if block_hash in self._meta:
            return True
        regions = []
        for a in arrays:
            try:
                regions.append((self.arena.store(a), a.dtype, a.shape))
            except ArenaExhausted:
                for r, _, _ in regions:
                    self.arena.free(r)
                return False
        self._meta[block_hash] = tuple(regions)
        return True

    def get(self, block_hash: int):
        meta = self._meta.get(block_hash)
        if meta is None:
            return None
        return tuple(
            self.arena.view(r, dtype, shape) for r, dtype, shape in meta
        )

    def pop(self, block_hash: int) -> None:
        meta = self._meta.pop(block_hash, None)
        if meta is not None:
            for r, _, _ in meta:
                self.arena.free(r)

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._meta

    def __len__(self) -> int:
        return len(self._meta)
