"""DistributedRuntime: the root handle tying planes together.

Reference parity: lib/runtime/src/distributed.rs:42 (DistributedRuntime),
:592 (process-local test mode), :610 (RequestPlaneMode). A runtime owns:

  - the **discovery plane** (instance/model registration + watch, leases),
  - the **request plane** (request/response streaming to instances),
  - the **event plane** (pub/sub for KV events and load metrics),
  - the set of locally served endpoints and their in-flight task trackers.

Modes:
  - ``DistributedRuntime.process_local(bus=...)`` — everything in-memory; N
    runtimes in one process sharing a bus emulate a cluster (test backbone,
    ref: distributed.rs:592 create_test_drt).
  - ``DistributedRuntime.detached()`` — single process, no sharing.
  - TCP/file modes are wired by dynamo_tpu.runtime.network (request plane) and
    runtime.discovery backends (file / discd service).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Dict, Optional

from dynamo_tpu.runtime.component import (
    Endpoint,
    Instance,
    Namespace,
    ServedEndpoint,
)
from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.discovery import DiscoveryBackend, Lease, MemoryDiscovery
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.events import EventPlane, MemoryEventPlane
from dynamo_tpu.runtime.tasks import TaskTracker

from dynamo_tpu import config

logger = logging.getLogger(__name__)


class LocalRequestPlane:
    """In-process request plane: client calls the engine directly.

    Shared per-bus so multiple runtimes in one process reach each other's
    engines (the process-local analogue of the TCP request plane)."""

    _buses: Dict[str, Dict[str, AsyncEngine]] = {}

    def __init__(self, bus: str = "default") -> None:
        self.bus = bus
        self._engines = self._buses.setdefault(bus, {})

    @classmethod
    def reset(cls, bus: Optional[str] = None) -> None:
        if bus is None:
            cls._buses.clear()
        else:
            cls._buses.pop(bus, None)

    async def serve(self, instance: Instance, engine: AsyncEngine, tracker: TaskTracker) -> Dict[str, Any]:
        self._engines[instance.key] = _TrackedEngine(engine, tracker)
        return {"kind": "local", "bus": self.bus, "key": instance.key}

    async def unserve(self, instance: Instance) -> None:
        self._engines.pop(instance.key, None)

    def client_for(self, instance: Instance) -> AsyncEngine:
        engines = self._buses.get(instance.transport.get("bus", self.bus), {})
        engine = engines.get(instance.transport.get("key", instance.key))
        if engine is None:
            from dynamo_tpu.runtime.component import NoInstancesError

            raise NoInstancesError(f"local engine gone: {instance.key}")
        return engine

    async def close(self) -> None:
        pass


class _TrackedEngine:
    """Wraps a served engine so in-flight streams register with the tracker
    (draining support) and refuse new work once draining."""

    def __init__(self, engine: AsyncEngine, tracker: TaskTracker) -> None:
        self._engine = engine
        self._tracker = tracker

    async def generate(self, request: Any, context: Context):
        if self._tracker.draining:
            from dynamo_tpu.runtime.component import NoInstancesError

            raise NoInstancesError("endpoint draining")
        with self._tracker.guard():
            async for item in self._engine.generate(request, context):
                yield item


class DistributedRuntime:
    def __init__(
        self,
        *,
        discovery: Optional[DiscoveryBackend] = None,
        request_plane: Optional[Any] = None,
        event_plane: Optional[EventPlane] = None,
        bus: str = "default",
    ) -> None:
        self.bus = bus
        self.discovery: DiscoveryBackend = discovery or MemoryDiscovery.shared(bus)
        self.request_plane = request_plane or LocalRequestPlane(bus)
        self.event_plane: EventPlane = event_plane or MemoryEventPlane.shared(bus)
        self.tracker = TaskTracker("runtime")
        self._served: Dict[str, ServedEndpoint] = {}
        self._serve_trackers: Dict[str, TaskTracker] = {}
        # Every doc put under the serving lease, kept for re-registration:
        # after a control-plane outage long enough to expire the lease,
        # the keep-alive loop re-puts these under a fresh lease so the
        # worker rejoins discovery without a process restart.
        self._leased_docs: Dict[str, Dict[str, Any]] = {}
        self._lease: Optional[Lease] = None
        self._shutdown = asyncio.Event()
        self._extra_planes: list = []
        self._owns_bus = False

    # -- constructors ------------------------------------------------------

    @classmethod
    def process_local(cls, bus: str = "default") -> "DistributedRuntime":
        return cls(bus=bus)

    @classmethod
    def detached(cls) -> "DistributedRuntime":
        bus = f"detached-{random.getrandbits(32):08x}"
        runtime = cls(
            discovery=MemoryDiscovery(),
            request_plane=LocalRequestPlane(bus),
            event_plane=MemoryEventPlane(),
            bus=bus,
        )
        runtime._owns_bus = True
        return runtime

    @classmethod
    def from_settings(cls) -> "DistributedRuntime":
        """Cross-process runtime wired from the DYN_TPU_* env registry
        (ref: distributed.rs:536 from_settings; environment_names.rs)."""
        discovery_kind = config.DISCOVERY.get()
        if discovery_kind == "file":
            from dynamo_tpu.runtime.discovery.file import FileDiscovery

            discovery = FileDiscovery(config.DISCOVERY_ADDR.get())
        elif discovery_kind == "discd":
            from dynamo_tpu.runtime.discovery.discd import DiscdDiscovery

            discovery = DiscdDiscovery(config.DISCOVERY_ADDR.get())
        else:
            discovery = MemoryDiscovery.shared("default")

        if config.REQUEST_PLANE.get() == "tcp":
            from dynamo_tpu.runtime.network.tcp import TcpRequestPlane

            request_plane = TcpRequestPlane(host=config.TCP_HOST.get())
        elif config.REQUEST_PLANE.get() == "http":
            from dynamo_tpu.runtime.network.http_plane import HttpRequestPlane

            request_plane = HttpRequestPlane(host=config.TCP_HOST.get())
        else:
            request_plane = LocalRequestPlane("default")

        if config.EVENT_PLANE.get() == "zmq":
            from dynamo_tpu.runtime.events.zmq_plane import ZmqEventPlane

            event_plane = ZmqEventPlane(config.EVENT_PLANE_ADDR.get())
        else:
            event_plane = MemoryEventPlane.shared("default")
        return cls(
            discovery=discovery, request_plane=request_plane, event_plane=event_plane
        )

    # -- naming ------------------------------------------------------------

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    # -- serving -----------------------------------------------------------

    async def _lease_for_serving(self) -> Lease:
        if self._lease is None:
            self._lease = await self.discovery.create_lease(config.LEASE_TTL.get())
            keep_alive = getattr(self.discovery, "keep_alive", None)
            if keep_alive is not None:
                self.tracker.spawn(
                    self._keep_alive_loop(keep_alive), name="lease-keepalive", critical=True
                )
        return self._lease

    async def _keep_alive_loop(self, keep_alive) -> None:
        assert self._lease is not None
        import time as _time

        from dynamo_tpu.runtime.tasks import Backoff

        interval = max(0.5, self._lease.ttl / 3.0)
        # Failure retries use jittered exponential backoff (capped above
        # the renewal cadence): a control-plane blip disconnects EVERY
        # worker's keep-alive at once, and fixed-interval retries would
        # reconnect as a synchronized herd.
        backoff = Backoff(base_s=interval / 2, cap_s=4 * interval)
        down_since: Optional[float] = None
        while not self._shutdown.is_set():
            delay = interval if down_since is None else backoff.next_delay()
            try:
                # Waiting on the shutdown event (not a bare sleep) lets
                # shutdown() proceed immediately instead of stalling a tick.
                await asyncio.wait_for(self._shutdown.wait(), timeout=delay)
                return
            except asyncio.TimeoutError:
                pass
            try:
                # Chaos seam: a failed renewal is absorbed by the TTL
                # budget (interval = ttl/3, so two consecutive misses
                # still beat expiry); sustained failure expires the lease
                # and watchers observe the instance Delete.
                fault_point(fault_names.DISCOVERY_LEASE_RENEW)
                await keep_alive(self._lease)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                now = _time.monotonic()
                if down_since is None:
                    down_since = now
                if now - down_since >= self._lease.ttl:
                    # The lease has (almost certainly) expired mid-outage:
                    # watchers saw our keys DELETE, and renewing a dead
                    # lease can never succeed again. Re-establish — fresh
                    # lease, every leased doc re-put — so the worker
                    # rejoins discovery the moment the plane recovers.
                    try:
                        await self._reregister()
                    except asyncio.CancelledError:
                        raise
                    except Exception as rexc:
                        logger.warning(
                            "discovery re-register failed (still down): %r",
                            rexc,
                        )
                        continue
                    down_since = None
                    backoff.reset()
                else:
                    logger.warning("lease keep-alive failed: %r", exc)
                continue
            if down_since is not None:
                down_since = None
                backoff.reset()

    async def _reregister(self) -> None:
        """Fresh lease + re-put of every lease-attached doc (endpoint
        instances and model cards) after an outage expired the old one."""
        lease = await self.discovery.create_lease(config.LEASE_TTL.get())
        self._lease = lease
        for key, doc in self._leased_docs.items():
            await self.discovery.put(key, doc, lease=lease)
        logger.warning(
            "re-registered %d discovery docs under fresh lease %s after "
            "control-plane outage", len(self._leased_docs), lease.id,
        )

    async def put_leased(self, key: str, doc: Dict[str, Any]) -> None:
        """Put a discovery doc under the serving lease AND remember it, so
        the keep-alive loop can re-register it after a control-plane
        outage expires the lease (endpoint instances, model cards)."""
        lease = await self._lease_for_serving()
        self._leased_docs[key] = doc
        await self.discovery.put(key, doc, lease=lease)

    def forget_leased(self, key: str) -> None:
        self._leased_docs.pop(key, None)

    async def _serve(
        self,
        endpoint: Endpoint,
        engine: AsyncEngine,
        *,
        instance_id: Optional[int],
        metadata: Dict[str, Any],
    ) -> ServedEndpoint:
        iid = instance_id if instance_id is not None else random.getrandbits(63)
        instance = Instance(
            namespace=endpoint.namespace,
            component=endpoint.component,
            endpoint=endpoint.name,
            instance_id=iid,
            transport={},
            metadata=metadata,
        )
        tracker = TaskTracker(f"endpoint:{endpoint.path}:{iid:x}")
        transport = await self.request_plane.serve(instance, engine, tracker)
        instance = Instance(
            namespace=instance.namespace,
            component=instance.component,
            endpoint=instance.endpoint,
            instance_id=iid,
            transport=transport,
            metadata=metadata,
        )
        await self.put_leased(instance.key, instance.to_dict())
        served = ServedEndpoint(instance=instance, _runtime=self, _engine=engine)
        self._served[instance.key] = served
        self._serve_trackers[instance.key] = tracker
        logger.info("serving %s as instance %x", endpoint.path, iid)
        return served

    async def _unserve(self, served: ServedEndpoint, grace_period: float = 30.0) -> None:
        key = served.instance.key
        self.forget_leased(key)
        # De-register first so routers stop picking us, then drain. A dead
        # discovery plane must not abort the shutdown: the lease expiry (or
        # a discd snapshot-restore sweep) will retire the key for us.
        try:
            await self.discovery.delete(key)
        except Exception as exc:
            logger.warning(
                "deregister of %s failed (discovery down?): %r", key, exc
            )
        tracker = self._serve_trackers.pop(key, None)
        if tracker is not None:
            await tracker.drain(grace_period)
        await self.request_plane.unserve(served.instance)
        self._served.pop(key, None)

    def request_plane_client(self, instance: Instance) -> AsyncEngine:
        kind = instance.transport.get("kind", "local")
        # The runtime's own plane serves matching transports (a from_settings
        # TCP runtime reuses its plane's connection pool for egress too).
        if getattr(self.request_plane, "kind", "local") == kind:
            return self.request_plane.client_for(instance)
        if kind == "local":
            return self.request_plane.client_for(instance)
        for plane in self._extra_planes:
            if plane.kind == kind:
                return plane.client_for(instance)
        if kind == "tcp":
            try:
                from dynamo_tpu.runtime.network.tcp import TcpRequestPlane
            except ImportError as exc:
                raise NotImplementedError(
                    "tcp request plane not available in this build"
                ) from exc
            plane = TcpRequestPlane()
            self._extra_planes.append(plane)
            return plane.client_for(instance)
        if kind == "http":
            from dynamo_tpu.runtime.network.http_plane import HttpRequestPlane

            plane = HttpRequestPlane()
            self._extra_planes.append(plane)
            return plane.client_for(instance)
        raise ValueError(f"unknown transport kind {kind!r} for {instance.key}")

    # -- lifecycle ---------------------------------------------------------

    async def shutdown(self, grace_period: float = 30.0) -> None:
        """Graceful shutdown: de-register, drain in-flight, release leases
        (ref: GracefulShutdownTracker lib.rs:58, docs/fault_tolerance/graceful_shutdown.md)."""
        self._shutdown.set()
        for served in list(self._served.values()):
            await self._unserve(served, grace_period=grace_period)
        if self._lease is not None:
            try:
                await self.discovery.revoke_lease(self._lease)
            except Exception as exc:
                logger.warning(
                    "lease revoke failed (discovery down?): %r — the TTL "
                    "sweep will expire it", exc
                )
            self._lease = None
        await self.tracker.drain(grace_period)
        for plane in self._extra_planes:
            await plane.close()
        await self.request_plane.close()
        await self.discovery.close()
        if self._owns_bus:
            LocalRequestPlane.reset(self.bus)
