"""Canonical fault-point names: ONE place declares every injection seam.

Mirror of runtime/metric_names.py for the fault plane (runtime/faults.py):
``fault_point(...)`` call sites import these constants, and the dynlint
DYN006 pass closes the loop in both directions — a point name used at a
seam must be declared here, and a declared point must have at least one
seam (a dead point is chaos coverage that silently stopped existing).

This module is loaded BY FILE PATH by the linter (no package import) and
must stay dependency-free — constants and tuples only.

Naming scheme: ``<subsystem>.<operation>[.<phase>]``.
"""

from __future__ import annotations

# -- request/event planes (runtime/network/tcp.py, runtime/events/zmq_plane.py)
NET_TCP_SEND = "net.tcp.send"
NET_TCP_RECV = "net.tcp.recv"
NET_ZMQ_SEND = "net.zmq.send"
NET_ZMQ_RECV = "net.zmq.recv"

# -- disaggregated KV transfer (disagg/handlers.py) ---------------------------
# One pull-side hit per received chunk, BEFORE the chunk is imported: an
# injection here models the wire dying mid-transfer with N chunks landed.
DISAGG_PULL_CHUNK = "disagg.pull.chunk"
# Export side: one hit per chunk gathered by the KvTransferHandler.
DISAGG_KV_EXPORT = "disagg.kv.export"
# Import side: one hit per chunk handed to the engine's scatter path.
DISAGG_KV_IMPORT = "disagg.kv.import"

# -- engine decode tick (engines/tpu/engine.py) -------------------------------
# Dispatch: after the sync payloads are built, before the device call — the
# adversarial spot, because the dirty-slot sets were already cleared and
# recovery must resync them from the mirrors (_abort_inflight).
ENGINE_TICK_DISPATCH = "engine.tick.dispatch"
# Reap: before the oldest in-flight burst's readback.
ENGINE_TICK_REAP = "engine.tick.reap"
# Tick budgeter (engines/tpu/tick_budget.py): one hit per budget
# ADJUSTMENT the AIMD controller is about to commit (shrink or grow), not
# per evaluation — an injection models the control law dying and MUST skip
# that adjustment cleanly (budget unchanged, streaks reset, skip counted),
# never corrupt the budget or take the tick loop down with it.
ENGINE_BUDGET_APPLY = "engine.budget.apply"

# -- discovery / health (runtime/distributed.py, runtime/health.py) -----------
DISCOVERY_LEASE_RENEW = "discovery.lease.renew"
HEALTH_CANARY = "health.canary"

# -- KVBM storage tiers (kvbm/tiers.py) ---------------------------------------
KVBM_TIER_READ = "kvbm.tier.read"
KVBM_TIER_WRITE = "kvbm.tier.write"

# -- KVBM speculative prefetch (kvbm/manager.py) ------------------------------
# One hit per speculative onboard walk, at the top of the prefetch task
# BEFORE any tier read or device scatter: an injection models the prefetch
# machinery dying outright — the lease must settle as wasted (outcome
# "error"), the pool must stay balanced, and admission must fall back to
# the serial onboard path untouched.
KVBM_PREFETCH = "kvbm.prefetch"

# -- drain plane (runtime/drain.py, engines/tpu/engine.py) --------------------
# Export side of a live handoff: one hit per detached sequence, BEFORE the
# device gather — an injection models the draining worker failing to read
# its own pool (the ladder must fall through to re-prefill migration).
DRAIN_HANDOFF_EXPORT = "drain.handoff.export"
# Import side: one hit per adoption attempt on the PEER, before any pool
# mutation — an injection models the receiving worker refusing/dying, which
# the source must absorb by trying the next peer or falling down the ladder.
DRAIN_HANDOFF_IMPORT = "drain.handoff.import"

# -- crash plane (runtime/liveness.py, engines/tpu/kv_checkpoint.py) ----------
# One hit per load report admitted by the liveness tracker: an injected
# failure models report loss between the wire and the tracker — N
# consecutive injections must trip the same suspect/dead machinery a
# crashed worker does (the fake-clock detection tests replay this).
LIVENESS_REPORT = "liveness.report"
# One hit at the top of a warm-restart checkpoint restore, before anything
# is read: an injection models the restore machinery failing outright —
# which MUST resolve to a logged cold start (counted cold_error), never a
# crash loop.
RESTORE_LOAD = "restore.load"

# -- planner / elasticity plane (planner/planner_core.py) ---------------------
# One hit per adjustment-interval observation, BEFORE the metrics source is
# read: an injection models the scrape (or the metrics pipeline) dying —
# the control loop must skip the interval and keep converging, never crash
# or act on a half-read snapshot.
PLANNER_OBSERVE = "planner.observe"
# One hit per plan handed to the connector, BEFORE any actuation: an
# injection models the actuation plane (k8s API, process supervisor,
# drain endpoints) refusing the plan — the loop must retry on its own
# cadence and the fleet must never be left half-actuated by the raise
# (the elastic controller's per-action error handling owns partial fleets).
PLANNER_APPLY = "planner.apply"

# -- trajectory plane (runtime/trajectory.py) ---------------------------------
# One hit per shipped span/event batch, BEFORE the event-plane publish: an
# injection models the telemetry path dying — the batch is counted dropped
# and serving continues untouched (observability must never take down the
# data plane; the shipper tests replay this).
TRAJECTORY_SHIP = "trajectory.ship"

# -- parser plane (parsers/jail.py) -------------------------------------------
# One hit per jail operation (each content delta fed, plus the finish at
# stream end): an injection models the tool-call parser dying mid-stream
# — which MUST surface as a terminal typed SSE error frame
# (error_kind=tool_call_parse), never a dropped stream (the chunk-fuzz
# chaos suite replays this bit-identically).
PARSER_JAIL_FEED = "parser.jail.feed"

# -- overload plane (runtime/overload.py) -------------------------------------
# One hit per QUEUED admission attempt, before the EDF wait: an injected
# timeout here expires exactly that request's queue budget — the
# deterministic mid-queue-expiry schedule the saturation tests replay
# (wall-clock deadline races can't).
OVERLOAD_ADMIT = "overload.admit"

# -- perf ledger (runtime/perf_ledger.py) -------------------------------------
# One hit at the top of the startup fingerprint load, before the file is
# opened: an injection models a corrupt / vanished / unreadable
# fingerprint file — which MUST degrade to a counted, flight-recorded
# cold start (no baseline, sentinel verdicts go "no_baseline"), never a
# crash.
PERF_FINGERPRINT_LOAD = "perf.fingerprint.load"
# One hit per clean-shutdown fingerprint store, before the tmp write: an
# injection models the persistence path dying — the shutdown proceeds,
# the failure is counted, and the NEXT start is a cold start (a degraded
# baseline is worse than none).
PERF_FINGERPRINT_STORE = "perf.fingerprint.store"

ALL_FAULT_POINTS = (
    NET_TCP_SEND,
    NET_TCP_RECV,
    NET_ZMQ_SEND,
    NET_ZMQ_RECV,
    DISAGG_PULL_CHUNK,
    DISAGG_KV_EXPORT,
    DISAGG_KV_IMPORT,
    ENGINE_TICK_DISPATCH,
    ENGINE_TICK_REAP,
    ENGINE_BUDGET_APPLY,
    DISCOVERY_LEASE_RENEW,
    HEALTH_CANARY,
    KVBM_TIER_READ,
    KVBM_TIER_WRITE,
    KVBM_PREFETCH,
    DRAIN_HANDOFF_EXPORT,
    DRAIN_HANDOFF_IMPORT,
    LIVENESS_REPORT,
    RESTORE_LOAD,
    PLANNER_OBSERVE,
    PLANNER_APPLY,
    TRAJECTORY_SHIP,
    OVERLOAD_ADMIT,
    PARSER_JAIL_FEED,
    PERF_FINGERPRINT_LOAD,
    PERF_FINGERPRINT_STORE,
)
