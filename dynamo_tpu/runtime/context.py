"""Request context: identity, cancellation, deadlines, tracing baggage.

Reference parity: dynamo-runtime's ``Context``/``AsyncEngineContext``
(lib/runtime/src/engine.rs:201 and pipeline context plumbing). The reference
relies on Rust drop-semantics for cancellation propagation; here we use an
explicit tree of asyncio-friendly stop events with parent→child kill
propagation, which composes with ``asyncio.CancelledError`` at await points.
"""

from __future__ import annotations

import asyncio
import contextvars
import time
import uuid
from typing import Any, Dict, List, Optional

# W3C-traceparent-style propagation: the active context rides a contextvar so
# nested operators and log records can pick it up without explicit threading.
_current_context: contextvars.ContextVar[Optional["Context"]] = contextvars.ContextVar(
    "dynamo_tpu_context", default=None
)


def current_context() -> Optional["Context"]:
    return _current_context.get()


class Context:
    """Per-request context flowing through the pipeline with the payload.

    - ``id``: globally unique request id (also the stream id on the wire).
    - ``stop``: cooperative cancellation. ``stopped`` is checked by engines
      between decode steps; awaiting code can use ``wait_stopped``.
    - ``kill``: hard cancellation — also cancels in-flight network I/O.
    - children: cancelling a parent cancels every child (router → worker
      sub-requests, disagg prefill sub-request, migration retries).
    """

    __slots__ = (
        "_id",
        "_stop_event",
        "_kill_event",
        "_children",
        "_parent",
        "_baggage",
        "_created_at",
        "_deadline",
        "_deadline_handle",
        "_stop_reason",
        "_token",
        "__weakref__",
    )

    def __init__(
        self,
        id: Optional[str] = None,
        *,
        parent: Optional["Context"] = None,
        baggage: Optional[Dict[str, Any]] = None,
        deadline: Optional[float] = None,
    ) -> None:
        self._id = id or uuid.uuid4().hex
        self._stop_event = asyncio.Event()
        self._kill_event = asyncio.Event()
        self._children: List[Context] = []
        self._parent = parent
        self._baggage: Dict[str, Any] = dict(baggage or {})
        self._created_at = time.monotonic()
        self._deadline = deadline
        self._deadline_handle = None
        self._stop_reason: Optional[str] = None
        if deadline is not None:
            # Arm a timer so wait_stopped() waiters observe the deadline even
            # if nobody polls `.stopped`.
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None
            if loop is not None:
                delay = max(0.0, deadline - time.monotonic())
                handle = loop.call_later(delay, self.stop_generating, "deadline")
                self._deadline_handle = handle
        if parent is not None:
            parent._children.append(self)
            if parent.stopped:
                self.stop_generating(reason=parent._stop_reason or "parent-stopped")
            if parent.killed:
                self.kill()

    # -- identity ---------------------------------------------------------

    @property
    def id(self) -> str:
        return self._id

    @property
    def baggage(self) -> Dict[str, Any]:
        return self._baggage

    @property
    def created_at(self) -> float:
        return self._created_at

    @property
    def elapsed(self) -> float:
        return time.monotonic() - self._created_at

    # -- cancellation -----------------------------------------------------

    @property
    def deadline(self) -> Optional[float]:
        """Absolute monotonic deadline, or None. Operators that spend
        bounded sub-budgets (the disagg pull timeout) derive them from
        ``time_remaining`` so a slow transfer can never eat the whole
        request budget."""
        return self._deadline

    def time_remaining(self) -> Optional[float]:
        """Seconds left until the deadline (None = unbounded, 0 = past)."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def set_deadline(self, deadline: Optional[float]) -> None:
        """(Re)arm the absolute monotonic deadline after construction —
        the overload plane stamps a default budget onto deadline-less
        requests this way. Arms the same wake-up timer the constructor
        would, so ``wait_stopped`` waiters observe the new deadline."""
        if self._deadline_handle is not None:
            self._deadline_handle.cancel()
            self._deadline_handle = None
        self._deadline = deadline
        if deadline is None or self._stop_event.is_set():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            delay = max(0.0, deadline - time.monotonic())
            self._deadline_handle = loop.call_later(
                delay, self.stop_generating, "deadline"
            )

    @property
    def stopped(self) -> bool:
        if self._deadline is not None and time.monotonic() > self._deadline:
            self.stop_generating(reason="deadline")
        return self._stop_event.is_set()

    @property
    def killed(self) -> bool:
        return self._kill_event.is_set()

    @property
    def stop_reason(self) -> Optional[str]:
        return self._stop_reason

    def stop_generating(self, reason: str = "cancelled") -> None:
        """Cooperatively stop: engines finish the current step then cease."""
        if not self._stop_event.is_set():
            self._stop_reason = reason
            self._stop_event.set()
            if self._deadline_handle is not None:
                self._deadline_handle.cancel()
                self._deadline_handle = None
            for child in self._children:
                child.stop_generating(reason=reason)

    def kill(self) -> None:
        """Hard-stop: also unblocks any ``wait_killed`` waiters (network I/O)."""
        self.stop_generating(reason="killed")
        if not self._kill_event.is_set():
            self._kill_event.set()
            for child in self._children:
                child.kill()

    async def wait_stopped(self) -> None:
        await self._stop_event.wait()

    async def wait_killed(self) -> None:
        await self._kill_event.wait()

    # -- tree -------------------------------------------------------------

    def child(self, id: Optional[str] = None) -> "Context":
        return Context(id=id, parent=self, baggage=self._baggage, deadline=self._deadline)

    # -- scoping ----------------------------------------------------------

    def __enter__(self) -> "Context":
        self._token = _current_context.set(self)
        return self

    def __exit__(self, *exc: Any) -> None:
        _current_context.reset(self._token)

    def __repr__(self) -> str:
        state = "killed" if self.killed else ("stopped" if self.stopped else "live")
        return f"Context({self._id[:8]}…, {state})"


class EngineStream:
    """Pairs a response stream with the context that controls it.

    Dropping the stream (``aclose``) stops the context, mirroring the
    reference's drop-based cancellation of ``AsyncEngineStream``.
    """

    def __init__(self, stream: Any, context: Context) -> None:
        self._stream = stream
        self._context = context

    @property
    def context(self) -> Context:
        return self._context

    def __aiter__(self) -> "EngineStream":
        return self

    async def __anext__(self) -> Any:
        if self._context.killed:
            raise StopAsyncIteration
        try:
            return await self._stream.__anext__()
        except StopAsyncIteration:
            raise

    async def aclose(self) -> None:
        self._context.stop_generating(reason="stream-closed")
        close = getattr(self._stream, "aclose", None)
        if close is not None:
            await close()
