"""Canonical metric names: ONE place defines every Prometheus name.

Reference parity: lib/runtime/src/metrics/prometheus_names.rs — the
reference centralizes metric-name constants so dashboards, alerts, the
planner's scrape source, and the emitting components can never drift
apart. Same rule here: emitters (http/metrics.py, runtime/system_server.py)
and consumers (planner/metrics_source.py) import these constants instead
of repeating strings.

Naming scheme: ``dynamo_tpu_<subsystem>_<metric>[_unit][_total]``.
"""

from __future__ import annotations

# -- frontend (http/metrics.py) ---------------------------------------------
FRONTEND_PREFIX = "dynamo_tpu_frontend"
FRONTEND_REQUESTS_TOTAL = f"{FRONTEND_PREFIX}_requests_total"
FRONTEND_INFLIGHT = f"{FRONTEND_PREFIX}_inflight_requests"
FRONTEND_REQUEST_DURATION = f"{FRONTEND_PREFIX}_request_duration_seconds"
FRONTEND_TTFT = f"{FRONTEND_PREFIX}_time_to_first_token_seconds"
FRONTEND_ITL = f"{FRONTEND_PREFIX}_inter_token_latency_seconds"
FRONTEND_OUTPUT_TOKENS_TOTAL = f"{FRONTEND_PREFIX}_output_tokens_total"
FRONTEND_INPUT_TOKENS_TOTAL = f"{FRONTEND_PREFIX}_input_tokens_total"

# -- engine (runtime/system_server.py engine_stats_prometheus) ---------------
ENGINE_PREFIX = "dynamo_tpu_engine"


def engine_gauge(stat_key: str) -> str:
    """Engine stats-dict key → canonical gauge name (system server)."""
    return f"{ENGINE_PREFIX}_{stat_key}"


ENGINE_ACTIVE_SEQS = engine_gauge("active_seqs")
ENGINE_WAITING = engine_gauge("waiting")
ENGINE_KV_USAGE = engine_gauge("kv_usage")
ENGINE_FREE_BLOCKS = engine_gauge("free_blocks")
ENGINE_CACHED_BLOCKS = engine_gauge("cached_blocks")
ENGINE_TOTAL_BLOCKS = engine_gauge("total_blocks")
ENGINE_DECODE_STEPS = engine_gauge("decode_steps")
ENGINE_PREFILL_TOKENS = engine_gauge("prefill_tokens")
ENGINE_GENERATED_TOKENS = engine_gauge("generated_tokens")
ENGINE_SLEEP_LEVEL = engine_gauge("sleep_level")

ALL_FRONTEND = (
    FRONTEND_REQUESTS_TOTAL,
    FRONTEND_INFLIGHT,
    FRONTEND_REQUEST_DURATION,
    FRONTEND_TTFT,
    FRONTEND_ITL,
    FRONTEND_OUTPUT_TOKENS_TOTAL,
    FRONTEND_INPUT_TOKENS_TOTAL,
)
