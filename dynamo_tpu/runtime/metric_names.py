"""Canonical metric names: ONE place defines every Prometheus name.

Reference parity: lib/runtime/src/metrics/prometheus_names.rs — the
reference centralizes metric-name constants so dashboards, alerts, the
planner's scrape source, and the emitting components can never drift
apart. Same rule here: emitters (http/metrics.py, runtime/system_server.py)
and consumers (planner/metrics_source.py) import these constants instead
of repeating strings.

Naming scheme: ``dynamo_tpu_<subsystem>_<metric>[_unit][_total]``.
"""

from __future__ import annotations

# -- frontend (http/metrics.py) ---------------------------------------------
FRONTEND_PREFIX = "dynamo_tpu_frontend"
FRONTEND_REQUESTS_TOTAL = f"{FRONTEND_PREFIX}_requests_total"
FRONTEND_INFLIGHT = f"{FRONTEND_PREFIX}_inflight_requests"
FRONTEND_REQUEST_DURATION = f"{FRONTEND_PREFIX}_request_duration_seconds"
FRONTEND_TTFT = f"{FRONTEND_PREFIX}_time_to_first_token_seconds"
FRONTEND_ITL = f"{FRONTEND_PREFIX}_inter_token_latency_seconds"
FRONTEND_OUTPUT_TOKENS_TOTAL = f"{FRONTEND_PREFIX}_output_tokens_total"
FRONTEND_INPUT_TOKENS_TOTAL = f"{FRONTEND_PREFIX}_input_tokens_total"

# -- engine (runtime/system_server.py engine_stats_prometheus) ---------------
ENGINE_PREFIX = "dynamo_tpu_engine"


def engine_gauge(stat_key: str) -> str:
    """Engine stats-dict key → canonical gauge name (system server)."""
    return f"{ENGINE_PREFIX}_{stat_key}"


ENGINE_ACTIVE_SEQS = engine_gauge("active_seqs")
ENGINE_WAITING = engine_gauge("waiting")
ENGINE_KV_USAGE = engine_gauge("kv_usage")
ENGINE_FREE_BLOCKS = engine_gauge("free_blocks")
ENGINE_CACHED_BLOCKS = engine_gauge("cached_blocks")
ENGINE_TOTAL_BLOCKS = engine_gauge("total_blocks")
ENGINE_DECODE_STEPS = engine_gauge("decode_steps")
ENGINE_PREFILL_TOKENS = engine_gauge("prefill_tokens")
ENGINE_GENERATED_TOKENS = engine_gauge("generated_tokens")
ENGINE_SLEEP_LEVEL = engine_gauge("sleep_level")
ENGINE_PIPELINE_DEPTH = engine_gauge("pipeline_depth")
ENGINE_INFLIGHT_BURSTS = engine_gauge("inflight_bursts")
ENGINE_PREEMPTIONS = engine_gauge("preemptions")
# Overload plane inputs (engine admission backpressure): waiting-queue
# depth + the admission refusal watermark (ride load reports router-ward)
# and requests shed at dequeue with an expired deadline.
ENGINE_QUEUE_DEPTH = engine_gauge("queue_depth")
ENGINE_KV_HIGH_WATERMARK = engine_gauge("kv_high_watermark")
ENGINE_DEADLINE_SHEDS = engine_gauge("deadline_sheds")
# Drain plane input: 1 while the engine refuses new admissions because a
# live handoff drain is in progress (rides load reports router-ward so
# KvScheduler stops placing work here immediately).
ENGINE_DRAINING = engine_gauge("draining")
# Megakernel coverage (decode-path observability): decode bursts that
# dispatched on the fused megakernel path vs the XLA fallback, and the
# count of per-(width bucket, variant) compile-failure demotions. The
# per-variant split rides the nested stats sub-dict (flattened at scrape
# like the kvbm sub-dict); bench.py records the fused fraction so a
# silent demotion can never masquerade as a plain perf regression.
ENGINE_MK_FUSED_BURSTS = engine_gauge("mk_fused_bursts")
ENGINE_MK_FALLBACK_BURSTS = engine_gauge("mk_fallback_bursts")
ENGINE_MK_DEMOTED_VARIANTS = engine_gauge("mk_demoted_variants")
# Tick budgeter (engines/tpu/tick_budget.py): the EFFECTIVE per-tick
# prefill token budget (0 = budgeter off, unbounded admission), the
# budgeter state (0 off, 1 throughput/ceiling, 2 adaptive, 3 floor /
# brownout-squeezed), the compile-time chunk size the budget is consumed
# in, and watermark-hold rollovers (budget returned to decode, not
# idled). A silent budget collapse shows up HERE, not as a mystery TTFT
# regression.
ENGINE_PREFILL_BUDGET_TOKENS = engine_gauge("prefill_budget_tokens")
ENGINE_BUDGET_STATE = engine_gauge("budget_state")
ENGINE_PREFILL_CHUNK_TOKENS = engine_gauge("prefill_chunk_tokens")
ENGINE_BUDGET_ROLLOVERS = engine_gauge("budget_rollovers")

# -- engine step loop (engines/metrics.py EngineStepMetrics) -----------------
ENGINE_STEP_DURATION = f"{ENGINE_PREFIX}_step_duration_seconds"
ENGINE_BATCH_OCCUPANCY = f"{ENGINE_PREFIX}_batch_occupancy"
ENGINE_STEP_PREFILL_TOKENS = f"{ENGINE_PREFIX}_prefill_tokens_per_step"
ENGINE_STEP_DECODE_TOKENS = f"{ENGINE_PREFIX}_decode_tokens_per_step"
# Decode-tick pipelining (engines/tpu/engine.py dispatch/reap split):
# host_gap = device wait injected by the host between a burst's readback
# completing and the next dispatch (0 when another burst was already in
# flight); inflight_depth = bursts in flight at each dispatch.
ENGINE_HOST_GAP = f"{ENGINE_PREFIX}_host_gap_seconds"
ENGINE_INFLIGHT_DEPTH = f"{ENGINE_PREFIX}_inflight_depth"

# -- router (router/router.py KvRouter + router/scheduler.py) ----------------
ROUTER_PREFIX = "dynamo_tpu_router"
ROUTER_DECISIONS_TOTAL = f"{ROUTER_PREFIX}_decisions_total"
ROUTER_OVERLAP_BLOCKS = f"{ROUTER_PREFIX}_overlap_blocks"
ROUTER_WORKER_LOAD_BLOCKS = f"{ROUTER_PREFIX}_worker_load_blocks"
ROUTER_WORKER_KV_USAGE = f"{ROUTER_PREFIX}_worker_kv_usage"
ROUTER_KV_EVENTS_TOTAL = f"{ROUTER_PREFIX}_kv_events_total"
# Link-cost model input: EWMA transfer bandwidth per (src prefill worker,
# dst decode worker) pair, as the scheduler's select_worker sees it.
ROUTER_LINK_BANDWIDTH = f"{ROUTER_PREFIX}_link_bandwidth_bytes_per_s"

# -- KVBM (kvbm/manager.py TieredKvManager + kvbm/connector.py) --------------
KVBM_PREFIX = "dynamo_tpu_kvbm"
KVBM_OFFLOAD_BLOCKS_TOTAL = f"{KVBM_PREFIX}_offload_blocks_total"
KVBM_OFFLOAD_BYTES_TOTAL = f"{KVBM_PREFIX}_offload_bytes_total"
KVBM_ONBOARD_BLOCKS_TOTAL = f"{KVBM_PREFIX}_onboard_blocks_total"
KVBM_ONBOARD_BYTES_TOTAL = f"{KVBM_PREFIX}_onboard_bytes_total"
KVBM_LOOKUP_HITS_TOTAL = f"{KVBM_PREFIX}_lookup_hits_total"
KVBM_LOOKUP_MISSES_TOTAL = f"{KVBM_PREFIX}_lookup_misses_total"
KVBM_TIER_BLOCKS = f"{KVBM_PREFIX}_tier_blocks"
KVBM_TIER_EVICTIONS_TOTAL = f"{KVBM_PREFIX}_tier_evictions_total"
KVBM_POOL_PRESSURE_TRUNCATIONS_TOTAL = (
    f"{KVBM_PREFIX}_pool_pressure_truncations_total"
)
KVBM_FAILED_LOADS_TOTAL = f"{KVBM_PREFIX}_failed_loads_total"
# Integrity: persisted KV (checkpoint manifest arrays, disk-tier npz
# spills) whose CRC32 did not match on restore — counted as a miss, never
# installed, never a crash. Labeled by source (checkpoint | disk).
KVBM_RESTORE_CORRUPTION_TOTAL = f"{KVBM_PREFIX}_restore_corruption_total"
# Tier-flow latency (kv_reuse_observability.md): one offload burst /
# onboard walk, wall time. Direction is the family; the tier the blocks
# landed in / came from rides the {tier} label.
KVBM_OFFLOAD_DURATION = f"{KVBM_PREFIX}_offload_duration_seconds"
KVBM_ONBOARD_DURATION = f"{KVBM_PREFIX}_onboard_duration_seconds"
# Write-through losses: a committed block was evicted from the device pool
# before the offload worker could gather it ({reason}: device_evicted).
KVBM_OFFLOAD_MISSED_TOTAL = f"{KVBM_PREFIX}_offload_missed_total"
# Speculative onboarding (kv_prefetch.md): one prefetch lease per routed
# request with a tier-resident hint. {outcome}: claimed (admission joined
# the lease), revoked (abort/shed released it), skipped (nothing tier-
# resident / pool already warm), error (walk died). Blocks ride the same
# split as {outcome}: used | wasted — wasted is the bounded cost of
# speculation and the number the cold leg must hold at zero.
KVBM_PREFETCHES_TOTAL = f"{KVBM_PREFIX}_prefetches_total"
KVBM_PREFETCH_BLOCKS_TOTAL = f"{KVBM_PREFIX}_prefetch_blocks_total"
# Onboard wall time hidden behind queue wait + suffix prefill: walk wall
# time minus the stall admission actually observed joining the lease.
KVBM_PREFETCH_OVERLAP_SECONDS = f"{KVBM_PREFIX}_prefetch_overlap_seconds"

# -- KV-reuse plane (runtime/kv_reuse_observe.py KvReusePlane) ----------------
KVCACHE_PREFIX = "dynamo_tpu_kvcache"
# Prefix-cache hits by the tier the hit resolved from (device | host |
# disk | remote) and requests that found no cached prefix at all. The
# hit-rate gauge is the render-time ratio of these monotonic sources.
KVCACHE_HITS_TOTAL = f"{KVCACHE_PREFIX}_hits_total"
KVCACHE_MISSES_TOTAL = f"{KVCACHE_PREFIX}_misses_total"
KVCACHE_HIT_RATE = f"{KVCACHE_PREFIX}_hit_rate"
# Cache ROI: prefill tokens served from cache vs recomputed, and the
# estimated prefill seconds the cache saved (cached tokens x EWMA
# per-token prefill cost — the same number stamped per-request onto the
# trajectory rollup).
KVCACHE_REUSED_TOKENS_TOTAL = f"{KVCACHE_PREFIX}_reused_prefill_tokens_total"
KVCACHE_RECOMPUTED_TOKENS_TOTAL = (
    f"{KVCACHE_PREFIX}_recomputed_prefill_tokens_total"
)
KVCACHE_PREFILL_SECONDS_SAVED_TOTAL = (
    f"{KVCACHE_PREFIX}_prefill_seconds_saved_total"
)
KVCACHE_PREFILL_COST_PER_TOKEN = (
    f"{KVCACHE_PREFIX}_prefill_cost_per_token_seconds"
)
# Space-saving popularity sketch: live tracked-prefix count (bounded by
# capacity by construction), min-replacements (sketch churn under a
# heavy-tailed workload), and the p99 sketch lookup latency recorded by
# the scale harness (tests/test_kv_reuse_scale.py).
KVCACHE_SKETCH_TRACKED_PREFIXES = f"{KVCACHE_PREFIX}_sketch_tracked_prefixes"
KVCACHE_SKETCH_REPLACEMENTS_TOTAL = (
    f"{KVCACHE_PREFIX}_sketch_replacements_total"
)
KVCACHE_SKETCH_LOOKUP_P99_SECONDS = (
    f"{KVCACHE_PREFIX}_sketch_lookup_p99_seconds"
)
# Tier evictions by (tier, reason): arena_full (straight spill past a
# full pinned arena) | capacity (LRU overflow) | corrupt (CRC drop on
# read-back). Mirrors kvbm_tier_evictions_total with the reason split the
# popularity-eviction follow-on acts on.
KVCACHE_EVICTIONS_TOTAL = f"{KVCACHE_PREFIX}_evictions_total"

# -- device/runtime plane (runtime/device_observe.py) ------------------------
RUNTIME_PREFIX = "dynamo_tpu_runtime"
# Compile telemetry (watched_jit / CompileWatcher): every jax.jit site.
RUNTIME_COMPILES_TOTAL = f"{RUNTIME_PREFIX}_compiles_total"
RUNTIME_COMPILE_SIGNATURES = f"{RUNTIME_PREFIX}_compile_signatures"
RUNTIME_COMPILE_SECONDS = f"{RUNTIME_PREFIX}_compile_seconds"
RUNTIME_RECOMPILE_STORMS_TOTAL = f"{RUNTIME_PREFIX}_recompile_storms_total"
# HBM ledger (structural byte accounting + device.memory_stats mirror).
RUNTIME_HBM_BYTES = f"{RUNTIME_PREFIX}_hbm_bytes"
RUNTIME_HBM_DEVICE_BYTES = f"{RUNTIME_PREFIX}_hbm_device_bytes"
# Flight recorder rings (engine tick loop + device-thread runner).
RUNTIME_FLIGHT_EVENTS_TOTAL = f"{RUNTIME_PREFIX}_flight_events_total"
RUNTIME_FLIGHT_OVERWRITTEN_TOTAL = f"{RUNTIME_PREFIX}_flight_overwritten_total"
# On-demand jax.profiler captures (POST /debug/profile).
RUNTIME_PROFILER_CAPTURES_TOTAL = f"{RUNTIME_PREFIX}_profiler_captures_total"

# -- disagg (disagg/handlers.py DecodeHandler) -------------------------------
DISAGG_PREFIX = "dynamo_tpu_disagg"
DISAGG_TRANSFERS_TOTAL = f"{DISAGG_PREFIX}_transfers_total"
# One failed pull ATTEMPT, labeled by classified error_kind (timeout vs
# connection vs decode vs other). Attempts retry with anchor-resume; a
# pull that exhausts retries is the 2×-cost path (second full prefill).
DISAGG_TRANSFER_FAILURES_TOTAL = f"{DISAGG_PREFIX}_transfer_failures_total"
# Retried pull attempts (attempt 2+). Anchor-resume means a retry only
# moves the not-yet-imported tail, so retries are cheap but visible.
DISAGG_PULL_RETRIES_TOTAL = f"{DISAGG_PREFIX}_pull_retries_total"
# Per-src circuit breaker: state transitions {src, to∈open|half_open|
# closed} and a 0/1 open gauge per src. An open breaker is advertised in
# load reports and prices the (src, this worker) pair out of disagg
# placement (router/scheduler.py LinkCostModel.set_fault).
DISAGG_BREAKER_TRANSITIONS_TOTAL = f"{DISAGG_PREFIX}_breaker_transitions_total"
DISAGG_BREAKER_OPEN = f"{DISAGG_PREFIX}_breaker_open"
DISAGG_BLOCKS_PULLED_TOTAL = f"{DISAGG_PREFIX}_blocks_pulled_total"
DISAGG_BYTES_PULLED_TOTAL = f"{DISAGG_PREFIX}_bytes_pulled_total"
# Serialized KV payload bytes by wire dtype (disagg/wire.py schema v2):
# int8-on-the-wire vs densified is THE transfer-bound disagg lever.
DISAGG_KV_WIRE_BYTES_TOTAL = f"{DISAGG_PREFIX}_kv_wire_bytes_total"
DISAGG_TRANSFER_DURATION = f"{DISAGG_PREFIX}_transfer_duration_seconds"
# Observed per-(src, dst) transfer bandwidth EWMA, measured at the decode
# worker's pull path and folded into the router via load reports.
DISAGG_LINK_BANDWIDTH = f"{DISAGG_PREFIX}_link_bandwidth_bytes_per_s"

# -- migration (llm/migration.py Migration) ----------------------------------
MIGRATION_PREFIX = "dynamo_tpu_migration"
# Re-dispatches of a live stream to another worker, by failure reason
# (connection | timeout | no_instances | disagg | other).
MIGRATION_MIGRATIONS_TOTAL = f"{MIGRATION_PREFIX}_migrations_total"
# Streams that failed AFTER exhausting the migration budget (attempt
# limit or the re-prefill token cap) — each one reached the client.
MIGRATION_EXHAUSTED_TOTAL = f"{MIGRATION_PREFIX}_exhausted_total"
# Prompt+carried tokens re-prefilled by migrations (the cost the
# re-prefill cap bounds).
MIGRATION_REPREFILL_TOKENS_TOTAL = f"{MIGRATION_PREFIX}_reprefill_tokens_total"

# -- fault plane (runtime/faults.py FaultPlane) ------------------------------
FAULTS_PREFIX = "dynamo_tpu_faults"
FAULTS_ARMED = f"{FAULTS_PREFIX}_armed"
FAULTS_INJECTIONS_TOTAL = f"{FAULTS_PREFIX}_injections_total"

# -- drain plane (runtime/drain.py DrainController) ---------------------------
DRAIN_PREFIX = "dynamo_tpu_drain"
# State machine: 0 serving, 1 draining, 2 drained.
DRAIN_STATE = f"{DRAIN_PREFIX}_state"
# Completed drains (a worker usually drains once per life; a counter so
# aborted/retried drains are visible across restarts of the controller).
DRAIN_DRAINS_TOTAL = f"{DRAIN_PREFIX}_drains_total"
# In-flight streams resolved by the drain, by ladder rung: handoff (live
# KV moved, zero re-prefill), reprefill (fell back to PR 7 migration —
# the frontend re-prefills on another worker), requeue (never admitted;
# typed migratable refusal re-dispatches it whole).
DRAIN_STREAMS_TOTAL = f"{DRAIN_PREFIX}_streams_total"
# Serialized wire bytes of exported handoff KV (payload + scales).
DRAIN_HANDOFF_BYTES_TOTAL = f"{DRAIN_PREFIX}_handoff_bytes_total"
# Peer adoptions refused (capacity, shape/seed mismatch, peer draining) —
# each refusal walks the source further down the peer list / ladder.
DRAIN_PEER_REFUSALS_TOTAL = f"{DRAIN_PREFIX}_peer_refusals_total"
# Wall time of one full drain (trigger -> drained).
DRAIN_DURATION = f"{DRAIN_PREFIX}_duration_seconds"

# -- crash plane (runtime/liveness.py) ---------------------------------------
LIVENESS_PREFIX = "dynamo_tpu_liveness"
# Per-worker liveness state machine: 0 alive, 1 suspect (2 missed load
# reports), 2 dead (drop_worker reconciliation ran, streams aborted).
LIVENESS_WORKER_STATE = f"{LIVENESS_PREFIX}_worker_state"
# Last-report-to-declared-dead latency; bounded by dead_after x interval_s
# by construction (no TCP timeouts anywhere in the path).
LIVENESS_DETECTION_SECONDS = f"{LIVENESS_PREFIX}_detection_seconds"
# Packets from a prior worker incarnation dropped at a fencing seam
# (load_report | router_load | pull_reply | handoff_ack | tcp) — counted,
# never applied. load_report = the liveness tracker's fence, router_load =
# the scheduler's (separate subscriptions to one topic; distinct labels so
# one zombie packet is never double-counted).
LIVENESS_STALE_DROPS_TOTAL = (
    f"{LIVENESS_PREFIX}_stale_incarnation_drops_total"
)
# Warm-restart KV checkpoint restore: wall time and outcome (restored |
# partial | empty | cold_mismatch | cold_corrupt | cold_error). Every
# cold_* is a logged cold start, never a crash loop.
LIVENESS_RESTORE_SECONDS = f"{LIVENESS_PREFIX}_restore_seconds"
LIVENESS_RESTORE_OUTCOME_TOTAL = f"{LIVENESS_PREFIX}_restore_outcome_total"

# -- planner / elasticity plane (planner/planner_core.py, planner/elastic.py) -
PLANNER_PREFIX = "dynamo_tpu_planner"
# Correction-factor feedback (docs/design_docs/elasticity.md): decayed EWMA
# of observed/predicted SLA ratios folded into the interpolator outputs,
# labeled by stage (ttft | itl). 1.0 = the profile is honest; 2.0 = the
# fleet is twice as slow as profiled and sizing is being corrected up.
PLANNER_CORRECTION_FACTOR = f"{PLANNER_PREFIX}_correction_factor"
# The last computed plan, per pool (prefill | decode) — what the planner
# WANTS; the elastic controller's state gauge says what it is DOING.
PLANNER_DESIRED_REPLICAS = f"{PLANNER_PREFIX}_desired_replicas"
# Plan-transition state machine: 0 steady, 1 scaling_up, 2 scaling_down,
# 3 converged (an actuation just completed; cooldown running).
PLANNER_STATE = f"{PLANNER_PREFIX}_state"
PLANNER_TRANSITIONS_TOTAL = f"{PLANNER_PREFIX}_transitions_total"
# Plans the planner handed the connector (one per adjustment interval once
# predictors warm up).
PLANNER_APPLIES_TOTAL = f"{PLANNER_PREFIX}_applies_total"
# Plan changes suppressed by hysteresis/cooldown — oscillating load shows
# up here instead of as fleet churn.
PLANNER_HOLDS_TOTAL = f"{PLANNER_PREFIX}_holds_total"
# Workers retired through the drain plane (zero-re-prefill live handoff),
# by mode (planned = scale-down, preemption = spot reclaim).
PLANNER_SCALE_DOWN_DRAINS_TOTAL = f"{PLANNER_PREFIX}_scale_down_drains_total"
# Replicas launched but not yet counted: a scale-up replica only counts
# once its /readyz (warm restore included) goes green.
PLANNER_SCALE_UP_PENDING = f"{PLANNER_PREFIX}_scale_up_pending"

# -- overload plane (runtime/overload.py OverloadController) -----------------
OVERLOAD_PREFIX = "dynamo_tpu_overload"
# Brownout state machine: 0 healthy, 1 brownout (max_tokens clamped,
# speculative decode off), 2 shed (new admissions refused 503).
OVERLOAD_STATE = f"{OVERLOAD_PREFIX}_state"
OVERLOAD_TRANSITIONS_TOTAL = f"{OVERLOAD_PREFIX}_transitions_total"
# Admissions refused, by reason (queue_full | predicted_delay |
# deadline_expired | brownout_shed) — every shed reached a client as a
# typed 429/503/504 + Retry-After.
OVERLOAD_SHED_TOTAL = f"{OVERLOAD_PREFIX}_shed_total"
OVERLOAD_ADMITTED_TOTAL = f"{OVERLOAD_PREFIX}_admitted_total"
# Bounded EDF admission queue: live depth and the wait granted requests
# actually paid (the predicted-delay shed keeps the tail of this
# histogram inside max_queue_delay_s).
OVERLOAD_QUEUE_DEPTH = f"{OVERLOAD_PREFIX}_queue_depth"
OVERLOAD_QUEUE_DELAY = f"{OVERLOAD_PREFIX}_queue_delay_seconds"
# Requests whose deadline expired before admission (dead on arrival or
# expired mid-queue) — shed before any prefill work.
OVERLOAD_DEADLINE_EXPIRED_TOTAL = f"{OVERLOAD_PREFIX}_deadline_expired_total"

# -- parser plane (parsers/observe.py ParserPlane) ----------------------------
PARSER_PREFIX = "dynamo_tpu_parser"
# Tool calls fully streamed through the incremental jail, by dialect.
PARSER_TOOL_CALLS_TOTAL = f"{PARSER_PREFIX}_tool_calls_total"
# Argument-delta characters emitted while the call was still being
# generated — the incremental jail's reason to exist (the old jail held
# every argument byte until stream end).
PARSER_ARGS_DELTA_CHARS_TOTAL = f"{PARSER_PREFIX}_args_delta_chars_total"
# Degradation-ladder activations by dialect and reason (truncated |
# bad_nesting | drift | buffer_cap | ...) — a malformed call sealed or
# returned to content, never a dropped stream.
PARSER_DEGRADED_CALLS_TOTAL = f"{PARSER_PREFIX}_degraded_calls_total"
# Calls whose argument string was unparseable and shipped as a lossy
# {"__raw__": ...} wrap (tool_calling._normalize and its streaming twin);
# the emitted call carries degraded=true so clients and the SLO plane can
# see lossy parses.
PARSER_DEGRADED_ARGS_TOTAL = f"{PARSER_PREFIX}_degraded_args_total"
# Parser BUGS (not malformed model output): each surfaced as a terminal
# typed SSE error frame (error_kind=tool_call_parse).
PARSER_EXCEPTIONS_TOTAL = f"{PARSER_PREFIX}_exceptions_total"
# Tool-enabled streams through the jail by outcome (clean | degraded |
# error).
PARSER_STREAMS_TOTAL = f"{PARSER_PREFIX}_streams_total"
# Peak jailed-buffer size (chars) — bounded by the jail's buffer cap.
PARSER_JAIL_BUFFERED_PEAK_CHARS = (
    f"{PARSER_PREFIX}_jail_buffered_peak_chars"
)

# -- perf ledger (runtime/perf_ledger.py PerfLedger) --------------------------
PERF_PREFIX = "dynamo_tpu_perf"
# Rolling-window median step wall time per (width, variant, path) decode
# shape — the always-on attribution the regression sentinel judges.
PERF_STEP_P50_SECONDS = f"{PERF_PREFIX}_step_p50_seconds"
# Rolling-window p99 step wall time per shape — tail drift shows here
# before the median moves.
PERF_STEP_P99_SECONDS = f"{PERF_PREFIX}_step_p99_seconds"
# Rolling-window median host gap (CPU time the device sat idle between
# reap and the next dispatch) per shape.
PERF_HOST_GAP_P50_SECONDS = f"{PERF_PREFIX}_host_gap_p50_seconds"
# Rolling-window median dispatch-side host cost per shape (the portion of
# the step spent building + launching the burst).
PERF_DISPATCH_P50_SECONDS = f"{PERF_PREFIX}_dispatch_p50_seconds"
# Rolling-window median reap-side host cost per shape (device_get + state
# update after the burst completed).
PERF_REAP_P50_SECONDS = f"{PERF_PREFIX}_reap_p50_seconds"
# Rolling-window decode throughput (tokens/s) per shape.
PERF_TOKENS_PER_SEC = f"{PERF_PREFIX}_tokens_per_sec"
# Measured tok/s divided by the pure-arithmetic bandwidth roofline
# (runtime/roofline.py, the same model bench's 70B projection leg uses)
# at the window's median occupancy and context — 1.0 is the HBM wall.
PERF_ROOFLINE_FRACTION = f"{PERF_PREFIX}_roofline_fraction"
# Rolling-window prefill throughput (tokens/s) per pow2 chunk bucket,
# from the admission loop's per-round stamps.
PERF_PREFILL_TOKENS_PER_SEC = f"{PERF_PREFIX}_prefill_tokens_per_sec"
# Live samples currently inside each shape's rolling window (TTL-pruned);
# verdicts are withheld below the min-sample floor.
PERF_WINDOW_SAMPLES = f"{PERF_PREFIX}_window_samples"
# Typed perf anomalies raised by the sentinel, labeled by kind
# (step_regression | toks_regression) — the lint-pinned counter ISSUE 19
# pages on.
PERF_ANOMALIES_TOTAL = f"{PERF_PREFIX}_anomalies_total"
# Steady-state fingerprints loaded from the persisted ledger at startup
# (0 on cold start).
PERF_FINGERPRINT_LOADED = f"{PERF_PREFIX}_fingerprints_loaded"
# Fingerprint persistence failures by op (load | store) — a corrupt or
# vanished file degrades to cold start and counts here, never crashes.
PERF_FINGERPRINT_FAILURES_TOTAL = (
    f"{PERF_PREFIX}_fingerprint_failures_total"
)

# -- SLO plane (runtime/trajectory.py SloTracker) -----------------------------
SLO_PREFIX = "dynamo_tpu_slo"
# Rolling-window fraction of finished streams that met BOTH the TTFT and
# ITL SLAs, labeled by window (5m | 60m). 1.0 = every stream inside SLA.
SLO_GOODPUT = f"{SLO_PREFIX}_goodput_ratio"
# Finished streams by SLO verdict (good | breach) — the goodput ratio's
# monotonic source of truth across scrapes.
SLO_STREAMS_TOTAL = f"{SLO_PREFIX}_streams_total"
# Error-budget burn rate per window: breach fraction ÷ (1 − slo_target).
# 1.0 = burning exactly the budget; a multi-window alert fires when BOTH
# the fast and slow windows burn hot (the SRE-workbook shape).
SLO_BURN_RATE = f"{SLO_PREFIX}_burn_rate"
# p99 of each phase's per-request duration over the trajectory window —
# which phase (queue / prefill / kv_transfer / decode / handoff_stall /
# overhead) dominates the tail, as a number a dashboard can rank.
SLO_PHASE_P99_MS = f"{SLO_PREFIX}_phase_p99_contribution_ms"

ALL_FRONTEND = (
    FRONTEND_REQUESTS_TOTAL,
    FRONTEND_INFLIGHT,
    FRONTEND_REQUEST_DURATION,
    FRONTEND_TTFT,
    FRONTEND_ITL,
    FRONTEND_OUTPUT_TOKENS_TOTAL,
    FRONTEND_INPUT_TOKENS_TOTAL,
)

ALL_ROUTER = (
    ROUTER_DECISIONS_TOTAL,
    ROUTER_OVERLAP_BLOCKS,
    ROUTER_WORKER_LOAD_BLOCKS,
    ROUTER_WORKER_KV_USAGE,
    ROUTER_KV_EVENTS_TOTAL,
    ROUTER_LINK_BANDWIDTH,
)

ALL_KVBM = (
    KVBM_OFFLOAD_BLOCKS_TOTAL,
    KVBM_OFFLOAD_BYTES_TOTAL,
    KVBM_ONBOARD_BLOCKS_TOTAL,
    KVBM_ONBOARD_BYTES_TOTAL,
    KVBM_LOOKUP_HITS_TOTAL,
    KVBM_LOOKUP_MISSES_TOTAL,
    KVBM_TIER_BLOCKS,
    KVBM_TIER_EVICTIONS_TOTAL,
    KVBM_POOL_PRESSURE_TRUNCATIONS_TOTAL,
    KVBM_FAILED_LOADS_TOTAL,
    KVBM_RESTORE_CORRUPTION_TOTAL,
    KVBM_OFFLOAD_DURATION,
    KVBM_ONBOARD_DURATION,
    KVBM_OFFLOAD_MISSED_TOTAL,
    KVBM_PREFETCHES_TOTAL,
    KVBM_PREFETCH_BLOCKS_TOTAL,
    KVBM_PREFETCH_OVERLAP_SECONDS,
)

ALL_KVCACHE = (
    KVCACHE_HITS_TOTAL,
    KVCACHE_MISSES_TOTAL,
    KVCACHE_HIT_RATE,
    KVCACHE_REUSED_TOKENS_TOTAL,
    KVCACHE_RECOMPUTED_TOKENS_TOTAL,
    KVCACHE_PREFILL_SECONDS_SAVED_TOTAL,
    KVCACHE_PREFILL_COST_PER_TOKEN,
    KVCACHE_SKETCH_TRACKED_PREFIXES,
    KVCACHE_SKETCH_REPLACEMENTS_TOTAL,
    KVCACHE_SKETCH_LOOKUP_P99_SECONDS,
    KVCACHE_EVICTIONS_TOTAL,
)

ALL_DISAGG = (
    DISAGG_TRANSFERS_TOTAL,
    DISAGG_TRANSFER_FAILURES_TOTAL,
    DISAGG_PULL_RETRIES_TOTAL,
    DISAGG_BREAKER_TRANSITIONS_TOTAL,
    DISAGG_BREAKER_OPEN,
    DISAGG_BLOCKS_PULLED_TOTAL,
    DISAGG_BYTES_PULLED_TOTAL,
    DISAGG_KV_WIRE_BYTES_TOTAL,
    DISAGG_TRANSFER_DURATION,
    DISAGG_LINK_BANDWIDTH,
)

ALL_MIGRATION = (
    MIGRATION_MIGRATIONS_TOTAL,
    MIGRATION_EXHAUSTED_TOTAL,
    MIGRATION_REPREFILL_TOKENS_TOTAL,
)

ALL_FAULTS = (
    FAULTS_ARMED,
    FAULTS_INJECTIONS_TOTAL,
)

ALL_DRAIN = (
    DRAIN_STATE,
    DRAIN_DRAINS_TOTAL,
    DRAIN_STREAMS_TOTAL,
    DRAIN_HANDOFF_BYTES_TOTAL,
    DRAIN_PEER_REFUSALS_TOTAL,
    DRAIN_DURATION,
)

ALL_LIVENESS = (
    LIVENESS_WORKER_STATE,
    LIVENESS_DETECTION_SECONDS,
    LIVENESS_STALE_DROPS_TOTAL,
    LIVENESS_RESTORE_SECONDS,
    LIVENESS_RESTORE_OUTCOME_TOTAL,
)

ALL_PLANNER = (
    PLANNER_CORRECTION_FACTOR,
    PLANNER_DESIRED_REPLICAS,
    PLANNER_STATE,
    PLANNER_TRANSITIONS_TOTAL,
    PLANNER_APPLIES_TOTAL,
    PLANNER_HOLDS_TOTAL,
    PLANNER_SCALE_DOWN_DRAINS_TOTAL,
    PLANNER_SCALE_UP_PENDING,
)

ALL_SLO = (
    SLO_GOODPUT,
    SLO_STREAMS_TOTAL,
    SLO_BURN_RATE,
    SLO_PHASE_P99_MS,
)

ALL_PARSER = (
    PARSER_TOOL_CALLS_TOTAL,
    PARSER_ARGS_DELTA_CHARS_TOTAL,
    PARSER_DEGRADED_CALLS_TOTAL,
    PARSER_DEGRADED_ARGS_TOTAL,
    PARSER_EXCEPTIONS_TOTAL,
    PARSER_STREAMS_TOTAL,
    PARSER_JAIL_BUFFERED_PEAK_CHARS,
)

ALL_OVERLOAD = (
    OVERLOAD_STATE,
    OVERLOAD_TRANSITIONS_TOTAL,
    OVERLOAD_SHED_TOTAL,
    OVERLOAD_ADMITTED_TOTAL,
    OVERLOAD_QUEUE_DEPTH,
    OVERLOAD_QUEUE_DELAY,
    OVERLOAD_DEADLINE_EXPIRED_TOTAL,
)

ALL_RUNTIME = (
    RUNTIME_COMPILES_TOTAL,
    RUNTIME_COMPILE_SIGNATURES,
    RUNTIME_COMPILE_SECONDS,
    RUNTIME_RECOMPILE_STORMS_TOTAL,
    RUNTIME_HBM_BYTES,
    RUNTIME_HBM_DEVICE_BYTES,
    RUNTIME_FLIGHT_EVENTS_TOTAL,
    RUNTIME_FLIGHT_OVERWRITTEN_TOTAL,
    RUNTIME_PROFILER_CAPTURES_TOTAL,
)

ALL_ENGINE = (
    ENGINE_ACTIVE_SEQS,
    ENGINE_WAITING,
    ENGINE_KV_USAGE,
    ENGINE_FREE_BLOCKS,
    ENGINE_CACHED_BLOCKS,
    ENGINE_TOTAL_BLOCKS,
    ENGINE_DECODE_STEPS,
    ENGINE_PREFILL_TOKENS,
    ENGINE_GENERATED_TOKENS,
    ENGINE_SLEEP_LEVEL,
    ENGINE_PIPELINE_DEPTH,
    ENGINE_INFLIGHT_BURSTS,
    ENGINE_PREEMPTIONS,
    ENGINE_QUEUE_DEPTH,
    ENGINE_KV_HIGH_WATERMARK,
    ENGINE_DEADLINE_SHEDS,
    ENGINE_DRAINING,
    ENGINE_MK_FUSED_BURSTS,
    ENGINE_MK_FALLBACK_BURSTS,
    ENGINE_MK_DEMOTED_VARIANTS,
    ENGINE_PREFILL_BUDGET_TOKENS,
    ENGINE_BUDGET_STATE,
    ENGINE_PREFILL_CHUNK_TOKENS,
    ENGINE_BUDGET_ROLLOVERS,
    ENGINE_STEP_DURATION,
    ENGINE_BATCH_OCCUPANCY,
    ENGINE_STEP_PREFILL_TOKENS,
    ENGINE_STEP_DECODE_TOKENS,
    ENGINE_HOST_GAP,
    ENGINE_INFLIGHT_DEPTH,
)

ALL_PERF = (
    PERF_STEP_P50_SECONDS,
    PERF_STEP_P99_SECONDS,
    PERF_HOST_GAP_P50_SECONDS,
    PERF_DISPATCH_P50_SECONDS,
    PERF_REAP_P50_SECONDS,
    PERF_TOKENS_PER_SEC,
    PERF_ROOFLINE_FRACTION,
    PERF_PREFILL_TOKENS_PER_SEC,
    PERF_WINDOW_SAMPLES,
    PERF_ANOMALIES_TOTAL,
    PERF_FINGERPRINT_LOADED,
    PERF_FINGERPRINT_FAILURES_TOTAL,
)
