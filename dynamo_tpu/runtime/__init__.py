"""Distributed runtime public API (ref: dynamo-runtime crate, lib/runtime)."""

from dynamo_tpu.runtime.component import (
    Client,
    Component,
    Endpoint,
    Instance,
    Namespace,
    NoInstancesError,
    RouterMode,
    ServedEndpoint,
)
from dynamo_tpu.runtime.context import Context, EngineStream, current_context
from dynamo_tpu.runtime.distributed import DistributedRuntime, LocalRequestPlane
from dynamo_tpu.runtime.engine import AsyncEngine, as_engine, collect
from dynamo_tpu.runtime.metric_names import (
    ALL_DISAGG,
    ALL_DRAIN,
    ALL_ENGINE,
    ALL_FAULTS,
    ALL_FRONTEND,
    ALL_KVBM,
    ALL_LIVENESS,
    ALL_MIGRATION,
    ALL_OVERLOAD,
    ALL_PARSER,
    ALL_PLANNER,
    ALL_ROUTER,
    ALL_RUNTIME,
    ALL_SLO,
)
from dynamo_tpu.runtime.pipeline import (
    MapRequestOperator,
    MapStreamOperator,
    Operator,
    PassthroughOperator,
    build_pipeline,
)
from dynamo_tpu.runtime.tasks import TaskTracker

__all__ = [
    "ALL_DISAGG",
    "ALL_DRAIN",
    "ALL_ENGINE",
    "ALL_FAULTS",
    "ALL_FRONTEND",
    "ALL_KVBM",
    "ALL_LIVENESS",
    "ALL_MIGRATION",
    "ALL_OVERLOAD",
    "ALL_PARSER",
    "ALL_PLANNER",
    "ALL_ROUTER",
    "ALL_RUNTIME",
    "ALL_SLO",
    "AsyncEngine",
    "Client",
    "Component",
    "Context",
    "DistributedRuntime",
    "Endpoint",
    "EngineStream",
    "Instance",
    "LocalRequestPlane",
    "MapRequestOperator",
    "MapStreamOperator",
    "Namespace",
    "NoInstancesError",
    "Operator",
    "PassthroughOperator",
    "RouterMode",
    "ServedEndpoint",
    "TaskTracker",
    "as_engine",
    "build_pipeline",
    "collect",
    "current_context",
]
