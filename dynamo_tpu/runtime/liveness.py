"""Crash plane: fast dead-worker detection + incarnation fencing.

Reference parity: the reference Dynamo discovers an unplanned worker death
through etcd lease expiry (seconds of TTL) and whatever TCP timeouts the
in-flight streams hit — the PR 9 drain plane only covers *planned* churn.
This module makes `kill -9` a bounded, fenced serving event:

**Detection** — a worker's liveness is derived from its load-report cadence
(router/publisher.py LoadPublisher, one report per ``interval_s``), judged
by the same clock-skew-safe local-observation rule deploy/leader.py uses
for lease staleness: we record OUR monotonic clock when a worker's report
last ARRIVED and never compare remote timestamps. Miss ``suspect_after``
intervals → SUSPECT (still routable; the canary may already be probing);
miss ``dead_after`` → DEAD, and the tracker fires callbacks that

  * run the router's single-purge ``KvScheduler.drop_worker``
    reconciliation (in-flight charges, link pairs, breaker faults, radix
    entries — atomically, in one call),
  * abort the worker's in-flight streams with a typed
    :class:`WorkerLostError` so the PR 7 migration ladder re-dispatches
    them IMMEDIATELY instead of hanging until a TCP timeout.

Detection-to-migration latency is therefore bounded by
``dead_after × interval_s`` — a configuration, not a kernel knob.

**Incarnation fencing** — every worker process stamps a monotonically
fresh :func:`process_incarnation` into its registrations, load reports,
KV-pull replies, handoff acks, and tcp response envelopes. A zombie (a
paused/partitioned previous incarnation whose late packets surface after
the restart) and the restarted worker's fresh state can then never be
conflated: :class:`IncarnationFence` admits only the newest incarnation
per worker id, and every stale packet is COUNTED
(``dynamo_tpu_liveness_stale_incarnation_drops_total{seam}``) and dropped,
never applied.

**Warm-restart rejoin** — the restore half lives in
engines/tpu/kv_checkpoint.py (CRC-verified, stamp-checked, restore is a
logged cold start on any mismatch — never a crash loop); this module owns
the restore duration/outcome metric families it reports into, and the
worker main gates readiness (``/readyz``) on the restore completing before
the new incarnation registers.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.metrics_core import MetricsRegistry
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Worker state machine values (also the liveness_worker_state gauge).
ALIVE, SUSPECT, DEAD = 0, 1, 2
_STATE_NAMES = {ALIVE: "alive", SUSPECT: "suspect", DEAD: "dead"}


class WorkerLostError(ConnectionError):
    """Typed migratable abort: liveness declared the stream's worker dead
    (missed load reports), so the frontend re-dispatches the stream — with
    its streamed tokens carried — instead of hanging until a TCP timeout.
    Subclasses ConnectionError so the PR 7 MIGRATABLE set already covers
    it; llm/migration.py labels the reason ``worker_lost``."""


class StaleIncarnationError(ConnectionError):
    """A reply carried a prior incarnation's stamp: the peer restarted (or
    a zombie's late packets surfaced) and its promised state no longer
    exists. Migratable — the correct recovery is a fresh dispatch, never
    applying the stale payload."""


# ---------------------------------------------------------------------------
# Process incarnation
# ---------------------------------------------------------------------------

_INCARNATION: Optional[int] = None


def process_incarnation() -> int:
    """This process's incarnation id, stamped once at first use.

    Monotonically fresh across restarts of the same logical worker: the
    wall-clock MICROsecond at first call, with the low bits salted so two
    processes born in the same microsecond still differ. Incarnations are
    only ever COMPARED between restarts of one worker id — a restart
    happens at human/orchestrator timescales, so wall-clock monotonicity
    (NTP steps included) holds by a margin of seconds. Microseconds (not
    nanoseconds) keep the stamp ≈2^61: it must survive msgpack's int64
    wire bound (network/codec.py) in tcp envelopes and pull replies."""
    global _INCARNATION
    if _INCARNATION is None:
        _INCARNATION = ((time.time_ns() // 1000) << 10) | random.getrandbits(10)
    return _INCARNATION


def set_process_incarnation(value: Optional[int]) -> None:
    """Pin (or reset with None) the process incarnation — restart
    simulations in tests; the soak harness gives each respawn a fresh
    process, so production never calls this."""
    global _INCARNATION
    _INCARNATION = value


# ---------------------------------------------------------------------------
# Process-global fencing + restore metric families
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()
STALE_DROPS = _REGISTRY.counter(
    mn.LIVENESS_STALE_DROPS_TOTAL,
    "Packets from a prior worker incarnation dropped (never applied) at a "
    "fencing seam: load_report (liveness tracker) | router_load (scheduler "
    "cost model — a separate subscription, hence a separate seam) | "
    "pull_reply | handoff_ack | tcp",
    ["seam"],
)
RESTORE_SECONDS = _REGISTRY.histogram(
    mn.LIVENESS_RESTORE_SECONDS,
    "Warm-restart KV checkpoint restore wall time (load + verify + "
    "install), successful or not",
)
RESTORE_OUTCOME = _REGISTRY.counter(
    mn.LIVENESS_RESTORE_OUTCOME_TOTAL,
    "Warm-restart restore outcomes: restored | partial (some blocks "
    "dropped by CRC) | empty | cold_mismatch (stamp) | cold_corrupt | "
    "cold_error — every cold_* is a logged cold start, never a crash",
    ["outcome"],
)


def note_stale_drop(seam: str, n: int = 1) -> None:
    """Count a fenced (dropped, never applied) stale-incarnation packet."""
    STALE_DROPS.inc(n, seam=seam)


def stale_drop_counts() -> Dict[str, int]:
    """seam → drop count (tests/bench; scrape-free)."""
    return {
        str(key[0]): int(value)
        for key, value in STALE_DROPS._values.items()
    }


def note_restore(outcome: str, seconds: Optional[float] = None) -> None:
    RESTORE_OUTCOME.inc(outcome=outcome)
    if seconds is not None:
        RESTORE_SECONDS.observe(seconds)


def render_fence_metrics(openmetrics: bool = False) -> str:
    """Process-global fencing/restore families (system-server source)."""
    return _REGISTRY.render(openmetrics=openmetrics)


class IncarnationFence:
    """Highest-seen incarnation per key, admitting only the newest.

    ``admit(key, inc)`` returns one of:

      * ``"applied"``  — same incarnation as before (or unfenced: inc 0 /
        None, from peers predating the stamp) — apply the packet;
      * ``"rejoined"`` — a STRICTLY newer incarnation: the worker
        restarted. The caller must purge the old incarnation's state
        (``drop_worker``) BEFORE applying, so fresh state is never
        conflated with the zombie's;
      * ``"stale"``    — older than the newest seen: a zombie's late
        packet. Counted at ``seam`` and must be dropped, never applied.
    """

    def __init__(self, seam: str) -> None:
        self.seam = seam
        self._newest: Dict[Any, int] = {}

    def admit(self, key: Any, inc: Optional[int]) -> str:
        if not inc:  # unstamped peer (or tests): fencing is opt-in
            return "applied"
        newest = self._newest.get(key, 0)
        if inc < newest:
            note_stale_drop(self.seam)
            return "stale"
        if inc > newest:
            self._newest[key] = inc
            return "rejoined" if newest else "applied"
        return "applied"

    def newest(self, key: Any) -> int:
        return self._newest.get(key, 0)

    def drop(self, key: Any) -> None:
        """Forget a key entirely (worker permanently removed). The next
        registration re-establishes the fence from its own stamp."""
        self._newest.pop(key, None)


# ---------------------------------------------------------------------------
# Liveness tracking
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LivenessConfig:
    """Detection budget, in load-report intervals. The defaults declare a
    worker dead after 5 missed 1 s reports — a 5 s detection-to-migration
    bound, an order of magnitude under the kernel's TCP retransmission
    timeouts and tunable per deployment (config.py DYN_TPU_LIVENESS_*)."""

    interval_s: float = 1.0
    suspect_after: int = 2
    dead_after: int = 5

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError("interval_s must be positive")
        if not (0 < self.suspect_after <= self.dead_after):
            raise ValueError(
                "need 0 < suspect_after <= dead_after "
                f"(got {self.suspect_after}, {self.dead_after})"
            )

    @property
    def detection_budget_s(self) -> float:
        """The bound detection latency must stay inside."""
        return self.dead_after * self.interval_s


@dataclass
class _WorkerLiveness:
    state: int = ALIVE
    incarnation: int = 0
    last_seen: float = 0.0  # OUR monotonic clock at last admitted report
    declared_dead_at: float = 0.0


class LivenessTracker:
    """Missed-report worker liveness with incarnation fencing.

    Fed one ``observe_report`` per load report (http/worker_monitor.py
    pump); ``evaluate()`` runs on the consumer's cadence (the monitor's
    evaluation task) and fires ``on_dead`` / ``on_rejoin`` callbacks.
    Judged ONLY by local observation time — the leader.py rule — so a
    worker on a skewed clock is never declared dead while its reports
    keep arriving, and a partitioned one is declared dead exactly when
    its reports stop reaching US (which is when it stopped serving us).

    Single event-loop consumer; the flight ring (DYN005 owner "liveness")
    records every transition for post-mortems."""

    def __init__(
        self,
        config: Optional[LivenessConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_dead: Optional[Callable[[int, int], None]] = None,
        on_rejoin: Optional[Callable[[int, int], None]] = None,
    ) -> None:
        self.config = config or LivenessConfig()
        self._clock = clock
        self._workers: Dict[int, _WorkerLiveness] = {}
        self._fence = IncarnationFence("load_report")
        # (worker_id, incarnation) -> None; rejoin fires BEFORE the fresh
        # report is applied so the old incarnation's router state is
        # purged first.
        self._on_dead: List[Callable[[int, int], None]] = (
            [on_dead] if on_dead else []
        )
        self._on_rejoin: List[Callable[[int, int], None]] = (
            [on_rejoin] if on_rejoin else []
        )
        self.deaths = 0  # total dead declarations (tests/bench)
        self.metrics = LivenessMetrics(self)
        self.flight = FlightRecorder("liveness", capacity=256)

    # -- wiring -------------------------------------------------------------

    def add_dead_callback(self, fn: Callable[[int, int], None]) -> None:
        self._on_dead.append(fn)

    def add_rejoin_callback(self, fn: Callable[[int, int], None]) -> None:
        self._on_rejoin.append(fn)

    # -- observation --------------------------------------------------------

    def observe_report(self, worker_id: int, incarnation: int = 0) -> str:
        """Admit one load report. Returns the fence verdict: ``"stale"``
        means the report must NOT be applied downstream (a zombie's late
        publish); ``"rejoined"`` means the old incarnation's state was
        purged via on_rejoin and the report should then be applied as the
        fresh worker's first."""
        # Chaos seam: an injected failure here models report loss between
        # the wire and the tracker — N consecutive injections MUST trip
        # the same suspect/dead machinery a crashed worker does.
        fault_point(fault_names.LIVENESS_REPORT, worker=worker_id)
        verdict = self._fence.admit(worker_id, incarnation)
        if verdict == "stale":
            self.flight.record(
                "stale_report", worker=worker_id, incarnation=incarnation,
                newest=self._fence.newest(worker_id),
            )
            return verdict
        now = self._clock()
        w = self._workers.get(worker_id)
        if verdict == "rejoined" or (w is not None and w.state == DEAD):
            # Restart (new incarnation) or a dead worker reporting again:
            # purge the old incarnation's router state BEFORE this report
            # is applied so fresh state is never conflated with it.
            self.flight.record(
                "rejoin", worker=worker_id, incarnation=incarnation,
                was=_STATE_NAMES[w.state if w else ALIVE],
            )
            logger.warning(
                "worker %#x rejoined (incarnation %d)", worker_id, incarnation
            )
            for fn in self._on_rejoin:
                try:
                    fn(worker_id, incarnation)
                except Exception:
                    logger.exception("liveness on_rejoin callback failed")
            self._workers[worker_id] = _WorkerLiveness(
                state=ALIVE, incarnation=incarnation, last_seen=now
            )
            return "rejoined"
        if w is None:
            w = self._workers[worker_id] = _WorkerLiveness()
            self.flight.record(
                "discovered", worker=worker_id, incarnation=incarnation
            )
        if w.state == SUSPECT:
            self.flight.record("recovered", worker=worker_id)
        w.state = ALIVE
        w.incarnation = incarnation or w.incarnation
        w.last_seen = now
        return verdict

    # -- evaluation ---------------------------------------------------------

    def evaluate(self) -> List[int]:
        """One detection sweep; returns workers newly declared dead.
        Transitions are judged against each worker's LAST ARRIVAL on our
        monotonic clock — never a remote timestamp."""
        cfg = self.config
        now = self._clock()
        newly_dead: List[int] = []
        for worker_id, w in self._workers.items():
            if w.state == DEAD:
                continue
            missed = (now - w.last_seen) / cfg.interval_s
            if missed >= cfg.dead_after:
                w.state = DEAD
                w.declared_dead_at = now
                self.deaths += 1
                latency = now - w.last_seen
                self.metrics.detection.observe(latency)
                self.flight.record(
                    "dead", worker=worker_id,
                    missed=int(missed), latency_ms=round(latency * 1000, 1),
                )
                logger.error(
                    "worker %#x declared DEAD after %.1f missed load "
                    "reports (%.2fs since last; budget %.2fs)",
                    worker_id, missed, latency, cfg.detection_budget_s,
                )
                newly_dead.append(worker_id)
            elif missed >= cfg.suspect_after and w.state == ALIVE:
                w.state = SUSPECT
                self.flight.record(
                    "suspect", worker=worker_id, missed=int(missed)
                )
                logger.warning(
                    "worker %#x SUSPECT after %d missed load reports",
                    worker_id, int(missed),
                )
        for worker_id in newly_dead:
            inc = self._workers[worker_id].incarnation
            for fn in self._on_dead:
                try:
                    fn(worker_id, inc)
                except Exception:
                    logger.exception("liveness on_dead callback failed")
        return newly_dead

    def note_streams_aborted(self, worker_id: int, streams: int) -> None:
        """Record the dead-worker stream-abort fan-out on the tracker's
        own ring (the on_dead callbacks run inside ``evaluate()``, on the
        ring's single consumer loop)."""
        self.flight.record(
            "streams_aborted", worker=worker_id, streams=streams
        )

    # -- surface ------------------------------------------------------------

    def state_of(self, worker_id: int) -> Optional[int]:
        w = self._workers.get(worker_id)
        return w.state if w is not None else None

    def states(self) -> Dict[int, int]:
        return {wid: w.state for wid, w in self._workers.items()}

    def dead_workers(self) -> List[int]:
        return sorted(
            wid for wid, w in self._workers.items() if w.state == DEAD
        )

    def drop(self, worker_id: int) -> None:
        """Forget a worker entirely (permanent departure via discovery
        DELETE) so dead entries don't accumulate across fleet turnover.
        The fence entry goes too — a re-registration re-establishes it."""
        self._workers.pop(worker_id, None)
        self._fence.drop(worker_id)

    def status(self) -> Dict[str, Any]:
        return {
            f"{wid:#x}": {
                "state": _STATE_NAMES[w.state],
                "incarnation": w.incarnation,
                "age_s": round(self._clock() - w.last_seen, 3),
            }
            for wid, w in self._workers.items()
        }

    def register_metrics(self, server: Any) -> None:
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)


class LivenessMetrics:
    """Tracker-owned canonical families (metric_names.py ALL_LIVENESS);
    the process-global fencing/restore families render separately
    (:func:`render_fence_metrics`)."""

    def __init__(self, tracker: "LivenessTracker") -> None:
        self._tracker = tracker
        self.registry = MetricsRegistry()
        self.worker_state = self.registry.gauge(
            mn.LIVENESS_WORKER_STATE,
            "Per-worker liveness state: 0 alive, 1 suspect (2 missed "
            "reports), 2 dead (drop_worker ran, streams aborted)",
            ["worker"],
        )
        self.detection = self.registry.histogram(
            mn.LIVENESS_DETECTION_SECONDS,
            "Last-report-to-declared-dead latency; bounded by dead_after "
            "x interval_s by construction",
        )
        self._gauge_workers: set = set()
        self.registry.on_render(self._sample)

    def _sample(self) -> None:
        labels = set()
        for wid, state in self._tracker.states().items():
            label = f"{wid:#x}"
            labels.add(label)
            self.worker_state.set(state, worker=label)
        for gone in self._gauge_workers - labels:
            self.worker_state.remove(worker=gone)
        self._gauge_workers = labels

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)
