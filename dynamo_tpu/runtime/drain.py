"""Drain plane: zero-re-prefill live handoff + coordinated rolling restarts.

Reference parity: the reference Dynamo treats planned worker churn (rolling
upgrades, spot preemption, planner scale-down) as a first-class serving
event — request migration plus CRIU checkpointing keep streams alive across
restarts (docs/fault_tolerance/). The TPU-native equivalent is this state
machine:

    serving ──trigger──▶ draining ──streams resolved──▶ drained

Triggers: SIGTERM (worker/__main__.py loop signal handler), ``POST /drain``
on the system server, or the k8s preStop hook (deploy/pod_connector.py).
Draining does, in order:

  1. **Stop new placement.** ``engine.begin_drain()`` flips the
     ``LoadSnapshot.draining`` bit (force-published immediately) so
     ``KvScheduler`` deflects placement; racing arrivals bounce with a
     typed :class:`WorkerDrainingError` (migratable — the frontend
     re-dispatches).
  2. **Live-hand-off every in-flight decode** to a peer chosen via the PR 6
     ``LinkCostModel`` (fastest measured link first; unmeasured peers quote
     the optimistic seed): a ``HandoffTicket`` + the sequence's KV blocks
     ride the wire-v2 int8 path, the peer installs them VERBATIM and
     resumes at the exact token — zero re-prefilled tokens, bit-identical
     continuation (the ticket carries the PR 3 sampling salt). The source
     then relays the peer's continuation to the still-attached client.
  3. **Fall down a ladder** when a peer refuses or the transfer fails:
     handoff → PR 7 re-prefill migration (a migratable error surfaces
     through the stream; the frontend re-dispatches with the streamed
     tokens carried) → typed requeue (never-admitted requests re-dispatch
     whole). Every rung is counted (``dynamo_tpu_drain_streams_total``).
  4. **Checkpoint the warm prefix cache** (engines/tpu/kv_checkpoint.py)
     so the restarted worker serves shared-prefix traffic without
     re-prefilling, then report drained (the worker main releases its
     lease/endpoints and exits).

Everything is bounded by a drain deadline (DYN_TPU_DRAIN_DEADLINE_S —
the k8s terminationGracePeriod's budget): at expiry, unresolved handoffs
and relays are cut down to the re-prefill rung, which is always safe.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, Dict, List, Optional

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# State machine values (also the dynamo_tpu_drain_state gauge).
SERVING, DRAINING, DRAINED = 0, 1, 2
_STATE_NAMES = {SERVING: "serving", DRAINING: "draining", DRAINED: "drained"}


class WorkerDrainingError(ConnectionError):
    """Typed migratable refusal/fallback: the worker is draining (or a
    handoff failed mid-drain) and the frontend should re-dispatch the
    request — with its streamed tokens carried — to a serving worker.
    Subclasses ConnectionError so the PR 7 MIGRATABLE set already covers
    it; llm/migration.py labels the reason ``drain``."""


class DrainMetrics:
    """Canonical drain families (runtime/metric_names.py ALL_DRAIN)."""

    def __init__(self) -> None:
        # Deferred imports: keep this module cheap to import from the
        # network planes (tcp err-kind mapping) — same pattern as
        # runtime/faults.py FaultPlane.
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import MetricsRegistry

        self.registry = MetricsRegistry()
        self.state = self.registry.gauge(
            mn.DRAIN_STATE,
            "Drain state machine: 0 serving, 1 draining, 2 drained",
        )
        self.drains = self.registry.counter(
            mn.DRAIN_DRAINS_TOTAL, "Completed drains"
        )
        self.streams = self.registry.counter(
            mn.DRAIN_STREAMS_TOTAL,
            "In-flight streams resolved by draining, by ladder rung: "
            "handoff (live KV moved, zero re-prefill) | reprefill "
            "(migratable error; the frontend re-prefills elsewhere) | "
            "requeue (never admitted; re-dispatched whole)",
            ["outcome"],
        )
        self.handoff_bytes = self.registry.counter(
            mn.DRAIN_HANDOFF_BYTES_TOTAL,
            "Serialized wire bytes of exported handoff KV (payload + "
            "scales, pool-native dtype)",
        )
        self.peer_refusals = self.registry.counter(
            mn.DRAIN_PEER_REFUSALS_TOTAL,
            "Peer adoptions refused (capacity, shape/seed mismatch, peer "
            "draining) — each walks the source down the peer list/ladder",
        )
        self.duration = self.registry.histogram(
            mn.DRAIN_DURATION, "Wall time of one full drain"
        )

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class DrainController:
    """Orchestrates one worker's drain. Lives on the worker's event loop;
    every engine interaction rides the engine's own drain-safe surface
    (detach/export/adopt happen at the scheduler's reconciled boundary).
    """

    def __init__(
        self,
        engine: Any,
        *,
        worker_id: Optional[int] = None,
        handoff_client_factory: Optional[Callable[[], Any]] = None,
        load_publisher: Optional[Any] = None,
        checkpoint_dir: Optional[str] = None,
        deadline_s: Optional[float] = None,
        on_drained: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        from dynamo_tpu import config
        from dynamo_tpu.runtime.device_observe import FlightRecorder

        self.engine = engine
        self.worker_id = worker_id
        # async () -> Client for the component's "handoff" endpoint; None
        # (prefill workers, tests) skips the handoff rung entirely.
        self._handoff_client_factory = handoff_client_factory
        self._load_publisher = load_publisher
        self.checkpoint_dir = checkpoint_dir
        self.deadline_s = (
            deadline_s if deadline_s is not None
            else config.DRAIN_DEADLINE_S.get()
        )
        self._on_drained = on_drained
        self._clock = clock
        self.state = SERVING
        self.metrics = DrainMetrics()
        self.metrics.state.set(SERVING)
        # Drain history for post-mortems (DYN005 owner "drain"; single
        # writer: the worker's event loop).
        self.flight = FlightRecorder("drain", capacity=256)
        # Peer choice: per-(this worker, peer) transfer bandwidth EWMA —
        # the PR 6 LinkCostModel, seeded optimistic and fed by the
        # handoffs themselves (accept-ack latency over wire bytes).
        from dynamo_tpu.router.scheduler import LinkCostModel
        from dynamo_tpu.runtime.liveness import IncarnationFence

        self.link_costs = LinkCostModel()
        # Handoff-ack fencing: accept-acks carry the adopting peer's
        # incarnation; a stale incarnation's ack (a zombie peer whose
        # late packets surface after its restart) must read as a refusal
        # — releasing the source KV copy against it would lose the stream.
        self._peer_fence = IncarnationFence("handoff_ack")
        self._drain_task: Optional[asyncio.Task] = None
        self._relays: set = set()
        # Ship phase (peer ranking + accept-ack round trips) runs as
        # bounded-concurrency tasks: detach/export serialize at the
        # engine's reconciled boundary, but a full worker's worth of peer
        # RTTs done strictly one-by-one would blow the deadline on a slow
        # link and cut every late stream down to the re-prefill rung.
        self.ship_concurrency = max(1, config.DRAIN_HANDOFF_CONCURRENCY.get())
        self._ships: set = set()
        self._ship_sem: Optional[asyncio.Semaphore] = None
        self._started_at: Optional[float] = None
        self._finished_at: Optional[float] = None
        self.checkpointed = False
        # Host-side mirrors (bench/tests read these without a scrape).
        self.handoffs = 0
        self.reprefill_fallbacks = 0
        self.requeued = 0
        self.peer_refusals = 0
        self.handoff_bytes = 0

    # -- surface -----------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        out = {
            "state": _STATE_NAMES[self.state],
            "deadline_s": self.deadline_s,
            "handoffs": self.handoffs,
            "reprefill_fallbacks": self.reprefill_fallbacks,
            "requeued": self.requeued,
            "peer_refusals": self.peer_refusals,
            "handoff_bytes": self.handoff_bytes,
            "checkpointed": self.checkpointed,
            "live_relays": len(self._relays),
        }
        if self._started_at is not None:
            end = self._finished_at or self._clock()
            out["duration_s"] = round(end - self._started_at, 3)
        return out

    def register_metrics(self, server: Any) -> None:
        server.register_metrics(self.metrics.render)
        server.register_flight(self.flight.name, self.flight.snapshot)

    def trigger(self, deadline_s: Optional[float] = None) -> "asyncio.Task":
        """Start the drain (idempotent — signal handler, POST /drain and
        preStop may all fire; the first wins) and return its task."""
        if self._drain_task is None:
            if deadline_s is not None:
                self.deadline_s = float(deadline_s)
            self._drain_task = asyncio.get_running_loop().create_task(
                self._run(), name="drain-controller"
            )
        elif deadline_s is not None and float(deadline_s) != self.deadline_s:
            # _run captured its deadline at start; a silent drop here
            # would look like a successful extension to the operator.
            logger.warning(
                "drain already running with deadline %.1fs; override "
                "%.1fs ignored", self.deadline_s, float(deadline_s),
            )
        return self._drain_task

    async def drain(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        """Trigger (if not already) and await completion. Shielded: one
        awaiter's cancellation (an aborted HTTP request) must not abort
        the drain every other trigger is relying on."""
        task = self.trigger(deadline_s)
        await asyncio.shield(task)
        return self.status()

    # -- the drain ---------------------------------------------------------

    def _requeue_exc(self, request_id: str) -> WorkerDrainingError:
        return WorkerDrainingError(
            f"worker draining before admission of {request_id}; re-dispatch"
        )

    async def _run(self) -> None:
        engine = self.engine
        self._started_at = t0 = self._clock()
        deadline = t0 + self.deadline_s
        self.state = DRAINING
        self.metrics.state.set(DRAINING)
        self.flight.record(
            "drain_start", deadline_s=self.deadline_s,
            active=len(engine.active_request_ids()),
        )
        logger.warning(
            "drain started (deadline %.1fs, %d active streams)",
            self.deadline_s, len(engine.active_request_ids()),
        )
        engine.begin_drain()
        if self._load_publisher is not None:
            # Don't wait for the next report cadence: the router must stop
            # placing work here NOW.
            try:
                await self._load_publisher.publish_once()
            except Exception:
                logger.exception("draining load report failed to publish")
        self._note_requeued(
            engine.shed_waiting_for_drain(self._requeue_exc)
        )
        client = None
        if self._handoff_client_factory is not None:
            try:
                client = await self._handoff_client_factory()
            except Exception:
                logger.exception(
                    "handoff client unavailable; draining without the "
                    "handoff rung"
                )
        try:
            while self._clock() < deadline:
                rids = engine.active_request_ids()
                if not rids and not engine.has_waiting():
                    break
                for rid in rids:
                    if self._clock() >= deadline:
                        break
                    await self._handoff_one(client, rid, deadline)
                # Requests that raced begin_drain into the waiting queue.
                self._note_requeued(
                    engine.shed_waiting_for_drain(self._requeue_exc)
                )
            # In-flight ship tasks must resolve (relay or fallback)
            # before the deadline sweep: their seqs are detached and no
            # longer visible to active_request_ids.
            await self._await_ships(deadline)
            # Deadline (or no peers): anything still live falls to the
            # re-prefill rung — always safe, never a dropped stream.
            for rid in engine.active_request_ids():
                try:
                    seq = await engine.detach_for_handoff(rid)
                except Exception:
                    logger.exception(
                        "deadline detach of %s failed; stream rides the "
                        "engine shutdown path", rid,
                    )
                    continue
                if seq is not None:
                    self._fallback(seq, "drain deadline exceeded")
            self._note_requeued(
                engine.shed_waiting_for_drain(self._requeue_exc)
            )
            await self._await_relays(deadline)
            await self._checkpoint()
        finally:
            # Normally empty here; non-empty means the try body raised —
            # cancel stragglers (each falls back) before the client dies
            # under them.
            if self._ships:
                for t in list(self._ships):
                    t.cancel()
                await asyncio.gather(
                    *list(self._ships), return_exceptions=True
                )
            if client is not None:
                try:
                    await client.close()
                except Exception:
                    logger.exception("handoff client close failed")
            self._finished_at = self._clock()
            self.state = DRAINED
            self.metrics.state.set(DRAINED)
            self.metrics.drains.inc()
            self.metrics.duration.observe(self._finished_at - t0)
            self.flight.record(
                "drain_done",
                handoffs=self.handoffs,
                reprefill=self.reprefill_fallbacks,
                requeued=self.requeued,
                duration_ms=round(1000 * (self._finished_at - t0), 1),
            )
            logger.warning(
                "drain finished in %.2fs: %d handed off, %d re-prefill "
                "fallbacks, %d requeued",
                self._finished_at - t0, self.handoffs,
                self.reprefill_fallbacks, self.requeued,
            )
            if self._on_drained is not None:
                try:
                    self._on_drained()
                except Exception:
                    logger.exception("on_drained callback failed")

    def _note_requeued(self, n: int) -> None:
        if n:
            self.requeued += n
            self.metrics.streams.inc(n, outcome="requeue")
            from dynamo_tpu.runtime.faults import note_activity

            note_activity("drain_requeues", n)

    async def _handoff_one(self, client, rid: str, deadline: float) -> None:
        """Serial phase of one handoff: detach + device export (both
        serialize at the engine's reconciled boundary anyway), then hand
        the network ship phase to a bounded-concurrency task so the next
        sequence's export overlaps this one's peer round trips."""
        engine = self.engine
        if self._ship_sem is None:
            self._ship_sem = asyncio.Semaphore(self.ship_concurrency)
        # Acquire BEFORE detach/export: the semaphore bounds not just the
        # peer round trips but how many exported wire payloads sit in
        # host RAM at once — detaching a full worker and serializing its
        # whole pool while ships queue would OOM the drain, dropping
        # every stream the plane exists to preserve.
        try:
            await asyncio.wait_for(
                self._ship_sem.acquire(),
                timeout=max(0.05, deadline - self._clock()),
            )
        except asyncio.TimeoutError:
            return  # still attached; the deadline sweep falls it back
        held = True
        try:
            try:
                seq = await engine.detach_for_handoff(rid)
            except Exception as exc:
                logger.warning("detach of %s failed: %r", rid, exc)
                return
            if seq is None:
                return  # finished while we were getting to it
            if seq.context.stopped:
                # Client already gone: nothing to preserve.
                engine.release_detached(seq)
                seq.queue.put_nowait(None)
                return
            # From here the seq is detached: EVERY path must resolve it
            # (relay, fallback, or requeue) — an unhandled exception would
            # leave the client stream hanging on a queue nobody feeds.
            peers: List[int] = []
            try:
                if client is not None:
                    peers = [
                        i for i in client.instance_ids if i != self.worker_id
                    ]
            except Exception as exc:
                # Discovery dying mid-drain must not strand the stream.
                self._fallback(seq, f"peer discovery failed: {exc!r}")
                return
            if not peers:
                self._fallback(seq, "no handoff peers available")
                return
            try:
                ticket, wire = await asyncio.wait_for(
                    engine.export_detached(seq),
                    timeout=max(0.05, deadline - self._clock()),
                )
            except Exception as exc:
                self._fallback(seq, f"export failed: {exc!r}")
                return
            task = asyncio.get_running_loop().create_task(
                self._ship_one(client, seq, ticket, wire, peers, deadline),
                name=f"drain-ship:{rid}",
            )
            held = False  # the ship task releases the slot when it resolves
            self._ships.add(task)
            task.add_done_callback(self._ships.discard)
        finally:
            if held:
                self._ship_sem.release()

    async def _ship_one(
        self, client, seq, ticket, wire, peers: List[int], deadline: float
    ) -> None:
        """Network phase of one handoff: rank peers, ship, fall back.
        Owns a detached seq — no exit path may strand it — and the ship
        semaphore slot _handoff_one acquired (released on resolve, which
        also caps the exported payloads buffered in host RAM)."""
        rid = seq.request.request_id
        resolved = False
        try:
            from dynamo_tpu.disagg.handoff import pack_handoff

            payload = pack_handoff(ticket, wire)
            nbytes = int(wire.nbytes)
            src = self.worker_id if self.worker_id is not None else 0
            # NetKV's decode-instance selection by network distance:
            # fastest measured (src → peer) link first; never-measured
            # peers quote the optimistic seed so a new peer isn't
            # penalized by speculation.
            ranked = sorted(
                peers,
                key=lambda p: (
                    self.link_costs.seconds(src, (p, 0), nbytes), p
                ),
            )
            for peer in ranked:
                if self._clock() >= deadline:
                    break
                try:
                    accepted = await asyncio.wait_for(
                        self._try_peer(client, seq, payload, nbytes, peer),
                        timeout=max(0.05, deadline - self._clock()),
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    self.peer_refusals += 1
                    self.metrics.peer_refusals.inc()
                    self.flight.record(
                        "peer_error", request_id=rid, peer=peer,
                        error=f"{type(exc).__name__}: {exc}",
                    )
                    continue
                if accepted:
                    # The relay task owns the stream now: nothing
                    # below may fall back on it.
                    resolved = True
                    self.handoffs += 1
                    self.handoff_bytes += nbytes
                    self.metrics.streams.inc(outcome="handoff")
                    self.metrics.handoff_bytes.inc(nbytes)
                    from dynamo_tpu.runtime.faults import note_activity

                    note_activity("drain_handoffs")
                    self.flight.record(
                        "handoff", request_id=rid, peer=peer,
                        bytes=nbytes, blocks=ticket.n_blocks,
                        carried=len(ticket.generated),
                    )
                    return
            resolved = True
            self._fallback(seq, "every peer refused the handoff")
        except asyncio.CancelledError:
            # Deadline (or drain teardown) cut the ship mid-flight: the
            # re-prefill rung is always safe — _try_peer's BaseException
            # path already closed the peer stream, reaping any ghost.
            if not resolved:
                self._fallback(seq, "drain deadline cut the handoff")
            raise
        except Exception as exc:
            # Packaging/ranking/accounting machinery failing must walk
            # the ladder, never strand the detached stream.
            if not resolved:
                self._fallback(seq, f"handoff machinery failed: {exc!r}")
        finally:
            self._ship_sem.release()

    async def _close_quietly(self, it: Any) -> None:
        """Best-effort aclose of a handoff/continuation stream. Closing
        propagates cancellation to the peer's handler context, so a peer
        that already adopted before the source gave up on it reaps the
        ghost sequence instead of decoding it to max_tokens with no
        consumer. Bounded: a dead wire must not hang the drain."""
        aclose = getattr(it, "aclose", None)
        if aclose is None:
            return
        try:
            await asyncio.wait_for(aclose(), timeout=1.0)
        except BaseException as exc:
            # The close is compensation on an already-failing path; a
            # dead wire here is expected (the dropped connection itself
            # cancels the peer's handler) — note it and move on.
            logger.debug("handoff stream close failed: %r", exc)

    async def _try_peer(
        self, client: Any, seq: Any, payload: dict, nbytes: int, peer: int
    ) -> bool:
        """Ship the ticket to one peer. True = accepted (a relay task now
        owns the stream and the source's block copy is released); False =
        typed refusal. Transport errors raise to the caller."""
        # Child context: cancelling the original client stream cancels the
        # peer continuation too (the tcp plane forwards the stop).
        ctx = seq.context.child()
        stream = client.direct(payload, peer, context=ctx)
        it = stream.__aiter__()
        t0 = self._clock()
        try:
            first = await it.__anext__()
        except StopAsyncIteration:
            raise ConnectionError(f"peer {peer:#x} closed the handoff stream")
        except BaseException:
            # Ambiguous outcome (deadline cancel, transport death mid
            # accept-ack): the peer may ALREADY have adopted. Close the
            # stream before walking the ladder — the cancel reaches the
            # peer and reaps any adopted ghost, so falling back to
            # re-prefill cannot leave two engines decoding one request.
            await self._close_quietly(it)
            raise
        stale_ack = (
            isinstance(first, dict)
            and self._peer_fence.admit(peer, first.get("inc")) == "stale"
        )
        if stale_ack or not (isinstance(first, dict) and first.get("accepted")):
            reason = (
                "stale-incarnation ack (zombie peer)" if stale_ack
                else first.get("reason", "unspecified")
                if isinstance(first, dict) else repr(first)
            )
            self.peer_refusals += 1
            self.metrics.peer_refusals.inc()
            self.flight.record(
                "peer_refusal", request_id=seq.request.request_id,
                peer=peer, reason=reason,
            )
            await self._close_quietly(it)
            return False
        # The accept-ack round trip carried the whole ticket: observe the
        # achieved (src → peer) bandwidth for the next seq's peer ranking.
        elapsed = self._clock() - t0
        src = self.worker_id if self.worker_id is not None else 0
        if elapsed > 0 and nbytes > 0:
            self.link_costs.observe(src, (peer, 0), nbytes / elapsed)
        # Peer owns the KV now; free the source copy.
        self.engine.release_detached(seq)
        task = asyncio.get_running_loop().create_task(
            self._relay(seq, it, peer),
            name=f"drain-relay:{seq.request.request_id}",
        )
        self._relays.add(task)
        task.add_done_callback(self._relays.discard)
        return True

    def _export_handoff_span(
        self, seq: Any, peer: int, *, ok: bool, reason: str = "",
    ) -> None:
        """Trajectory handoff_stall span: detach → first relayed token (or
        the fallback decision) — the gap the client actually felt. Never
        raises; streams outside any trace cost one dict lookup."""
        if not seq.context.baggage.get("traceparent"):
            return
        try:
            from dynamo_tpu.runtime import trajectory
            from dynamo_tpu.runtime.lifecycle import trace_id_of
            from dynamo_tpu.utils.tracing import export_span

            start = getattr(seq, "t_detached", 0.0) or self._clock()
            proc = (
                f"worker-{self.worker_id:#x}"
                if isinstance(self.worker_id, int) else None
            )
            export_span(
                "drain.handoff", seq.context,
                start_mono=start,
                proc=proc,
                status="ok" if ok else f"error: {reason or 'fallback'}",
                peer=peer if peer >= 0 else None,
                outcome="handoff" if ok else "reprefill",
            )
            trajectory.note_event(
                trace_id_of(seq.context), "drain",
                "handoff" if ok else "fallback",
                request_id=seq.request.request_id,
                peer=peer if peer >= 0 else None, reason=reason or None,
            )
        except Exception:
            logger.debug("handoff span export failed", exc_info=True)

    async def _relay(self, seq: Any, it: Any, peer: int) -> None:
        """Pipe the peer's continuation into the still-attached client
        stream. On relay failure, a MIGRATABLE error surfaces instead —
        the frontend re-dispatches (to the peer, most likely, whose cache
        is now warm with this very sequence)."""
        from dynamo_tpu.llm.protocols.common import BackendOutput

        rid = seq.request.request_id
        first_relayed = False
        try:
            while True:
                try:
                    item = await it.__anext__()
                except StopAsyncIteration:
                    raise WorkerDrainingError(
                        f"peer {peer:#x} continuation ended without a "
                        "finish; re-dispatch"
                    )
                out = (
                    BackendOutput.from_dict(item)
                    if isinstance(item, dict) else item
                )
                if not first_relayed:
                    # The stall the client felt ends HERE: tokens flow
                    # again from the peer through the source's relay.
                    first_relayed = True
                    self._export_handoff_span(seq, peer, ok=True)
                seq.queue.put_nowait(out)
                if out.finish_reason is not None:
                    self.flight.record(
                        "relay_done", request_id=rid, peer=peer,
                    )
                    return
        except asyncio.CancelledError:
            seq.queue.put_nowait(
                WorkerDrainingError(
                    "drain deadline cut the relay; re-dispatch with "
                    "carried tokens"
                )
            )
            # Stop the peer's continuation too: the client is about to
            # re-dispatch, and an unconsumed peer stream would decode to
            # max_tokens for nobody.
            await self._close_quietly(it)
            raise
        except Exception as exc:
            mig = (
                exc
                if isinstance(exc, (ConnectionError, TimeoutError))
                else WorkerDrainingError(
                    f"handoff relay to peer {peer:#x} failed: {exc!r}"
                )
            )
            self.flight.record(
                "relay_error", request_id=rid, peer=peer,
                error=f"{type(exc).__name__}: {exc}",
            )
            seq.queue.put_nowait(mig)
            await self._close_quietly(it)

    def _fallback(self, seq: Any, reason: str) -> None:
        """The PR 7 re-prefill rung: a migratable error surfaces through
        the stream; the frontend re-dispatches with the streamed tokens
        carried (Migration accumulated them), re-prefilling on a serving
        worker."""
        rid = seq.request.request_id
        self.reprefill_fallbacks += 1
        self.metrics.streams.inc(outcome="reprefill")
        from dynamo_tpu.runtime.faults import note_activity

        note_activity("drain_fallbacks")
        self.flight.record("fallback", request_id=rid, reason=reason)
        self._export_handoff_span(seq, -1, ok=False, reason=reason)
        logger.warning(
            "handoff of %s fell back to re-prefill migration: %s",
            rid, reason,
        )
        self.engine.fail_detached(
            seq,
            WorkerDrainingError(
                f"worker draining; handoff unavailable ({reason}) — "
                "re-dispatch with carried tokens"
            ),
        )

    async def _await_ships(self, deadline: float) -> None:
        """Ship tasks (peer ranking + accept-ack) must resolve their
        detached seqs before the deadline sweep; at the deadline they are
        cancelled and each falls back to the re-prefill rung."""
        if not self._ships:
            return
        remaining = max(0.0, deadline - self._clock())
        done, pending = await asyncio.wait(
            list(self._ships), timeout=remaining
        )
        if pending:
            for task in pending:
                task.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
            self.flight.record("ships_cut", count=len(pending))

    async def _await_relays(self, deadline: float) -> None:
        """Relays (source → client piping of peer continuations) must
        finish before the process exits; at the deadline they are cut —
        the relay's cancellation path pushes a migratable error, and the
        frontend re-dispatches to the peer, whose cache is warm."""
        if not self._relays:
            return
        remaining = max(0.0, deadline - self._clock())
        done, pending = await asyncio.wait(
            list(self._relays), timeout=remaining
        )
        for task in pending:
            task.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
            self.flight.record("relays_cut", count=len(pending))

    async def _checkpoint(self) -> None:
        engine = self.engine
        if not self.checkpoint_dir:
            return
        if getattr(engine.pool, "cached_blocks", 0) <= 0:
            return
        try:
            result = await engine.save_checkpoint(self.checkpoint_dir)
            self.checkpointed = True
            self.flight.record(
                "checkpoint", blocks=result.get("blocks"),
                path=self.checkpoint_dir,
            )
        except Exception:
            logger.exception(
                "warm-KV checkpoint failed during drain (next start runs "
                "cold)"
            )
