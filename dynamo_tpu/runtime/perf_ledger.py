"""Perf ledger: always-on tick-level performance attribution + the live
half of the regression sentinel.

Seven rounds of this repo measured performance *nowhere continuously*:
bench legs are one-shot, and the stack's defense against a silently
slower kernel was a pile of gauges nobody compared against anything.
This module makes performance a first-class, self-comparing observable
(design: docs/design_docs/perf_ledger.md):

* **Attribution** — rolling, TTL-pruned windows per decode shape
  ``(width bucket, program variant, path fused/fallback)`` built from
  stamps the pipelined engine already takes: step wall, host gap,
  dispatch/reap host split, tokens/s, plus prefill tokens/s per pow2
  chunk bucket from the admission loop. Quantiles are computed at READ
  time (render / ``/debug/perf``); the feed itself is deque appends and
  arithmetic only.
* **Roofline gauge** — measured tok/s divided by the pure-arithmetic
  bandwidth roofline (runtime/roofline.py — the same formula bench's
  70B projection leg grades rounds with) at the window's own median
  occupancy and context: "how far from the HBM wall is this shape,
  right now".
* **Fingerprints** — a persisted per-(model preset, width bucket,
  backend, host) steady-state record (median step time + tok/s with a
  noise band) written at clean shutdown and loaded at start. Live
  windows drifting past the band for ``anomaly_streak`` consecutive
  evaluations raise a typed anomaly: lint-pinned counter
  (``PERF_ANOMALIES_TOTAL``), a "perf" flight-ring event, and a verdict
  on ``GET /debug/perf`` — a Mosaic demotion or a quietly slower kernel
  becomes a paged fact, not a post-hoc diff. A corrupt or vanished
  fingerprint file degrades to cold start (counted, flight-recorded),
  never crashes.

Hot-path budget (DYN002: this module is in the decode-tick scope):
``observe_decode`` / ``observe_prefill`` are dict lookups + deque
appends + arithmetic — no locks, no logging, no metric updates (Counter
takes a lock; gauges refresh in the registry's on_render hook).
``PerfLedger.evaluate`` is the registered time-gated boundary (the
TickBudgeter.evaluate precedent): it self-gates on ``eval_interval_s``
and only past the gate touches counters and the flight ring.

Threading contract mirrors FlightRecorder: ONE writer (the engine tick
loop feeds decode and — via admission, same loop — prefill); readers
(render, ``/debug/perf``) tolerate a concurrently advancing window — a
torn read can at worst miss the newest sample, never corrupt a deque.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from dynamo_tpu import config
from dynamo_tpu.runtime import fault_names as fp
from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.device_observe import FlightRecorder
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.metrics_core import MetricsRegistry

logger = logging.getLogger(__name__)

FINGERPRINT_SCHEMA_VERSION = 1

# Declared in the canonical registry (config.py); aliased here so the
# ledger's call sites keep their local names.
PERF_WINDOW = config.PERF_WINDOW
PERF_SAMPLE_TTL_S = config.PERF_SAMPLE_TTL_S
PERF_EVAL_INTERVAL_S = config.PERF_EVAL_INTERVAL_S
PERF_NOISE_BAND = config.PERF_NOISE_BAND
PERF_MIN_SAMPLES = config.PERF_MIN_SAMPLES
PERF_FINGERPRINT_PATH = config.PERF_FINGERPRINT_PATH


class PerfLedgerConfig:
    """Knobs, env-seeded with per-test overrides (TickBudgeter idiom)."""

    def __init__(
        self,
        *,
        window: Optional[int] = None,
        sample_ttl_s: Optional[float] = None,
        eval_interval_s: Optional[float] = None,
        noise_band: Optional[float] = None,
        min_samples: Optional[int] = None,
        anomaly_streak: int = 2,
        fingerprint_path: Optional[str] = None,
    ) -> None:
        self.window = int(window if window is not None else PERF_WINDOW.get())
        self.sample_ttl_s = float(
            sample_ttl_s if sample_ttl_s is not None
            else PERF_SAMPLE_TTL_S.get()
        )
        self.eval_interval_s = float(
            eval_interval_s if eval_interval_s is not None
            else PERF_EVAL_INTERVAL_S.get()
        )
        self.noise_band = float(
            noise_band if noise_band is not None else PERF_NOISE_BAND.get()
        )
        self.min_samples = int(
            min_samples if min_samples is not None else PERF_MIN_SAMPLES.get()
        )
        self.anomaly_streak = int(anomaly_streak)
        self.fingerprint_path = (
            fingerprint_path if fingerprint_path is not None
            else PERF_FINGERPRINT_PATH.get()
        )


class RollingWindow:
    """Bounded deque of ``(t, value)`` with TTL aging. Appends are O(1)
    amortized (the TTL prune pops from the left only as far as needed);
    quantiles sort a snapshot copy at READ time, never on the feed."""

    __slots__ = ("_maxlen", "_ttl_s", "_q")

    def __init__(self, maxlen: int, ttl_s: float) -> None:
        self._maxlen = maxlen
        self._ttl_s = ttl_s
        self._q: Deque[Tuple[float, float]] = deque(maxlen=maxlen)

    def add(self, t: float, value: float) -> None:
        q = self._q
        horizon = t - self._ttl_s
        while q and q[0][0] < horizon:
            q.popleft()
        q.append((t, value))

    def prune(self, now: float) -> None:
        q = self._q
        horizon = now - self._ttl_s
        while q and q[0][0] < horizon:
            q.popleft()

    def __len__(self) -> int:
        return len(self._q)

    def values(self, now: Optional[float] = None) -> List[float]:
        """Snapshot of live values (TTL-filtered at read when ``now`` is
        given — reads must not mutate, other threads may be appending)."""
        if now is None:
            return [v for _, v in list(self._q)]
        horizon = now - self._ttl_s
        return [v for t, v in list(self._q) if t >= horizon]

    def quantile(self, q: float, now: Optional[float] = None) -> float:
        """Nearest-rank-interpolated quantile of the live samples; 0.0
        when empty (gauges render 0, verdicts gate on sample count)."""
        vals = sorted(self.values(now))
        if not vals:
            return 0.0
        if len(vals) == 1:
            return vals[0]
        pos = q * (len(vals) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac


class _ShapeWindows:
    """Per-(width, variant, path) decode attribution windows."""

    __slots__ = (
        "step", "gap", "dispatch", "reap", "toks_rate", "occupancy",
        "avg_ctx", "samples_total",
    )

    def __init__(self, window: int, ttl_s: float) -> None:
        self.step = RollingWindow(window, ttl_s)
        self.gap = RollingWindow(window, ttl_s)
        self.dispatch = RollingWindow(window, ttl_s)
        self.reap = RollingWindow(window, ttl_s)
        self.toks_rate = RollingWindow(window, ttl_s)
        self.occupancy = RollingWindow(window, ttl_s)
        self.avg_ctx = RollingWindow(window, ttl_s)
        self.samples_total = 0


class PerfMetrics:
    """The lint-pinned ``ALL_PERF`` family on a private registry.
    Gauges only refresh inside the registry's pre-scrape hook — the feed
    path never touches a metric (Counter.inc takes a lock)."""

    def __init__(self, ledger: "PerfLedger") -> None:
        self._ledger = ledger
        self.registry = MetricsRegistry()
        shape = ["width", "variant", "path"]
        self.step_p50 = self.registry.gauge(
            mn.PERF_STEP_P50_SECONDS,
            "Rolling median decode step wall time per shape",
            shape,
        )
        self.step_p99 = self.registry.gauge(
            mn.PERF_STEP_P99_SECONDS,
            "Rolling p99 decode step wall time per shape",
            shape,
        )
        self.gap_p50 = self.registry.gauge(
            mn.PERF_HOST_GAP_P50_SECONDS,
            "Rolling median host gap (device idle between bursts)",
            shape,
        )
        self.dispatch_p50 = self.registry.gauge(
            mn.PERF_DISPATCH_P50_SECONDS,
            "Rolling median dispatch-side host cost per shape",
            shape,
        )
        self.reap_p50 = self.registry.gauge(
            mn.PERF_REAP_P50_SECONDS,
            "Rolling median reap-side host cost per shape",
            shape,
        )
        self.toks = self.registry.gauge(
            mn.PERF_TOKENS_PER_SEC,
            "Rolling median decode throughput per shape",
            shape,
        )
        self.roofline = self.registry.gauge(
            mn.PERF_ROOFLINE_FRACTION,
            "Measured tok/s over the bandwidth roofline at the window's "
            "median occupancy and context (1.0 = HBM wall)",
            shape,
        )
        self.prefill_toks = self.registry.gauge(
            mn.PERF_PREFILL_TOKENS_PER_SEC,
            "Rolling median prefill throughput per pow2 chunk bucket",
            ["chunk_bucket"],
        )
        self.window_samples = self.registry.gauge(
            mn.PERF_WINDOW_SAMPLES,
            "Live samples in each shape's rolling window",
            shape,
        )
        self.anomalies = self.registry.counter(
            mn.PERF_ANOMALIES_TOTAL,
            "Typed perf anomalies raised by the sentinel "
            "(step_regression | toks_regression)",
            ["kind"],
        )
        self.fp_loaded = self.registry.gauge(
            mn.PERF_FINGERPRINT_LOADED,
            "Steady-state fingerprints loaded at startup (0 = cold start)",
        )
        self.fp_failures = self.registry.counter(
            mn.PERF_FINGERPRINT_FAILURES_TOTAL,
            "Fingerprint persistence failures by op (load | store) — "
            "each degrades to cold start, never crashes",
            ["op"],
        )
        self.registry.on_render(self._refresh)

    def _refresh(self) -> None:
        led = self._ledger
        now = led.clock()
        for (width, variant, path), sw in list(led._decode.items()):
            lab = {"width": str(width), "variant": variant, "path": path}
            self.step_p50.set(sw.step.quantile(0.50, now), **lab)
            self.step_p99.set(sw.step.quantile(0.99, now), **lab)
            self.gap_p50.set(sw.gap.quantile(0.50, now), **lab)
            self.dispatch_p50.set(sw.dispatch.quantile(0.50, now), **lab)
            self.reap_p50.set(sw.reap.quantile(0.50, now), **lab)
            toks = sw.toks_rate.quantile(0.50, now)
            self.toks.set(toks, **lab)
            self.window_samples.set(len(sw.step.values(now)), **lab)
            frac = led._roofline_fraction(sw, toks, now)
            if frac is not None:
                self.roofline.set(frac, **lab)
        for bucket, win in list(led._prefill.items()):
            self.prefill_toks.set(
                win.quantile(0.50, now), chunk_bucket=str(bucket)
            )
        self.fp_loaded.set(led._fingerprints_loaded)

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)


class PerfLedger:
    """Process-global perf attribution + live regression sentinel.

    Owns the "perf" flight ring (DYN005): every sentinel anomaly and
    fingerprint-persistence outcome is a typed ring event."""

    def __init__(
        self,
        cfg: Optional[PerfLedgerConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.cfg = cfg or PerfLedgerConfig()
        self.clock = clock
        self.flight = FlightRecorder("perf", capacity=512)
        # Decode attribution: (width, variant, path) -> windows. Plain
        # dict, single writer (the tick thread) — see module docstring.
        self._decode: Dict[Tuple[int, str, str], _ShapeWindows] = {}
        # Prefill attribution: pow2 chunk bucket -> tok/s window.
        self._prefill: Dict[int, RollingWindow] = {}
        # Identity (configure()): the fingerprint key's non-shape half.
        self._preset = ""
        self._backend = ""
        self._host = ""
        self._roofline_fn: Optional[Callable[[int, float], float]] = None
        # Fingerprints: key -> record (see _fingerprint_key). Loaded
        # records are the baseline; live records replace them at store.
        self._fingerprints: Dict[str, Dict[str, Any]] = {}
        self._fingerprints_loaded = 0
        # Sentinel state (evaluate() only — the DYN002 boundary).
        self._t_last_eval = 0.0
        self._streaks: Dict[Tuple[str, str], int] = {}  # (key, kind) -> n
        self._verdicts: Dict[str, Dict[str, Any]] = {}
        self._anomalies_total = 0
        self.metrics = PerfMetrics(self)

    # -- identity / fingerprint I/O (startup + shutdown paths) --------------

    def configure(
        self,
        *,
        preset: str,
        backend: str,
        host: str,
        roofline_fn: Optional[Callable[[int, float], float]] = None,
    ) -> None:
        """Install the engine's identity and (optionally) a roofline
        closure (runtime/roofline.make_roofline_fn), then load any
        persisted fingerprints for it. Called once at engine start."""
        self._preset = str(preset)
        self._backend = str(backend)
        self._host = str(host)
        self._roofline_fn = roofline_fn
        self.load_fingerprints()

    def _identity(self) -> Dict[str, str]:
        return {
            "preset": self._preset,
            "backend": self._backend,
            "host": self._host,
        }

    def _fingerprint_key(self, width: int) -> str:
        # ISSUE 19's fingerprint identity: (preset, width bucket,
        # backend, host). Variants/paths fold into the width bucket —
        # the shape the compiled program is keyed on.
        return f"{self._preset}|w{width}|{self._backend}|{self._host}"

    def load_fingerprints(self) -> int:
        """Load persisted fingerprints for the configured identity.
        Corrupt / vanished / fault-injected file -> cold start: counted,
        flight-recorded, NEVER raised (DYN006 contract)."""
        path = self.cfg.fingerprint_path
        if not path:
            return 0
        try:
            fault_point(fp.PERF_FINGERPRINT_LOAD, path=path)
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema_version") != FINGERPRINT_SCHEMA_VERSION:
                raise ValueError(
                    f"fingerprint schema {doc.get('schema_version')!r} "
                    f"!= {FINGERPRINT_SCHEMA_VERSION}"
                )
            records = doc["fingerprints"]
            if not isinstance(records, dict):
                raise ValueError("fingerprints is not a mapping")
            prefix = f"{self._preset}|"
            mine = {
                k: v for k, v in records.items()
                if k.startswith(prefix)
                and k.endswith(f"|{self._backend}|{self._host}")
                and isinstance(v, dict)
            }
            self._fingerprints = mine
            self._fingerprints_loaded = len(mine)
            self.flight.record(
                "fingerprint_load", path=path, loaded=len(mine)
            )
            return len(mine)
        except FileNotFoundError:
            # First run on this box: a cold start is the expected state,
            # not a failure.
            self._fingerprints_loaded = 0
            return 0
        except Exception as e:
            self.metrics.fp_failures.inc(op="load")
            self.flight.record(
                "fingerprint_load_failed", path=path, error=repr(e)
            )
            logger.warning(
                "perf fingerprint load failed (%s); cold start", e
            )
            self._fingerprints = {}
            self._fingerprints_loaded = 0
            return 0

    def store_fingerprints(self, now: Optional[float] = None) -> int:
        """Persist steady-state fingerprints (clean shutdown only — the
        engine skips this after a failed tick so a degraded run never
        becomes the baseline). Atomic tmp+rename; failures counted and
        flight-recorded, never raised."""
        path = self.cfg.fingerprint_path
        if not path:
            return 0
        t = self.clock() if now is None else now
        fresh = dict(self._fingerprints)
        wrote = 0
        for width, sw in self._per_width(t).items():
            vals = sw.step.values(t)
            if len(vals) < self.cfg.min_samples:
                continue
            fresh[self._fingerprint_key(width)] = {
                "step_p50_s": sw.step.quantile(0.50, t),
                "toks_per_sec": sw.toks_rate.quantile(0.50, t),
                "band": self.cfg.noise_band,
                "samples": len(vals),
                "saved_at": time.time(),
            }
            wrote += 1
        if not wrote:
            return 0
        try:
            fault_point(fp.PERF_FINGERPRINT_STORE, path=path)
            doc = {
                "schema_version": FINGERPRINT_SCHEMA_VERSION,
                "identity": self._identity(),
                "fingerprints": fresh,
            }
            tmp = f"{path}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            self._fingerprints = fresh
            self.flight.record("fingerprint_store", path=path, wrote=wrote)
            return wrote
        except Exception as e:
            self.metrics.fp_failures.inc(op="store")
            self.flight.record(
                "fingerprint_store_failed", path=path, error=repr(e)
            )
            logger.warning("perf fingerprint store failed: %s", e)
            return 0

    # -- feeds (DYN002 hot path: deque + arithmetic ONLY) -------------------

    def observe_decode(
        self,
        width: int,
        variant: str,
        path: str,
        step_s: float,
        tokens: int,
        occupancy: int,
        avg_ctx: float,
        host_gap_s: float,
        dispatch_s: float,
        reap_s: float,
        now: Optional[float] = None,
    ) -> None:
        """One reaped decode burst. Called from the engine tick thread."""
        t = self.clock() if now is None else now
        key = (width, variant, path)
        sw = self._decode.get(key)
        if sw is None:
            sw = _ShapeWindows(self.cfg.window, self.cfg.sample_ttl_s)
            self._decode[key] = sw
        sw.samples_total += 1
        sw.step.add(t, step_s)
        sw.gap.add(t, host_gap_s)
        sw.dispatch.add(t, dispatch_s)
        sw.reap.add(t, reap_s)
        sw.occupancy.add(t, occupancy)
        sw.avg_ctx.add(t, avg_ctx)
        if step_s > 0.0 and tokens > 0:
            sw.toks_rate.add(t, tokens / step_s)

    def observe_prefill(
        self,
        chunk_bucket: int,
        duration_s: float,
        tokens: int,
        now: Optional[float] = None,
    ) -> None:
        """One prefill chunk round (admission loop, same engine thread)."""
        if duration_s <= 0.0 or tokens <= 0:
            return
        t = self.clock() if now is None else now
        win = self._prefill.get(chunk_bucket)
        if win is None:
            win = RollingWindow(self.cfg.window, self.cfg.sample_ttl_s)
            self._prefill[chunk_bucket] = win
        win.add(t, tokens / duration_s)

    # -- sentinel (DYN002 boundary: time-gated, may count/record) -----------

    def evaluate(self, now: Optional[float] = None) -> bool:
        """Compare live per-width medians against the loaded fingerprints
        (time-gated to ``eval_interval_s``). A breach past the noise band
        must persist ``anomaly_streak`` consecutive evaluations before it
        raises — one cold tick is noise, a regime is a regression.
        Returns True when an evaluation actually ran."""
        t = self.clock() if now is None else now
        if t - self._t_last_eval < self.cfg.eval_interval_s:
            return False
        self._t_last_eval = t
        verdicts: Dict[str, Dict[str, Any]] = {}
        for width, sw in self._per_width(t).items():
            key = self._fingerprint_key(width)
            verdicts[key] = self._judge(key, width, sw, t)
        self._verdicts = verdicts
        return True

    def _judge(
        self, key: str, width: int, sw: _ShapeWindows, t: float
    ) -> Dict[str, Any]:
        n = len(sw.step.values(t))
        base = self._fingerprints.get(key)
        step_p50 = sw.step.quantile(0.50, t)
        toks = sw.toks_rate.quantile(0.50, t)
        out: Dict[str, Any] = {
            "width": width,
            "samples": n,
            "step_p50_s": step_p50,
            "toks_per_sec": toks,
            "fingerprint": base,
        }
        if n < self.cfg.min_samples:
            out["verdict"] = "insufficient"
            self._clear_streaks(key)
            return out
        if base is None:
            out["verdict"] = "no_baseline"
            self._clear_streaks(key)
            return out
        band = float(base.get("band", self.cfg.noise_band))
        breaches: List[Tuple[str, float, float, float]] = []
        improved = False
        base_step = float(base.get("step_p50_s") or 0.0)
        if base_step > 0.0 and step_p50 > 0.0:
            ratio = step_p50 / base_step
            if ratio > 1.0 + band:
                breaches.append(
                    ("step_regression", ratio, step_p50, base_step)
                )
            elif ratio < 1.0 - band:
                improved = True
        base_toks = float(base.get("toks_per_sec") or 0.0)
        if base_toks > 0.0 and toks > 0.0:
            ratio = toks / base_toks
            if ratio < 1.0 - band:
                breaches.append(("toks_regression", ratio, toks, base_toks))
            elif ratio > 1.0 + band:
                improved = True
        if not breaches:
            self._clear_streaks(key)
            out["verdict"] = "improved" if improved else "ok"
            return out
        anomalies: List[Dict[str, Any]] = []
        active_kinds = set()
        for kind, ratio, live, baseline in breaches:
            active_kinds.add(kind)
            streak = self._streaks.get((key, kind), 0) + 1
            self._streaks[(key, kind)] = streak
            if streak == self.cfg.anomaly_streak:
                # Edge-triggered page: count + ring ONCE per regime, not
                # every 5s while the regression persists.
                self._anomalies_total += 1
                self.metrics.anomalies.inc(kind=kind)
                self.flight.record(
                    "anomaly", key=key, anomaly=kind,
                    ratio=round(ratio, 4), live=live, baseline=baseline,
                )
            if streak >= self.cfg.anomaly_streak:
                anomalies.append(
                    {
                        "kind": kind,
                        "ratio": ratio,
                        "live": live,
                        "baseline": baseline,
                        "streak": streak,
                    }
                )
        for (k, kind) in list(self._streaks):
            if k == key and kind not in active_kinds:
                del self._streaks[(k, kind)]
        if anomalies:
            out["verdict"] = "regression"
            out["anomalies"] = anomalies
        else:
            # Breach seen but the streak hasn't matured: hold the page.
            out["verdict"] = "ok"
            out["pending"] = [b[0] for b in breaches]
        return out

    def _clear_streaks(self, key: str) -> None:
        for pair in [p for p in self._streaks if p[0] == key]:
            del self._streaks[pair]

    # -- aggregation helpers -------------------------------------------------

    def _per_width(self, now: float) -> Dict[int, _ShapeWindows]:
        """Merge shape windows down to the fingerprint granularity (width
        bucket): samples from every (variant, path) on that width share
        one judged window. Read-time only — bounded by window size."""
        merged: Dict[int, _ShapeWindows] = {}
        for (width, _variant, _path), sw in list(self._decode.items()):
            agg = merged.get(width)
            if agg is None:
                agg = _ShapeWindows(
                    self.cfg.window * max(1, len(self._decode)),
                    self.cfg.sample_ttl_s,
                )
                merged[width] = agg
            for attr in ("step", "gap", "dispatch", "reap", "toks_rate",
                         "occupancy", "avg_ctx"):
                src: RollingWindow = getattr(sw, attr)
                dst: RollingWindow = getattr(agg, attr)
                for t, v in list(src._q):
                    dst._q.append((t, v))
            agg.samples_total += sw.samples_total
        # Time-order the merged deques so TTL reads stay correct.
        for agg in merged.values():
            for attr in ("step", "gap", "dispatch", "reap", "toks_rate",
                         "occupancy", "avg_ctx"):
                win: RollingWindow = getattr(agg, attr)
                win._q = deque(sorted(win._q), maxlen=win._q.maxlen)
        return merged

    def _roofline_fraction(
        self, sw: _ShapeWindows, toks: float, now: float
    ) -> Optional[float]:
        fn = self._roofline_fn
        if fn is None or toks <= 0.0:
            return None
        occ = sw.occupancy.quantile(0.50, now)
        ctx = sw.avg_ctx.quantile(0.50, now)
        if occ <= 0.0:
            return None
        try:
            ceiling = fn(int(round(occ)), ctx)
        except Exception:
            return None
        if ceiling <= 0.0:
            return None
        return toks / ceiling

    # -- reads ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The GET /debug/perf body (also the CLI's source)."""
        now = self.clock()
        decode: List[Dict[str, Any]] = []
        for (width, variant, path), sw in sorted(self._decode.items()):
            toks = sw.toks_rate.quantile(0.50, now)
            row: Dict[str, Any] = {
                "width": width,
                "variant": variant,
                "path": path,
                "samples": len(sw.step.values(now)),
                "samples_total": sw.samples_total,
                "step_p50_s": sw.step.quantile(0.50, now),
                "step_p99_s": sw.step.quantile(0.99, now),
                "host_gap_p50_s": sw.gap.quantile(0.50, now),
                "dispatch_p50_s": sw.dispatch.quantile(0.50, now),
                "reap_p50_s": sw.reap.quantile(0.50, now),
                "toks_per_sec": toks,
                "occupancy_p50": sw.occupancy.quantile(0.50, now),
                "avg_ctx_p50": sw.avg_ctx.quantile(0.50, now),
            }
            frac = self._roofline_fraction(sw, toks, now)
            if frac is not None:
                row["roofline_fraction"] = frac
            decode.append(row)
        prefill = {
            str(bucket): {
                "samples": len(win.values(now)),
                "toks_per_sec_p50": win.quantile(0.50, now),
            }
            for bucket, win in sorted(self._prefill.items())
        }
        return {
            "identity": self._identity(),
            "decode": decode,
            "prefill": prefill,
            "fingerprints": dict(self._fingerprints),
            "fingerprints_loaded": self._fingerprints_loaded,
            "verdicts": dict(self._verdicts),
            "anomalies_total": self._anomalies_total,
            "config": {
                "window": self.cfg.window,
                "sample_ttl_s": self.cfg.sample_ttl_s,
                "eval_interval_s": self.cfg.eval_interval_s,
                "noise_band": self.cfg.noise_band,
                "min_samples": self.cfg.min_samples,
                "anomaly_streak": self.cfg.anomaly_streak,
                "fingerprint_path": self.cfg.fingerprint_path,
            },
        }

    def render(self, openmetrics: bool = False) -> str:
        return self.metrics.render(openmetrics=openmetrics)


_LEDGER: Optional[PerfLedger] = None
_LEDGER_LOCK = threading.Lock()


def global_perf_ledger() -> PerfLedger:
    """The process-global ledger (engine feeds it; the status server and
    CLI read it — same double-checked singleton as the KV-reuse plane)."""
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = PerfLedger()
    return _LEDGER


def render_perf_metrics(openmetrics: bool = False) -> str:
    """ALL_PERF (+ the perf flight ring's RUNTIME_FLIGHT_* series)
    exposition for every SystemStatusServer."""
    led = global_perf_ledger()
    text = led.render(openmetrics=openmetrics)
    return text + led.flight.registry.render(openmetrics=openmetrics)


def perf_index(ledger: Optional[PerfLedger] = None) -> Dict[str, Any]:
    """The GET /debug/perf response body — ONE shape shared by the
    system server and the CLI."""
    led = ledger if ledger is not None else global_perf_ledger()
    return led.snapshot()
