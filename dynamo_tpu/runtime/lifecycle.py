"""Per-request lifecycle timelines with a slow-request capture ring.

Reference parity: the reference hangs OTel spans off every hop so one
request's path (frontend → router → prefill → transfer → decode) is
reconstructable; this module is the always-on, bounded-memory version of
that: every layer stamps named events onto a timeline keyed by request id
(received → tokenized → routed(worker, overlap) → prefill_start →
first_token → kv_transfer → done), bound to the utils/tracing.py trace id
so a metrics exemplar or an exported span resolves to the full timeline.

Two rings:
  - a recent ring (LRU by request id) holding the last N timelines;
  - a slow ring retaining ONLY timelines whose total duration exceeded the
    SLA threshold (``DYN_TPU_SLOW_REQUEST_S``) — a tail-latency incident
    stays inspectable long after the recent ring has churned past it.

Exposed via the system status server:
  GET /debug/requests       recent + slow timeline summaries
  GET /debug/requests/{id}  one ordered event timeline
  GET /debug/traces         the process tracer's finished-span ring
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu import config
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Declared in the canonical registry (config.py).
SLOW_REQUEST_S = config.SLOW_REQUEST_S
LIFECYCLE_RECENT = config.LIFECYCLE_RECENT
LIFECYCLE_SLOW = config.LIFECYCLE_SLOW


@dataclass
class LifecycleEvent:
    name: str
    t_wall: float  # unix seconds (export/display)
    t_mono: float  # monotonic seconds (durations; NTP-step-proof)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self, start_mono: float) -> Dict[str, Any]:
        return {
            "event": self.name,
            "t_unix_s": round(self.t_wall, 6),
            "offset_ms": round((self.t_mono - start_mono) * 1000, 3),
            **({"attrs": self.attrs} if self.attrs else {}),
        }


@dataclass
class RequestTimeline:
    request_id: str
    trace_id: Optional[str] = None
    events: List[LifecycleEvent] = field(default_factory=list)
    done: bool = False

    @property
    def start_mono(self) -> float:
        return self.events[0].t_mono if self.events else 0.0

    @property
    def duration_s(self) -> float:
        if not self.events:
            return 0.0
        return self.events[-1].t_mono - self.events[0].t_mono

    def to_dict(self) -> Dict[str, Any]:
        start = self.start_mono
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "done": self.done,
            "duration_ms": round(self.duration_s * 1000, 3),
            "events": [e.to_dict(start) for e in self.events],
        }

    def summary(self) -> Dict[str, Any]:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "done": self.done,
            "duration_ms": round(self.duration_s * 1000, 3),
            "events": [e.name for e in self.events],
        }


def trace_id_of(context: Any) -> Optional[str]:
    """Pull the trace id from a runtime Context's traceparent baggage."""
    if context is None:
        return None
    baggage = getattr(context, "baggage", None)
    if not isinstance(baggage, dict):
        return None
    header = baggage.get("traceparent")
    if not header:
        return None
    from dynamo_tpu.utils.tracing import parse_traceparent

    tc = parse_traceparent(header)
    return tc.trace_id if tc else None


class RequestLifecycle:
    """Bounded recorder. Thread-safe: stamps arrive from the event loop,
    the engine's device threads, and disagg worker handlers."""

    def __init__(
        self,
        *,
        max_recent: Optional[int] = None,
        max_slow: Optional[int] = None,
        slow_threshold_s: Optional[float] = None,
    ) -> None:
        self.max_recent = max_recent if max_recent is not None else LIFECYCLE_RECENT.get()
        self.max_slow = max_slow if max_slow is not None else LIFECYCLE_SLOW.get()
        self.slow_threshold_s = (
            slow_threshold_s if slow_threshold_s is not None else SLOW_REQUEST_S.get()
        )
        self._recent: "OrderedDict[str, RequestTimeline]" = OrderedDict()
        self._slow: "OrderedDict[str, RequestTimeline]" = OrderedDict()
        self._lock = threading.Lock()

    def record(
        self,
        request_id: Optional[str],
        event: str,
        *,
        context: Any = None,
        trace_id: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Stamp one event. Unknown request ids start a new timeline (layers
        stamp independently — whichever runs first creates it). Never raises:
        observability must not take down serving."""
        if not request_id:
            return
        try:
            tid = trace_id or trace_id_of(context)
            ev = LifecycleEvent(
                name=event,
                t_wall=time.time(),
                t_mono=time.monotonic(),
                attrs={k: v for k, v in attrs.items() if v is not None},
            )
            with self._lock:
                tl = self._recent.get(request_id)
                if tl is None:
                    tl = self._slow.get(request_id)
                if tl is None:
                    tl = RequestTimeline(request_id=request_id)
                    self._recent[request_id] = tl
                    while len(self._recent) > self.max_recent:
                        # Evict finished timelines first: an in-flight
                        # long-tail request must still be present when its
                        # "done" arrives, or it can never reach the slow
                        # ring. Only when every entry is in flight does
                        # bounded memory win over capture.
                        victim = next(
                            (r for r, t in self._recent.items() if t.done),
                            None,
                        )
                        if victim is None:
                            self._recent.popitem(last=False)
                        else:
                            del self._recent[victim]
                else:
                    if request_id in self._recent:
                        self._recent.move_to_end(request_id)
                if tid and not tl.trace_id:
                    tl.trace_id = tid
                tl.events.append(ev)
                if event == "done":
                    tl.done = True
                    if tl.duration_s >= self.slow_threshold_s:
                        self._slow[request_id] = tl
                        self._slow.move_to_end(request_id)
                        while len(self._slow) > self.max_slow:
                            self._slow.popitem(last=False)
        except Exception:
            # Timeline capture must never break serving — but a capture
            # bug must not be invisible either.
            logger.debug("request-timeline capture failed", exc_info=True)

    def get(self, request_id: str) -> Optional[RequestTimeline]:
        with self._lock:
            return self._recent.get(request_id) or self._slow.get(request_id)

    def timelines(self) -> List[RequestTimeline]:
        """Recent first, then slow-only (evicted from recent but retained)."""
        with self._lock:
            out = list(self._recent.values())
            out.extend(
                tl for rid, tl in self._slow.items() if rid not in self._recent
            )
        return out

    def slow_timelines(self) -> List[RequestTimeline]:
        with self._lock:
            return list(self._slow.values())

    def clear(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slow.clear()


_GLOBAL: Optional[RequestLifecycle] = None


def global_lifecycle() -> RequestLifecycle:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = RequestLifecycle()
    return _GLOBAL


def record(request_id: Optional[str], event: str, **kwargs: Any) -> None:
    """Convenience: stamp on the process-global recorder."""
    global_lifecycle().record(request_id, event, **kwargs)
