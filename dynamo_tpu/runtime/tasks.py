"""Task tracking and graceful shutdown.

Reference parity: the graceful-shutdown TaskTracker
(lib/runtime/src/utils/tasks/tracker.rs) and critical-task supervision
(utils/tasks/critical.rs). Endpoints register in-flight request tasks here;
shutdown flips to "draining", stops accepting new work, waits for in-flight
streams up to a grace period, then cancels stragglers.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Callable, Coroutine, Optional, Set

logger = logging.getLogger(__name__)


class Backoff:
    """Jittered exponential backoff for reconnect/re-register loops.

    A control-plane blip (discd restart, broker hiccup) disconnects every
    worker at once; bare fixed-interval retries then reconnect as a
    synchronized herd and flatten the recovering service again. This
    schedule spreads them: ``base × 2^n`` capped at ``cap``, multiplied by
    a uniform draw in ``[1 − jitter, 1 + jitter]``. ``reset()`` on the
    first success so steady-state failures start cheap again.

    Deterministic under a seeded ``rng`` (the fake-clock tests replay the
    exact delay sequence); the default draws process randomness, which is
    precisely the de-synchronization production wants."""

    def __init__(
        self,
        base_s: float = 0.1,
        cap_s: float = 15.0,
        jitter: float = 0.5,
        rng: Optional[random.Random] = None,
    ) -> None:
        if not (0.0 <= jitter < 1.0):
            raise ValueError("jitter must be in [0, 1)")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.jitter = float(jitter)
        self._rng = rng or random.Random()
        self.attempt = 0

    def next_delay(self) -> float:
        """Delay before the (attempt+1)-th retry; advances the attempt."""
        raw = min(self.base_s * (2 ** self.attempt), self.cap_s)
        self.attempt += 1
        if self.jitter:
            raw *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return raw

    def reset(self) -> None:
        self.attempt = 0

    async def sleep(self) -> float:
        delay = self.next_delay()
        await asyncio.sleep(delay)
        return delay


async def reap_task(
    task: Optional["asyncio.Future"],
    what: str = "task",
    log: Optional[logging.Logger] = None,
) -> Optional[BaseException]:
    """Await a (usually just-cancelled) background task at shutdown.

    Cancellation is the expected outcome. A real exception is returned
    and recorded at DEBUG — the task's own failure path already reported
    it when it happened; this is only the reaper's receipt (DYN003: a
    broad swallow must leave a trace)."""
    if task is None:
        return None
    try:
        # shield: a cancellation of the REAPER (the shutdown path itself
        # sits under wait_for somewhere) must not be mistaken for — or
        # converted into — the task's own cancellation. A bare `await
        # task` would forward the reaper's cancel into the task and then
        # swallow it, making the shutdown path uncancellable.
        await asyncio.shield(task)
    except asyncio.CancelledError:
        if task.cancelled():
            return None
        raise  # reaper cancelled; keep unwinding cooperatively
    except Exception as exc:
        (log or logger).debug("%s ended with %r at shutdown", what, exc)
        return exc
    return None


class TaskTracker:
    def __init__(self, name: str = "tracker") -> None:
        self.name = name
        self._tasks: Set[asyncio.Task] = set()
        self._guards = 0
        self._draining = False
        self._drained = asyncio.Event()
        self._drained.set()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def in_flight(self) -> int:
        return len(self._tasks) + self._guards

    def guard(self) -> "_Guard":
        """Context manager marking a unit of in-flight work (e.g. a response
        stream) that drain() must wait for."""
        if self._draining:
            raise RuntimeError(f"{self.name}: draining, refusing new work")
        return _Guard(self)

    def spawn(
        self,
        coro: Coroutine[Any, Any, Any],
        *,
        name: Optional[str] = None,
        critical: bool = False,
        on_failure: Optional[Callable[[BaseException], None]] = None,
    ) -> asyncio.Task:
        """Track a task. Critical tasks log at error level when they die
        unexpectedly and invoke ``on_failure`` (e.g. to trigger shutdown)."""
        if self._draining:
            coro.close()
            raise RuntimeError(f"{self.name}: draining, refusing new task")
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.add(task)
        self._drained.clear()

        def _done(t: asyncio.Task) -> None:
            self._tasks.discard(t)
            self._maybe_drained()
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                level = logging.ERROR if critical else logging.WARNING
                logger.log(level, "%s: task %s failed: %r", self.name, t.get_name(), exc)
                if on_failure is not None:
                    on_failure(exc)

        task.add_done_callback(_done)
        return task

    async def drain(self, grace_period: float = 30.0) -> bool:
        """Stop accepting work; wait for in-flight tasks, cancel stragglers.

        Returns True if everything finished within the grace period."""
        self._draining = True
        if not self._tasks and not self._guards:
            return True
        try:
            await asyncio.wait_for(self._drained.wait(), timeout=grace_period)
            return True
        except asyncio.TimeoutError:
            logger.warning(
                "%s: %d tasks still running after %.1fs grace, cancelling",
                self.name,
                len(self._tasks),
                grace_period,
            )
            for t in list(self._tasks):
                t.cancel()
            await asyncio.gather(*self._tasks, return_exceptions=True)
            return False

    def cancel_all(self) -> None:
        for t in list(self._tasks):
            t.cancel()

    def _maybe_drained(self) -> None:
        if not self._tasks and not self._guards:
            self._drained.set()


class _Guard:
    def __init__(self, tracker: TaskTracker) -> None:
        self._tracker = tracker
        self._active = False

    def __enter__(self) -> "_Guard":
        self._tracker._guards += 1
        self._tracker._drained.clear()
        self._active = True
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._active:
            self._active = False
            self._tracker._guards -= 1
            self._tracker._maybe_drained()
