"""Endpoint picker (EPP): KV-aware routing at the gateway layer.

Reference parity: deploy/inference-gateway/epp — the `dyn-kv` plugin runs
the router inside the Gateway API Inference Extension picker ("moves
intelligent routing upstream"), tokenizing the prompt inline for a
token-aware KV algorithm, with router bookkeeping ops and header routing
hints (README.md "Header Routing Hints" / "Router bookkeeping
operations"). TPU-native form: a small aiohttp service over the same
KvRouter the frontends use.

Routes:
  POST /v1/pick      {model, prompt|messages|token_ids, request_id?,
                      lora_name?} →
                     {worker_id, dp_rank, overlap_blocks, request_id,
                      headers: {"x-dynamo-worker": "..."}}
  POST /v1/complete  {request_id} → releases the in-flight charge
  GET  /healthz

Charges expire after ``charge_ttl_s`` if /complete never arrives (a
crashed gateway hop must not poison the load model forever).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from aiohttp import web

from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.tokens.blocks import adapter_salt, compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

WORKER_HEADER = "x-dynamo-worker"


class EndpointPicker:
    def __init__(
        self,
        router: Any,  # router.KvRouter
        tokenize: Callable[[str], Sequence[int]],
        *,
        host: str = "0.0.0.0",
        port: int = 0,
        charge_ttl_s: float = 600.0,
    ) -> None:
        self.router = router
        self.tokenize = tokenize
        self.host = host
        self.port = port
        self.charge_ttl_s = charge_ttl_s
        # request_id → (worker, charged_blocks, report_gen, deadline)
        self._inflight: Dict[str, Tuple[Tuple[int, int], int, Any, float]] = {}
        self._runner: Optional[web.AppRunner] = None
        self._sweeper: Optional[asyncio.Task] = None
        self.picks = 0
        self.completes = 0
        self.expired = 0

    # -- request body → token ids -----------------------------------------

    def _token_ids(self, body: Dict[str, Any]) -> Optional[Sequence[int]]:
        if isinstance(body.get("token_ids"), list):
            return body["token_ids"]
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return self.tokenize(prompt)
        messages = body.get("messages")
        if isinstance(messages, list):
            # Token-aware routing needs the text, not the chat structure —
            # concatenating content fields approximates the engine's
            # template closely enough for prefix-overlap scoring.
            parts = []
            for m in messages:
                c = m.get("content")
                if isinstance(c, str):
                    parts.append(c)
            return self.tokenize("\n".join(parts))
        return None

    # -- handlers ----------------------------------------------------------

    async def _pick(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        token_ids = self._token_ids(body)
        if token_ids is None:
            return web.json_response(
                {"error": "body needs token_ids, prompt, or messages"},
                status=400,
            )
        worker, overlap = self.router.find_best_match(
            token_ids, lora_name=body.get("lora_name")
        )
        if worker is None:
            return web.json_response(
                {"error": "no workers available"}, status=503
            )
        request_id = str(body.get("request_id") or uuid.uuid4().hex)
        # Release EXACTLY what select_worker charged — the net new blocks
        # (request minus predicted overlap), guarded by the worker's load
        # report generation so a report landing between pick and complete
        # doesn't double-subtract (scheduler.py complete_request contract).
        n_blocks = max(
            len(compute_block_hashes(
                token_ids, self.router.block_size,
                salt=adapter_salt(body.get("lora_name")),
            )),
            1,
        )
        charged = max(n_blocks - overlap, 0)
        gen = self.router.scheduler.report_generation(worker)
        self._inflight[request_id] = (
            worker, charged, gen, time.monotonic() + self.charge_ttl_s
        )
        self.picks += 1
        return web.json_response({
            "worker_id": worker[0],
            "dp_rank": worker[1],
            "overlap_blocks": overlap,
            "request_id": request_id,
            # The gateway copies these onto the upstream request; frontends
            # (or the request-plane client) honor the pin.
            "headers": {WORKER_HEADER: f"{worker[0]}:{worker[1]}"},
        })

    async def _complete(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            request_id = body["request_id"]
        except Exception:
            return web.json_response(
                {"error": "body must be {'request_id': ...}"}, status=400
            )
        entry = self._inflight.pop(request_id, None)
        if entry is None:
            return web.json_response({"released": False}, status=404)
        worker, charged, gen, _ = entry
        self.router.release(worker, charged, gen)
        self.completes += 1
        return web.json_response({"released": True})

    async def _healthz(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "ok",
            "picks": self.picks,
            "completes": self.completes,
            "inflight": len(self._inflight),
            "expired": self.expired,
        })

    async def _sweep(self) -> None:
        while True:
            await asyncio.sleep(min(self.charge_ttl_s / 4, 30.0))
            now = time.monotonic()
            for rid in [
                r for r, (_, _, _, d) in self._inflight.items() if d < now
            ]:
                worker, charged, gen, _ = self._inflight.pop(rid)
                self.router.release(worker, charged, gen)
                self.expired += 1
                logger.warning("EPP charge %s expired (no /complete)", rid)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/pick", self._pick)
        app.router.add_post("/v1/complete", self._complete)
        app.router.add_get("/healthz", self._healthz)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self._sweeper = asyncio.get_running_loop().create_task(
            self._sweep(), name="epp-charge-sweeper"
        )
        logger.info("EPP listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            await reap_task(self._sweeper, "epp session sweeper", logger)
        if self._runner is not None:
            await self._runner.cleanup()
