"""Inference-gateway integration: the endpoint-picker (EPP) service.

Reference parity: deploy/inference-gateway — the reference ships a custom
EPP image whose `dyn-kv` plugin embeds its router in the Gateway API
Inference Extension endpoint picker, so KV-aware, token-aware routing
happens at the gateway layer before the request reaches any frontend.

Here the same role is an aiohttp sidecar (gateway/epp.py): the gateway
(or any L7 proxy with an ext-proc-style hook) POSTs the request body to
``/v1/pick``; the picker tokenizes inline, scores workers through the
KvRouter's radix index + load model, charges the in-flight prediction,
and returns the chosen worker as a header hint. ``/v1/complete`` is the
router-bookkeeping op releasing the charge when the stream ends.
"""

from dynamo_tpu.gateway.epp import EndpointPicker

__all__ = ["EndpointPicker"]
