"""EPP service entrypoint.

Usage:
  python -m dynamo_tpu.gateway --namespace prod --component backend \
      --port 9002 [--model-dir /path/to/hf/model]

Wires a KvRouter (event-plane fed) behind the pick/complete HTTP surface.
Without --model-dir the prompt tokenizer is the deterministic test
tokenizer (llm.tiny_tokenizer) — fine for mocker clusters; real clusters
pass the served model's directory so gateway-side hashing matches the
engine's.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu import config
from dynamo_tpu.gateway.epp import EndpointPicker
from dynamo_tpu.router import KvRouter
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu endpoint picker (EPP)")
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--component", default="backend")
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--port", type=int, default=9002)
    parser.add_argument("--model-dir", default=None,
                        help="HF model dir for the inline tokenizer")
    args = parser.parse_args()
    configure_logging()

    runtime = DistributedRuntime.from_settings()
    router = KvRouter(
        runtime, args.namespace, args.component, block_size=args.block_size
    )
    await router.start()

    if args.model_dir:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.model_dir)

        def tokenize(text: str):
            return tok.encode(text)
    else:
        from dynamo_tpu.llm import tiny_tokenizer

        tok = tiny_tokenizer()

        def tokenize(text: str):
            return tok.encode(text)

    epp = EndpointPicker(router, tokenize, port=args.port)
    await epp.start()
    print(f"EPP serving on :{epp.port}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await epp.stop()
        await router.stop()
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
