"""Migration operator: re-dispatch a live request when its worker dies.

Reference parity: lib/llm/src/migration.rs:24 (Migration) + docs/
fault_tolerance/request_migration.md — when the response stream dies mid-
generation (worker crash, connection loss, no instances), rebuild the
PreprocessedRequest with the tokens accumulated so far appended to the
prompt, and send it to another worker, up to ``migration_limit`` times. The
new worker's prefix cache makes the re-prefill cheap; the client stream never
observes the failure.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Union

from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.component import NoInstancesError
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

try:
    from dynamo_tpu.runtime.network.tcp import StreamDisconnectedError
except ImportError:  # pragma: no cover

    class StreamDisconnectedError(ConnectionError):  # type: ignore[no-redef]
        pass


MIGRATABLE = (StreamDisconnectedError, NoInstancesError, ConnectionError)


class Migration:
    def __init__(self, migration_limit: int = 3) -> None:
        self.migration_limit = migration_limit

    async def generate(
        self, request: Any, context: Context, next: AsyncEngine
    ) -> AsyncIterator[Union[BackendOutput, dict]]:
        if isinstance(request, PreprocessedRequest):
            req = request
        else:
            req = PreprocessedRequest.from_dict(dict(request))
        generated: List[int] = []
        migrations = 0

        while True:
            finished = False
            try:
                async for item in next.generate(_as_wire(request, req), context):
                    tokens = _tokens_of(item)
                    if tokens:
                        generated.extend(tokens)
                    yield item
                    if _finish_reason_of(item) is not None:
                        finished = True
                return
            except MIGRATABLE as exc:
                if finished or context.stopped:
                    return
                migrations += 1
                if migrations > self.migration_limit:
                    logger.error(
                        "request %s exceeded migration limit (%d): %r",
                        req.request_id, self.migration_limit, exc,
                    )
                    yield BackendOutput(
                        error=f"stream failed after {self.migration_limit} migrations: {exc}",
                        finish_reason=FinishReason.ERROR,
                    )
                    return
                logger.warning(
                    "migrating request %s (attempt %d/%d) after %r with %d tokens carried",
                    req.request_id, migrations, self.migration_limit, exc, len(generated),
                )
                req = _carry_tokens(req, generated)
                generated = []  # now embedded in the prompt; don't carry twice
                request = req  # from now on send the rebuilt request

    # Streams that end without any finish reason (worker vanished without an
    # exception) are NOT retried here: the transport layer is responsible for
    # surfacing disconnects as exceptions (tcp.py StreamDisconnectedError).


def _carry_tokens(req: PreprocessedRequest, generated: List[int]) -> PreprocessedRequest:
    """New request whose prompt embeds everything generated so far
    (ref: migration.rs retained-token re-dispatch)."""
    d = req.to_dict()
    d["token_ids"] = list(req.token_ids) + list(generated)
    new = PreprocessedRequest.from_dict(d)
    if new.stop.max_tokens is not None:
        new.stop.max_tokens = max(new.stop.max_tokens - len(generated), 1)
    if new.stop.min_tokens is not None:
        new.stop.min_tokens = max(new.stop.min_tokens - len(generated), 0)
    return new


def _as_wire(original: Any, req: PreprocessedRequest) -> Any:
    """Preserve the caller's representation (dict over the wire, object locally)."""
    return req.to_dict() if isinstance(original, dict) else req


def _tokens_of(item: Any) -> List[int]:
    if isinstance(item, dict):
        return item.get("token_ids") or []
    return getattr(item, "token_ids", None) or []


def _finish_reason_of(item: Any):
    if isinstance(item, dict):
        return item.get("finish_reason")
    return getattr(item, "finish_reason", None)
